"""Flat-parameter-view utilities.

The reference keeps ALL network parameters in one flat buffer with per-layer views
(reference MultiLayerNetwork.flattenedParams:100, init:386) — updaters, parameter
averaging, and serialization all operate on that 1-D view. In JAX the natural
representation is a pytree; these helpers provide the same flat view on demand
(for ParallelWrapper-style averaging, checkpoint compatibility, and the `params()` /
`set_params()` API), with a deterministic ordering.
"""
from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

Array = jax.Array


def flatten_params(tree: Any, dtype=None) -> Array:
    """Concatenate all leaves into one 1-D float vector (deterministic pytree order).
    dtype=None keeps the leaves' promoted dtype (float64 under enable_x64 for
    gradient checks); pass jnp.float32 for the standard flat view."""
    leaves = jax.tree_util.tree_leaves(tree)
    if not leaves:
        return jnp.zeros((0,), dtype or jnp.float32)
    if dtype is None:
        dtype = jnp.result_type(*leaves)
    if _any_partially_sharded(leaves):
        # XLA's CPU SPMD partitioner miscompiles the eager mixed-layout
        # concatenate below when the leaves carry different NamedShardings
        # on a multi-axis mesh (jax 0.4.37: with a sharded 1-D leaf in the
        # mix, every segment comes back scaled by a product of mesh axis
        # sizes). Resolving each leaf to host values first sidesteps the
        # partitioner entirely; this branch only fires on concrete arrays,
        # so traced callers are unaffected.
        return jnp.asarray(np.concatenate(
            [np.asarray(l).ravel().astype(dtype) for l in leaves]))
    return jnp.concatenate([jnp.ravel(l).astype(dtype) for l in leaves])


def _any_partially_sharded(leaves) -> bool:
    for l in leaves:
        if isinstance(l, jax.core.Tracer):
            return False
        sh = getattr(l, "sharding", None)
        if (sh is not None and getattr(sh, "num_devices", 1) > 1
                and not sh.is_fully_replicated):
            return True
    return False


def unflatten_params(tree_like: Any, flat: Array) -> Any:
    """Inverse of flatten_params given a structure/shape template."""
    leaves, treedef = jax.tree_util.tree_flatten(tree_like)
    out = []
    pos = 0
    for l in leaves:
        n = int(np.prod(l.shape)) if l.shape else 1
        out.append(jnp.reshape(flat[pos:pos + n], l.shape).astype(l.dtype))
        pos += n
    if pos != flat.shape[0]:
        raise ValueError(f"Flat vector length {flat.shape[0]} != param count {pos}")
    return jax.tree_util.tree_unflatten(treedef, out)


def num_params(tree: Any) -> int:
    return sum(int(np.prod(l.shape)) for l in jax.tree_util.tree_leaves(tree))


def tree_average(trees: list) -> Any:
    """Elementwise average of identically-structured pytrees (parameter averaging,
    reference Nd4j.averageAndPropagate at ParallelWrapper.java:179)."""
    return jax.tree_util.tree_map(lambda *xs: sum(xs) / len(xs), *trees)
