"""K-step batch grouping for fused-dispatch training loops.

One shared state machine for the three fused fit loops
(MultiLayerNetwork.fit_iterator, ComputationGraph.fit_iterator,
ParallelWrapper._fit_sync): accumulate up to ``k`` same-shape host-staged
minibatches, emit them as a group for one stacked (K, B, ...) device
dispatch, and route batches the caller declines (masked, ragged tail) to the
per-batch fallback. Keeping this in one place prevents the three loops from
drifting on flush ordering / fallback semantics.
"""
from __future__ import annotations

from typing import Callable, Iterable, Iterator, Tuple

import jax


def _shape_key(batch) -> list:
    return [a.shape for a in jax.tree_util.tree_leaves(batch)]


def k_step_groups(iterator: Iterable, k: int,
                  to_batch: Callable) -> Iterator[Tuple[str, object]]:
    """Yield ``("group", [batch, ...])`` (1 <= len <= k, identical shapes) or
    ``("single", ds)`` for datasets ``to_batch`` declines.

    ``to_batch(ds)`` returns a pytree of host (numpy) arrays to include the
    dataset in fused dispatch, or None to route it to the caller's per-batch
    fallback (masked batches, unsupported layouts). A shape change (e.g. the
    ragged final batch of an epoch) flushes the pending group first so groups
    always stack cleanly.
    """
    pending: list = []
    for ds in iterator:
        batch = to_batch(ds)
        if batch is None:
            if pending:
                yield "group", pending
                pending = []
            yield "single", ds
            continue
        if pending and _shape_key(batch) != _shape_key(pending[-1]):
            yield "group", pending
            pending = []
        pending.append(batch)
        if len(pending) == k:
            yield "group", pending
            pending = []
    if pending:
        yield "group", pending
