"""GravesLSTM char-RNN configuration — BASELINE.json config-3 benchmark.

Matches the reference's canonical character-modelling example (2x GravesLSTM 200 +
RnnOutputLayer, TBPTT 50). The recurrence runs through the three-variant engine
in ``ops/lstm.py`` (fused scan by default; ``DL4J_LSTM_IMPL``/auto thresholds
can engage the Pallas persistent cell at MXU-filling widths — the tanh/sigmoid
GravesLSTM cell here satisfies the kernel's hard constraints, so this model is
the engine's bench vehicle via ``bench.py --model char_rnn --lstm-impl``).
"""
from __future__ import annotations

from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.layers import GravesLSTM, RnnOutputLayer
from deeplearning4j_tpu.nn.conf.multilayer import MultiLayerConfiguration


def char_rnn_lstm(vocab_size: int, hidden: int = 200, layers: int = 2,
                  tbptt_length: int = 50, seed: int = 12345,
                  learning_rate: float = 0.1) -> MultiLayerConfiguration:
    lb = (NeuralNetConfiguration.builder()
          .seed(seed)
          .learning_rate(learning_rate)
          .updater("rmsprop").rms_decay(0.95)
          .weight_init("xavier")
          .list())
    for i in range(layers):
        lb.layer(GravesLSTM(n_in=vocab_size if i == 0 else hidden, n_out=hidden,
                            activation="tanh"))
    lb.layer(RnnOutputLayer(n_in=hidden, n_out=vocab_size, loss="mcxent",
                            activation="softmax"))
    lb.backprop_type("TruncatedBPTT")
    lb.t_bptt_forward_length(tbptt_length)
    lb.t_bptt_backward_length(tbptt_length)
    return lb.build()
