from deeplearning4j_tpu.models.lenet import lenet_mnist
from deeplearning4j_tpu.models.resnet import resnet50
from deeplearning4j_tpu.models.vgg import vgg16
from deeplearning4j_tpu.models.char_rnn import char_rnn_lstm
