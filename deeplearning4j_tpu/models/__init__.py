from deeplearning4j_tpu.models.alexnet import alexnet
from deeplearning4j_tpu.models.char_rnn import char_rnn_lstm
from deeplearning4j_tpu.models.googlenet import googlenet
from deeplearning4j_tpu.models.lenet import lenet_mnist
from deeplearning4j_tpu.models.resnet import resnet18, resnet50
from deeplearning4j_tpu.models.vgg import vgg16
from deeplearning4j_tpu.models.transformer import moe_transformer_lm, transformer_lm
