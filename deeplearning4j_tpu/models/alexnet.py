"""AlexNet (Krizhevsky 2012) as a MultiLayerNetwork configuration.

The reference era's standard ImageNet CNN besides VGG/GoogLeNet (its model
zoo ships AlexNet built from the same conf primitives this framework
provides: Convolution/LRN/MaxPooling/Dense/Dropout — reference
nn/conf/layers/* and nn/layers/normalization/LocalResponseNormalization.java
for the LRN stages). NHWC layout for XLA:TPU; the two-GPU grouping of the
original is folded into plain convolutions, as every modern reimplementation
does.
"""
from __future__ import annotations

from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import (
    ConvolutionLayer, DenseLayer, DropoutLayer, LocalResponseNormalization,
    OutputLayer, SubsamplingLayer,
)
from deeplearning4j_tpu.nn.conf.multilayer import MultiLayerConfiguration


def alexnet(n_classes: int = 1000, image_size: int = 224, channels: int = 3,
            seed: int = 12345, learning_rate: float = 0.01,
            dropout: float = 0.5) -> MultiLayerConfiguration:
    lb = (NeuralNetConfiguration.builder()
          .seed(seed)
          .learning_rate(learning_rate)
          .updater("nesterovs").momentum(0.9)
          .weight_init("relu")
          .list()
          .layer(ConvolutionLayer(n_out=96, kernel_size=(11, 11),
                                  stride=(4, 4), convolution_mode="same",
                                  activation="relu"))
          .layer(LocalResponseNormalization(n=5, alpha=1e-4, beta=0.75, k=2))
          .layer(SubsamplingLayer(pooling_type="max", kernel_size=(3, 3),
                                  stride=(2, 2)))
          .layer(ConvolutionLayer(n_out=256, kernel_size=(5, 5),
                                  stride=(1, 1), convolution_mode="same",
                                  activation="relu"))
          .layer(LocalResponseNormalization(n=5, alpha=1e-4, beta=0.75, k=2))
          .layer(SubsamplingLayer(pooling_type="max", kernel_size=(3, 3),
                                  stride=(2, 2)))
          .layer(ConvolutionLayer(n_out=384, kernel_size=(3, 3),
                                  stride=(1, 1), convolution_mode="same",
                                  activation="relu"))
          .layer(ConvolutionLayer(n_out=384, kernel_size=(3, 3),
                                  stride=(1, 1), convolution_mode="same",
                                  activation="relu"))
          .layer(ConvolutionLayer(n_out=256, kernel_size=(3, 3),
                                  stride=(1, 1), convolution_mode="same",
                                  activation="relu"))
          .layer(SubsamplingLayer(pooling_type="max", kernel_size=(3, 3),
                                  stride=(2, 2)))
          .layer(DenseLayer(n_out=4096, activation="relu"))
          .layer(DropoutLayer(dropout=dropout))
          .layer(DenseLayer(n_out=4096, activation="relu"))
          .layer(DropoutLayer(dropout=dropout))
          .layer(OutputLayer(n_out=n_classes, loss="mcxent",
                             activation="softmax", weight_init="xavier")))
    lb.set_input_type(InputType.convolutional(image_size, image_size,
                                              channels))
    return lb.build()
