"""Decoder-only transformer LM (TPU-native flagship for long-context work).

The reference's sequence story is the GravesLSTM char-RNN (models/char_rnn.py
here); this is its TPU-native successor: causal TransformerBlocks over the
flash-attention kernel, homogeneous blocks so the stack pipeline-parallelizes
(parallel/pipeline.py) and the sequence axis shards for ring/Ulysses
attention (parallel/ring_attention.py).
"""
from __future__ import annotations

from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import (
    EmbeddingLayer, RnnOutputLayer, TransformerBlock,
)
from deeplearning4j_tpu.nn.conf.multilayer import MultiLayerConfiguration


def transformer_lm(vocab_size: int, width: int = 256, n_layers: int = 4,
                   n_heads: int = 4, ffn_multiplier: int = 4,
                   max_len: int = 512, seed: int = 12345,
                   learning_rate: float = 3e-4) -> MultiLayerConfiguration:
    """Causal LM: one-hot/[B,T] ids -> embedding -> N blocks -> vocab logits.

    Inputs are one-hot [B, T, V] (EmbeddingLayer consumes either ids or
    one-hot); loss is per-timestep mcxent like the char-RNN config.
    """
    lb = (NeuralNetConfiguration.builder()
          .seed(seed)
          .learning_rate(learning_rate)
          .updater("adam")
          .weight_init("xavier")
          .list())
    lb.layer(EmbeddingLayer(n_in=vocab_size, n_out=width))
    for _ in range(n_layers):
        lb.layer(TransformerBlock(n_in=width, n_out=width, n_heads=n_heads,
                                  ffn_multiplier=ffn_multiplier, causal=True))
    lb.layer(RnnOutputLayer(n_in=width, n_out=vocab_size, loss="mcxent",
                            activation="softmax"))
    lb.set_input_type(InputType.recurrent(vocab_size, max_len))
    return lb.build()


def moe_transformer_lm(vocab_size: int, width: int = 256, n_layers: int = 4,
                       n_heads: int = 4, n_experts: int = 8,
                       expert_hidden: int = 0, max_len: int = 512,
                       seed: int = 12345,
                       learning_rate: float = 3e-4) -> MultiLayerConfiguration:
    """Sparse-FFN causal LM: Switch-transformer blocks (pre-LN residual
    attention + pre-LN residual top-1 MoE FFN). The MoE sublayers publish
    their load-balance auxiliary loss into the training objective and
    expert-parallelize over a mesh axis (parallel/moe.ExpertParallelMoE)."""
    from deeplearning4j_tpu.nn.conf.layers.moe import MoETransformerBlock

    lb = (NeuralNetConfiguration.builder()
          .seed(seed)
          .learning_rate(learning_rate)
          .updater("adam")
          .weight_init("xavier")
          .list())
    lb.layer(EmbeddingLayer(n_in=vocab_size, n_out=width))
    for _ in range(n_layers):
        lb.layer(MoETransformerBlock(n_in=width, n_out=width,
                                     n_heads=n_heads, n_experts=n_experts,
                                     expert_hidden=expert_hidden, causal=True,
                                     activation="identity"))
    lb.layer(RnnOutputLayer(n_in=width, n_out=vocab_size, loss="mcxent",
                            activation="softmax"))
    lb.set_input_type(InputType.recurrent(vocab_size, max_len))
    return lb.build()
