"""Decoder-only transformer LM (TPU-native flagship for long-context work).

The reference's sequence story is the GravesLSTM char-RNN (models/char_rnn.py
here); this is its TPU-native successor: causal TransformerBlocks over the
flash-attention kernel, homogeneous blocks so the stack pipeline-parallelizes
(parallel/pipeline.py) and the sequence axis shards for ring/Ulysses
attention (parallel/ring_attention.py).
"""
from __future__ import annotations

from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import (
    EmbeddingLayer, RnnOutputLayer, TransformerBlock,
)
from deeplearning4j_tpu.nn.conf.multilayer import MultiLayerConfiguration


def transformer_lm(vocab_size: int, width: int = 256, n_layers: int = 4,
                   n_heads: int = 4, ffn_multiplier: int = 4,
                   max_len: int = 512, seed: int = 12345,
                   learning_rate: float = 3e-4) -> MultiLayerConfiguration:
    """Causal LM: one-hot/[B,T] ids -> embedding -> N blocks -> vocab logits.

    Inputs are one-hot [B, T, V] (EmbeddingLayer consumes either ids or
    one-hot); loss is per-timestep mcxent like the char-RNN config.
    """
    lb = (NeuralNetConfiguration.builder()
          .seed(seed)
          .learning_rate(learning_rate)
          .updater("adam")
          .weight_init("xavier")
          .list())
    lb.layer(EmbeddingLayer(n_in=vocab_size, n_out=width))
    for _ in range(n_layers):
        lb.layer(TransformerBlock(n_in=width, n_out=width, n_heads=n_heads,
                                  ffn_multiplier=ffn_multiplier, causal=True))
    lb.layer(RnnOutputLayer(n_in=width, n_out=vocab_size, loss="mcxent",
                            activation="softmax"))
    lb.set_input_type(InputType.recurrent(vocab_size, max_len))
    return lb.build()
