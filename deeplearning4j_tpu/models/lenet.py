"""LeNet-5 MNIST configuration — the reference's canonical CNN example and the
BASELINE.json config-1 benchmark (reference deeplearning4j-core LenetMnistExample
hyperparameters: 20/50 conv filters, 500 dense, nesterovs 0.9, lr 0.01)."""
from __future__ import annotations

from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import (
    ConvolutionLayer, DenseLayer, OutputLayer, SubsamplingLayer,
)
from deeplearning4j_tpu.nn.conf.multilayer import MultiLayerConfiguration


def lenet_mnist(seed: int = 12345, learning_rate: float = 0.01) -> MultiLayerConfiguration:
    return (NeuralNetConfiguration.builder()
            .seed(seed)
            .learning_rate(learning_rate)
            .updater("nesterovs").momentum(0.9)
            .weight_init("xavier")
            .list()
            .layer(ConvolutionLayer(n_out=20, kernel_size=(5, 5), stride=(1, 1),
                                    activation="identity"))
            .layer(SubsamplingLayer(pooling_type="max", kernel_size=(2, 2),
                                    stride=(2, 2)))
            .layer(ConvolutionLayer(n_out=50, kernel_size=(5, 5), stride=(1, 1),
                                    activation="identity"))
            .layer(SubsamplingLayer(pooling_type="max", kernel_size=(2, 2),
                                    stride=(2, 2)))
            .layer(DenseLayer(n_out=500, activation="relu"))
            .layer(OutputLayer(n_out=10, loss="mcxent", activation="softmax"))
            .set_input_type(InputType.convolutional_flat(28, 28, 1))
            .build())
