"""ResNet-50 as a ComputationGraph — BASELINE.json config-2 benchmark model.

The reference expresses ResNet-style models through ComputationGraph
(ElementWiseVertex residual adds, reference nn/graph/vertex/impl/ElementWiseVertex.java);
this builder produces the standard 50-layer bottleneck architecture with the
conv->BN->ReLU ordering, NHWC layout for XLA:TPU.
"""
from __future__ import annotations

from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.graphconf import ComputationGraphConfiguration
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import (
    ActivationLayer, BatchNormalization, ConvolutionLayer, GlobalPoolingLayer, OutputLayer, SubsamplingLayer,
)
from deeplearning4j_tpu.nn.conf.vertices import ElementWiseVertex


def _conv_bn(gb, name: str, n_out: int, kernel, stride, input_name: str,
             activation: str = "relu", mode: str = "same") -> str:
    gb.add_layer(f"{name}_conv",
                 ConvolutionLayer(n_out=n_out, kernel_size=kernel, stride=stride,
                                  convolution_mode=mode, activation="identity",
                                  has_bias=False),
                 input_name)
    gb.add_layer(f"{name}_bn", BatchNormalization(activation=activation),
                 f"{name}_conv")
    return f"{name}_bn"


def _bottleneck(gb, name: str, in_name: str, filters: int, stride: int,
                downsample: bool) -> str:
    """1x1 -> 3x3 -> 1x1(x4) bottleneck with identity/projection shortcut."""
    out_ch = filters * 4
    a = _conv_bn(gb, f"{name}_a", filters, (1, 1), (stride, stride), in_name)
    b = _conv_bn(gb, f"{name}_b", filters, (3, 3), (1, 1), a)
    c = _conv_bn(gb, f"{name}_c", out_ch, (1, 1), (1, 1), b, activation="identity")
    if downsample:
        shortcut = _conv_bn(gb, f"{name}_proj", out_ch, (1, 1), (stride, stride),
                            in_name, activation="identity")
    else:
        shortcut = in_name
    gb.add_vertex(f"{name}_add", ElementWiseVertex(op="add"), c, shortcut)
    gb.add_layer(f"{name}_relu", ActivationLayer(activation="relu"), f"{name}_add")
    return f"{name}_relu"


def resnet50(n_classes: int = 1000, image_size: int = 224, channels: int = 3,
             seed: int = 12345, learning_rate: float = 0.1,
             stage_blocks=(3, 4, 6, 3)) -> ComputationGraphConfiguration:
    gb = (NeuralNetConfiguration.builder()
          .seed(seed)
          .learning_rate(learning_rate)
          .updater("nesterovs").momentum(0.9)
          .weight_init("relu")
          .graph_builder()
          .add_inputs("input"))
    stem = _conv_bn(gb, "stem", 64, (7, 7), (2, 2), "input")
    gb.add_layer("stem_pool",
                 SubsamplingLayer(pooling_type="max", kernel_size=(3, 3),
                                  stride=(2, 2), convolution_mode="same"),
                 stem)
    cur = "stem_pool"
    filters = [64, 128, 256, 512]
    for stage, blocks in enumerate(stage_blocks):
        for block in range(blocks):
            stride = 2 if (stage > 0 and block == 0) else 1
            downsample = block == 0
            cur = _bottleneck(gb, f"s{stage}b{block}", cur, filters[stage],
                              stride, downsample)
    gb.add_layer("avgpool", GlobalPoolingLayer(pooling_type="avg"), cur)
    gb.add_layer("fc", OutputLayer(n_out=n_classes, loss="mcxent",
                                   activation="softmax", weight_init="xavier"),
                 "avgpool")
    gb.set_outputs("fc")
    gb.set_input_types(InputType.convolutional(image_size, image_size, channels))
    return gb.build()


def resnet18(n_classes: int = 1000, image_size: int = 224, channels: int = 3,
             seed: int = 12345, learning_rate: float = 0.1) -> ComputationGraphConfiguration:
    """Basic-block ResNet-18 (smaller benchmarking/test variant)."""
    gb = (NeuralNetConfiguration.builder()
          .seed(seed).learning_rate(learning_rate)
          .updater("nesterovs").momentum(0.9).weight_init("relu")
          .graph_builder()
          .add_inputs("input"))
    stem = _conv_bn(gb, "stem", 64, (7, 7), (2, 2), "input")
    gb.add_layer("stem_pool",
                 SubsamplingLayer(pooling_type="max", kernel_size=(3, 3),
                                  stride=(2, 2), convolution_mode="same"), stem)
    cur = "stem_pool"
    filters = [64, 128, 256, 512]
    for stage in range(4):
        for block in range(2):
            name = f"s{stage}b{block}"
            stride = 2 if (stage > 0 and block == 0) else 1
            a = _conv_bn(gb, f"{name}_a", filters[stage], (3, 3), (stride, stride), cur)
            b = _conv_bn(gb, f"{name}_b", filters[stage], (3, 3), (1, 1), a,
                         activation="identity")
            if stage > 0 and block == 0:
                shortcut = _conv_bn(gb, f"{name}_proj", filters[stage], (1, 1),
                                    (stride, stride), cur, activation="identity")
            else:
                shortcut = cur
            gb.add_vertex(f"{name}_add", ElementWiseVertex(op="add"), b, shortcut)
            gb.add_layer(f"{name}_relu", ActivationLayer(activation="relu"),
                         f"{name}_add")
            cur = f"{name}_relu"
    gb.add_layer("avgpool", GlobalPoolingLayer(pooling_type="avg"), cur)
    gb.add_layer("fc", OutputLayer(n_out=n_classes, loss="mcxent",
                                   activation="softmax", weight_init="xavier"),
                 "avgpool")
    gb.set_outputs("fc")
    gb.set_input_types(InputType.convolutional(image_size, image_size, channels))
    return gb.build()
