"""VGG-16 configuration — BASELINE.json config-5 (Keras-import fine-tune target).

Matches the Keras 1.x VGG-16 layer stack the reference's modelimport handles
(reference KerasLayer.java:39-52 supported set: Convolution2D/MaxPooling2D/Flatten/
Dense/Dropout), so an imported Keras VGG-16 lands on this exact architecture.
"""
from __future__ import annotations

from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import (
    ConvolutionLayer, DenseLayer, DropoutLayer, OutputLayer, SubsamplingLayer,
)
from deeplearning4j_tpu.nn.conf.multilayer import MultiLayerConfiguration

_VGG16_BLOCKS = [(64, 2), (128, 2), (256, 3), (512, 3), (512, 3)]


def vgg16(n_classes: int = 1000, image_size: int = 224, channels: int = 3,
          seed: int = 12345, learning_rate: float = 0.01,
          dropout: float = 0.5) -> MultiLayerConfiguration:
    lb = (NeuralNetConfiguration.builder()
          .seed(seed)
          .learning_rate(learning_rate)
          .updater("nesterovs").momentum(0.9)
          .weight_init("relu")
          .list())
    for filters, convs in _VGG16_BLOCKS:
        for _ in range(convs):
            lb.layer(ConvolutionLayer(n_out=filters, kernel_size=(3, 3),
                                      stride=(1, 1), convolution_mode="same",
                                      activation="relu"))
        lb.layer(SubsamplingLayer(pooling_type="max", kernel_size=(2, 2),
                                  stride=(2, 2)))
    lb.layer(DenseLayer(n_out=4096, activation="relu"))
    lb.layer(DropoutLayer(dropout=dropout))
    lb.layer(DenseLayer(n_out=4096, activation="relu"))
    lb.layer(DropoutLayer(dropout=dropout))
    lb.layer(OutputLayer(n_out=n_classes, loss="mcxent", activation="softmax",
                         weight_init="xavier"))
    lb.set_input_type(InputType.convolutional(image_size, image_size, channels))
    return lb.build()
