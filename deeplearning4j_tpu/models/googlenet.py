"""GoogLeNet / Inception-v1 (Szegedy 2014) as a ComputationGraph.

The canonical multi-branch ComputationGraph model of the reference era:
each inception module is four parallel towers (1x1 / 1x1->3x3 / 1x1->5x5 /
maxpool->1x1) concatenated on the channel axis — exactly what MergeVertex
exists for (reference nn/graph/vertex/impl/MergeVertex.java). Auxiliary
classifier heads are omitted (inference-era practice); NHWC layout for
XLA:TPU. The MXU sees each tower as an independent conv, and XLA fuses the
channel concat into the consumers.
"""
from __future__ import annotations

from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.graphconf import ComputationGraphConfiguration
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import (
    ConvolutionLayer, DropoutLayer, GlobalPoolingLayer,
    LocalResponseNormalization, OutputLayer, SubsamplingLayer,
)
from deeplearning4j_tpu.nn.conf.vertices import MergeVertex

# (1x1, 3x3reduce, 3x3, 5x5reduce, 5x5, poolproj) per module, GoogLeNet table 1
_INCEPTION = {
    "3a": (64, 96, 128, 16, 32, 32),
    "3b": (128, 128, 192, 32, 96, 64),
    "4a": (192, 96, 208, 16, 48, 64),
    "4b": (160, 112, 224, 24, 64, 64),
    "4c": (128, 128, 256, 24, 64, 64),
    "4d": (112, 144, 288, 32, 64, 64),
    "4e": (256, 160, 320, 32, 128, 128),
    "5a": (256, 160, 320, 32, 128, 128),
    "5b": (384, 192, 384, 48, 128, 128),
}


def _conv(gb, name, n_out, kernel, stride, input_name):
    gb.add_layer(name, ConvolutionLayer(n_out=n_out, kernel_size=kernel,
                                        stride=stride,
                                        convolution_mode="same",
                                        activation="relu"), input_name)
    return name


def _inception(gb, name: str, in_name: str, cfg) -> str:
    c1, r3, c3, r5, c5, pp = cfg
    b1 = _conv(gb, f"{name}_1x1", c1, (1, 1), (1, 1), in_name)
    t3 = _conv(gb, f"{name}_3x3r", r3, (1, 1), (1, 1), in_name)
    b3 = _conv(gb, f"{name}_3x3", c3, (3, 3), (1, 1), t3)
    t5 = _conv(gb, f"{name}_5x5r", r5, (1, 1), (1, 1), in_name)
    b5 = _conv(gb, f"{name}_5x5", c5, (5, 5), (1, 1), t5)
    gb.add_layer(f"{name}_pool",
                 SubsamplingLayer(pooling_type="max", kernel_size=(3, 3),
                                  stride=(1, 1), convolution_mode="same"),
                 in_name)
    bp = _conv(gb, f"{name}_poolproj", pp, (1, 1), (1, 1), f"{name}_pool")
    gb.add_vertex(f"{name}_concat", MergeVertex(), b1, b3, b5, bp)
    return f"{name}_concat"


def googlenet(n_classes: int = 1000, image_size: int = 224, channels: int = 3,
              seed: int = 12345, learning_rate: float = 0.01,
              dropout: float = 0.6) -> ComputationGraphConfiguration:
    # dropout is the RETAIN probability (DropoutLayer convention); the
    # paper's "dropout (40%)" drops 40% -> retain 0.6
    gb = (NeuralNetConfiguration.builder()
          .seed(seed)
          .learning_rate(learning_rate)
          .updater("nesterovs").momentum(0.9)
          .weight_init("relu")
          .graph_builder()
          .add_inputs("input"))
    _conv(gb, "stem_conv", 64, (7, 7), (2, 2), "input")
    gb.add_layer("stem_pool",
                 SubsamplingLayer(pooling_type="max", kernel_size=(3, 3),
                                  stride=(2, 2), convolution_mode="same"),
                 "stem_conv")
    gb.add_layer("stem_lrn", LocalResponseNormalization(n=5), "stem_pool")
    _conv(gb, "stem_conv2r", 64, (1, 1), (1, 1), "stem_lrn")
    _conv(gb, "stem_conv2", 192, (3, 3), (1, 1), "stem_conv2r")
    gb.add_layer("stem_lrn2", LocalResponseNormalization(n=5), "stem_conv2")
    gb.add_layer("pool2",
                 SubsamplingLayer(pooling_type="max", kernel_size=(3, 3),
                                  stride=(2, 2), convolution_mode="same"),
                 "stem_lrn2")
    cur = "pool2"
    for mod in ("3a", "3b"):
        cur = _inception(gb, mod, cur, _INCEPTION[mod])
    gb.add_layer("pool3",
                 SubsamplingLayer(pooling_type="max", kernel_size=(3, 3),
                                  stride=(2, 2), convolution_mode="same"),
                 cur)
    cur = "pool3"
    for mod in ("4a", "4b", "4c", "4d", "4e"):
        cur = _inception(gb, mod, cur, _INCEPTION[mod])
    gb.add_layer("pool4",
                 SubsamplingLayer(pooling_type="max", kernel_size=(3, 3),
                                  stride=(2, 2), convolution_mode="same"),
                 cur)
    cur = "pool4"
    for mod in ("5a", "5b"):
        cur = _inception(gb, mod, cur, _INCEPTION[mod])
    gb.add_layer("avgpool", GlobalPoolingLayer(pooling_type="avg"), cur)
    gb.add_layer("drop", DropoutLayer(dropout=dropout), "avgpool")
    gb.add_layer("fc", OutputLayer(n_out=n_classes, loss="mcxent",
                                   activation="softmax", weight_init="xavier"),
                 "drop")
    gb.set_outputs("fc")
    gb.set_input_types(InputType.convolutional(image_size, image_size,
                                               channels))
    return gb.build()
