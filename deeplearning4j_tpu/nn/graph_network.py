"""ComputationGraph: DAG network with multi-input/multi-output training.

Reference: nn/graph/ComputationGraph.java (2280 LoC) — init:266, fit:670/747,
computeGradientAndScore:952, feedForward:1003, calcBackpropGradients:1174 (reverse topo).

TPU-native: forward walks the topological order inside one traced function; autodiff
produces the reverse-topo backward (the reference's hand-written calcBackpropGradients).
The whole train step (multi-output loss sum + updaters) is one jit-compiled, donated
function, as in MultiLayerNetwork.
"""
from __future__ import annotations

import dataclasses
import math
import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu import common
from deeplearning4j_tpu.nn.conf.graphconf import ComputationGraphConfiguration
from deeplearning4j_tpu.nn.conf.vertices import LayerVertex
from deeplearning4j_tpu.nn.multilayer import (
    LazyScore, _updater_spec, _t_staging, _t_dispatch, _t_listeners,
)
from deeplearning4j_tpu.observability.compile_tracker import (
    global_tracker as _compile_tracker,
)
from deeplearning4j_tpu.observability.flight_recorder import (
    dump_on_unhandled as _dump_on_unhandled,
    global_recorder as _flight_recorder,
)
from deeplearning4j_tpu.observability.watchdog import beat as _wd_beat
from deeplearning4j_tpu.nn.updaters import (
    effective_lr, grads_to_param_dtype, normalize_gradients, updater_init,
    updater_step_with_param,
)
from deeplearning4j_tpu.utils.pytree import flatten_params, num_params, unflatten_params

Array = jax.Array


@dataclasses.dataclass
class MultiDataSet:
    """Multi-input/multi-output dataset (reference ND4J MultiDataSet)."""

    features: list
    labels: list
    features_masks: Optional[list] = None
    labels_masks: Optional[list] = None

    def num_examples(self) -> int:
        return int(self.features[0].shape[0])


def _graph_regularization(conf, params):
    if not conf.global_conf.use_regularization:
        return jnp.float32(0.0)
    total = jnp.float32(0.0)
    for name, vertex in conf.vertices.items():
        if not isinstance(vertex, LayerVertex) or name not in params:
            continue
        layer = vertex.layer
        for pname in layer.regularizable_params():
            if pname not in params[name]:
                continue
            w = params[name][pname]
            if layer.l1:
                total = total + layer.l1 * jnp.sum(jnp.abs(w))
            if layer.l2:
                total = total + 0.5 * layer.l2 * jnp.sum(w * w)
    return total


def graph_forward(conf: ComputationGraphConfiguration, params: dict, states: dict,
                  inputs: list, *, train: bool, rng: Optional[jax.Array],
                  masks: Optional[list] = None, collect_loss_inputs: bool = False):
    """Walk the DAG in topological order (reference feedForward:1003).

    Masks are routed per input stream: each vertex receives the mask propagated from
    its ancestors (first non-None among its inputs), mirroring the reference's
    per-input mask arrays (ComputationGraph.setLayerMaskArrays).

    Returns (activations dict, new states dict, loss_inputs dict) — loss_inputs maps
    each loss-bearing output vertex to its pre-layer input (for compute_loss), while
    acts[name] always holds the real activation so downstream consumers see the right
    tensor even during training.
    """
    acts: dict[str, Array] = dict(zip(conf.network_inputs, inputs))
    mask_of: dict[str, Optional[Array]] = {name: None for name in conf.network_inputs}
    if masks:
        for i, name in enumerate(conf.network_inputs):
            if i < len(masks):
                mask_of[name] = masks[i]
    new_states: dict[str, dict] = {}
    loss_inputs: dict[str, Array] = {}
    order = conf.topological_order or conf.topo_sort()
    rngs = (jax.random.split(rng, len(order)) if rng is not None
            else [None] * len(order))
    remat = train and conf.global_conf.gradient_checkpointing
    for i, name in enumerate(order):
        vertex = conf.vertices[name]
        srcs = conf.vertex_inputs[name]
        vins = [acts[src] for src in srcs]
        mask = next((mask_of[s] for s in srcs if mask_of.get(s) is not None), None)
        if (collect_loss_inputs and name in conf.network_outputs
                and isinstance(vertex, LayerVertex) and vertex.layer.has_loss()):
            loss_inputs[name] = vins[0]
        if remat and isinstance(vertex, LayerVertex):
            # jax.checkpoint per layer vertex: backward recomputes this
            # vertex's forward instead of holding its activations
            def f(p, vi, _v=vertex, _s=states.get(name, {}), _r=rngs[i]):
                return _v.apply(p, _s, vi, train=True, rng=_r, mask=mask)
            y, ns = jax.checkpoint(f)(params.get(name, {}), vins)
        else:
            y, ns = vertex.apply(params.get(name, {}), states.get(name, {}),
                                 vins, train=train, rng=rngs[i], mask=mask)
        acts[name] = y
        new_states[name] = ns
        mask_of[name] = mask
    return acts, new_states, loss_inputs


def graph_loss(conf, params, states, inputs, labels, rng, fmasks=None, lmasks=None):
    """Sum of output-layer losses + regularization (reference computeGradientAndScore:952)."""
    acts, new_states, loss_inputs = graph_forward(
        conf, params, states, inputs, train=True, rng=rng, masks=fmasks,
        collect_loss_inputs=True)
    total = jnp.float32(0.0)
    for i, out_name in enumerate(conf.network_outputs):
        vertex = conf.vertices[out_name]
        if not (isinstance(vertex, LayerVertex) and vertex.layer.has_loss()):
            raise ValueError(f"Output vertex '{out_name}' has no loss function")
        h = loss_inputs[out_name]
        lmask = lmasks[i] if lmasks else None
        total = total + vertex.layer.compute_loss(params[out_name], h, labels[i], lmask)
    total = total + _aux_losses(conf, new_states)
    return total + _graph_regularization(conf, params), new_states


def _aux_losses(conf, new_states):
    """Layer-declared auxiliary objectives (MoE load-balance etc.), published
    through the vertex state pytree as "aux_loss". Shared by the standard and
    TBPTT train objectives so a MoE vertex keeps its balance term under
    truncated BPTT too (reference computeGradientAndScore:952 adds every
    layer's contribution regardless of backprop type)."""
    total = jnp.float32(0.0)
    for name, ns in new_states.items():
        if isinstance(ns, dict) and "aux_loss" in ns:
            vertex = conf.vertices[name]
            w = getattr(getattr(vertex, "layer", None), "aux_loss_weight", 1.0)
            total = total + w * ns["aux_loss"]
    return total


def _coerce_graph_batch(ds):
    """Normalize a DataSet or MultiDataSet into (xs, ys, fmasks, lmasks) lists."""
    if isinstance(ds, MultiDataSet):
        return ds.features, ds.labels, ds.features_masks, ds.labels_masks
    fm = [ds.features_mask] if ds.features_mask is not None else None
    lm = [ds.labels_mask] if ds.labels_mask is not None else None
    return [ds.features], [ds.labels], fm, lm


def _apply_graph_updates(conf, params, grads, upd_state, iteration):
    """Per-vertex gradient normalization + updater math (shared by the
    standard and TBPTT train steps)."""
    g = conf.global_conf
    grads = grads_to_param_dtype(
        grads, {n: {k: params[n][k] for k in gv} for n, gv in grads.items()})
    new_params = {}
    new_upd = {}
    for name in conf.topological_order:
        vertex = conf.vertices[name]
        g_v = grads.get(name, {})
        if not g_v or not isinstance(vertex, LayerVertex):
            new_params[name] = params.get(name, {})
            new_upd[name] = upd_state.get(name, {})
            continue
        layer = vertex.layer
        g_v = normalize_gradients(g_v, layer.gradient_normalization,
                                  layer.gradient_normalization_threshold or 1.0)
        spec = _updater_spec(layer)
        lr = effective_lr(layer.learning_rate, g.lr_policy, iteration,
                          g.lr_policy_decay_rate, g.lr_policy_power,
                          g.lr_policy_steps, g.lr_schedule, g.max_num_iterations)
        lr_bias = (jnp.float32(layer.bias_learning_rate)
                   if layer.bias_learning_rate is not None else lr)
        p_new, u_new = {}, {}
        for pname, grad in g_v.items():
            this_lr = lr_bias if pname in ("b", "vb", "beta") else lr
            step, ustate = updater_step_with_param(
                spec, grad, params[name][pname], upd_state[name][pname],
                this_lr, iteration)
            p_new[pname] = params[name][pname] - step
            u_new[pname] = ustate
        new_params[name] = p_new
        new_upd[name] = u_new
    return new_params, new_upd


def make_graph_train_step(conf: ComputationGraphConfiguration, *,
                          health: bool = False):
    """``health=True`` appends the health monitor's packed summary vector to
    the return tuple (see make_train_step in multilayer.py)."""
    def train_step(params, states, upd_state, inputs, labels, rng, iteration,
                   fmasks=None, lmasks=None):
        (loss, new_states), grads = jax.value_and_grad(
            lambda p: graph_loss(conf, p, states, inputs, labels, rng, fmasks, lmasks),
            has_aux=True)(params)
        new_params, new_upd = _apply_graph_updates(conf, params, grads,
                                                   upd_state, iteration)
        if health:
            from deeplearning4j_tpu.observability.health import health_terms

            haux = health_terms(grads, params, new_params, loss)
            return new_params, new_states, new_upd, loss, haux
        return new_params, new_states, new_upd, loss

    # a config-declared dtype policy is baked in at trace time (GlobalConf.dtype)
    return common.wrap_with_policy(train_step, conf.global_conf.dtype)


def _is_streaming_lstm(vertex) -> bool:
    from deeplearning4j_tpu.nn.conf.layers.recurrent import LSTM

    return (isinstance(vertex, LayerVertex) and isinstance(vertex.layer, LSTM)
            and not type(vertex.layer).__name__.startswith(
                "GravesBidirectional"))


def _init_graph_rnn_states(conf, batch: int, dtype) -> dict:
    states = {}
    for name, vertex in conf.vertices.items():
        if _is_streaming_lstm(vertex):
            h = vertex.layer.n_out
            states[name] = {"h": jnp.zeros((batch, h), dtype),
                            "c": jnp.zeros((batch, h), dtype)}
        else:
            states[name] = {}
    return states


def graph_forward_streaming(conf, params, states, rnn_states, inputs, *,
                            train: bool, rng, masks=None,
                            collect_loss_inputs: bool = False,
                            truncate: bool = False):
    """DAG walk threading LSTM streaming state across calls (reference
    ComputationGraph.rnnTimeStep:1788 / rnnActivateUsingStoredState:1955).

    ``truncate=True`` stop-gradients the carried state at the chunk boundary
    — the TBPTT truncation (reference doTruncatedBPTT semantics on graphs,
    ComputationGraph.fit -> rnnUpdateStateWithTBPTTState:2032).
    Returns (acts, new_states, loss_inputs, new_rnn_states).
    """
    acts: dict = dict(zip(conf.network_inputs, inputs))
    mask_of: dict = {name: None for name in conf.network_inputs}
    if masks:
        for i, name in enumerate(conf.network_inputs):
            if i < len(masks):
                mask_of[name] = masks[i]
    new_states: dict = {}
    new_rnn: dict = {}
    loss_inputs: dict = {}
    order = conf.topological_order or conf.topo_sort()
    rngs = (jax.random.split(rng, len(order)) if rng is not None
            else [None] * len(order))
    for i, name in enumerate(order):
        vertex = conf.vertices[name]
        srcs = conf.vertex_inputs[name]
        vins = [acts[src] for src in srcs]
        mask = next((mask_of[s] for s in srcs if mask_of.get(s) is not None),
                    None)
        if (collect_loss_inputs and name in conf.network_outputs
                and isinstance(vertex, LayerVertex)
                and vertex.layer.has_loss()):
            loss_inputs[name] = vins[0]
        if _is_streaming_lstm(vertex):
            y, rs = vertex.layer.apply_streaming(
                params.get(name, {}), rnn_states.get(name, {}), vins[0],
                mask=mask)
            if truncate:
                rs = jax.tree_util.tree_map(jax.lax.stop_gradient, rs)
            new_rnn[name] = rs
            ns = states.get(name, {})
        else:
            y, ns = vertex.apply(params.get(name, {}), states.get(name, {}),
                                 vins, train=train, rng=rngs[i], mask=mask)
            new_rnn[name] = rnn_states.get(name, {})
        acts[name] = y
        new_states[name] = ns
        mask_of[name] = mask
    return acts, new_states, loss_inputs, new_rnn


def make_graph_tbptt_step(conf: ComputationGraphConfiguration):
    """TBPTT train step for graphs: threads LSTM state across time chunks,
    truncating gradients at chunk boundaries (reference ComputationGraph
    doTruncatedBPTT path, fit:747 -> calcBackpropGradients with tbptt)."""

    def tbptt_step(params, states, upd_state, rnn_states, inputs, labels, rng,
                   iteration, fmasks=None, lmasks=None):
        def lf(p):
            _, new_states, loss_inputs, new_rnn = graph_forward_streaming(
                conf, p, states, rnn_states, inputs, train=True, rng=rng,
                masks=fmasks, collect_loss_inputs=True, truncate=True)
            total = jnp.float32(0.0)
            for i, out_name in enumerate(conf.network_outputs):
                vertex = conf.vertices[out_name]
                if not (isinstance(vertex, LayerVertex)
                        and vertex.layer.has_loss()):
                    raise ValueError(
                        f"Output vertex '{out_name}' has no loss function")
                lmask = lmasks[i] if lmasks else None
                total = total + vertex.layer.compute_loss(
                    p[out_name], loss_inputs[out_name], labels[i], lmask)
            total = total + _aux_losses(conf, new_states)
            return total + _graph_regularization(conf, p), (new_states, new_rnn)

        (loss, (new_states, new_rnn)), grads = jax.value_and_grad(
            lf, has_aux=True)(params)
        new_params, new_upd = _apply_graph_updates(conf, params, grads,
                                                   upd_state, iteration)
        return new_params, new_states, new_upd, new_rnn, loss

    return common.wrap_with_policy(tbptt_step, conf.global_conf.dtype)


def make_graph_multistep_train_step(conf: ComputationGraphConfiguration, *,
                                    health: bool = False):
    """K fused graph train steps per host dispatch via `lax.scan`.

    ``inputs_stack``/``labels_stack`` are lists of ``(K, B, ...)`` arrays (one
    per graph input/output). See make_multistep_train_step in multilayer.py
    for the rationale (dispatch amortization on TPU) and the ``health``
    variant's stacked ``(K, 4)`` summary output."""
    step = make_graph_train_step(conf, health=health)

    def multi_step(params, states, upd_state, inputs_stack, labels_stack,
                   rng, iteration0):
        def body(carry, batch):
            p, s, u, it = carry
            xs, ys = batch
            key = jax.random.fold_in(rng, it)
            if health:
                p, s, u, loss, haux = step(p, s, u, xs, ys, key, it)
                return (p, s, u, it + 1), (loss, haux)
            p, s, u, loss = step(p, s, u, xs, ys, key, it)
            return (p, s, u, it + 1), loss

        (p, s, u, _), out = jax.lax.scan(
            body, (params, states, upd_state, iteration0),
            (list(inputs_stack), list(labels_stack)))
        if health:
            losses, hauxs = out
            return p, s, u, losses, hauxs
        return p, s, u, out

    return multi_step


def _ancestor_set(conf, target: str) -> set:
    """All vertices the target transitively depends on (inputs included)."""
    anc: set = set()
    stack = list(conf.vertex_inputs.get(target, []))
    while stack:
        n = stack.pop()
        if n in anc:
            continue
        anc.add(n)
        stack.extend(conf.vertex_inputs.get(n, []))
    return anc


def eval_forward_to_vertex(conf, params, states, inputs, name: str):
    """Eval-mode forward of ``name``'s ancestors only; returns the vertex's
    (first) input activation. ONE walk shared by the pretrain train step and
    the graph pretrain gradient checker so both always see the same forward."""
    anc = _ancestor_set(conf, name)
    order = [n for n in (conf.topological_order or conf.topo_sort())
             if n in anc]
    acts = dict(zip(conf.network_inputs, inputs))
    for n in order:
        if n in acts:
            continue
        vins = [acts[s] for s in conf.vertex_inputs[n]]
        y, _ = conf.vertices[n].apply(params.get(n, {}), states.get(n, {}),
                                      vins, train=False, rng=None)
        acts[n] = y
    return acts[conf.vertex_inputs[name][0]]


def make_graph_pretrain_step(conf: ComputationGraphConfiguration, name: str):
    """Unsupervised pretrain step for one graph vertex (reference
    ComputationGraph.pretrainLayer:540): evaluate the vertex's ancestors in
    eval mode, stop the gradient at the vertex input, and minimize the
    vertex layer's pretrain objective — only that vertex's params move."""
    g = conf.global_conf
    layer = conf.vertices[name].layer

    def pretrain_step(params, states, vertex_upd_state, inputs, rng, iteration):
        h = jax.lax.stop_gradient(
            eval_forward_to_vertex(conf, params, states, inputs, name))

        def lf(p):
            return layer.pretrain_loss(p, h, rng=rng)

        loss, grads = jax.value_and_grad(lf)(params[name])
        grads = grads_to_param_dtype(grads, params[name])
        grads = normalize_gradients(grads, layer.gradient_normalization,
                                    layer.gradient_normalization_threshold or 1.0)
        spec = _updater_spec(layer)
        lr = effective_lr(layer.learning_rate, g.lr_policy, iteration,
                          g.lr_policy_decay_rate, g.lr_policy_power,
                          g.lr_policy_steps, g.lr_schedule, g.max_num_iterations)
        p_new, u_new = {}, {}
        for pname, grad in grads.items():
            step, ustate = updater_step_with_param(
                spec, grad, params[name][pname], vertex_upd_state[pname],
                lr, iteration)
            p_new[pname] = params[name][pname] - step
            u_new[pname] = ustate
        return p_new, u_new, loss

    return common.wrap_with_policy(pretrain_step, g.dtype)


class ComputationGraph(LazyScore):
    """Stateful shell (reference nn/graph/ComputationGraph.java)."""

    _multistep_builder = staticmethod(make_graph_multistep_train_step)

    def __init__(self, conf: ComputationGraphConfiguration):
        self.conf = conf
        self.params_list: Optional[dict] = None   # name -> params dict
        self.state_list: Optional[dict] = None
        self.updater_state: Optional[dict] = None
        self.iteration = 0
        self.epoch = 0
        self.listeners: list = []
        self.score_value = float("nan")
        self._rng = None
        self._jit_cache: dict = {}
        self._rnn_state: Optional[dict] = None  # streaming rnn_time_step state

    # ------------------------------------------------------------------ lifecycle
    def init(self, seed: Optional[int] = None) -> "ComputationGraph":
        g = self.conf.global_conf
        key = jax.random.PRNGKey(g.seed if seed is None else seed)
        self._rng = jax.random.fold_in(key, 0xC6)
        order = self.conf.topological_order or self.conf.topo_sort()
        self.conf.topological_order = order
        keys = jax.random.split(key, max(len(order), 1))
        # propagate input types for init
        types: dict = {}
        if self.conf.input_types:
            types.update(zip(self.conf.network_inputs, self.conf.input_types))
        self.params_list = {}
        self.state_list = {}
        for i, name in enumerate(order):
            vertex = self.conf.vertices[name]
            in_types = [types.get(src) for src in self.conf.vertex_inputs[name]]
            self.params_list[name] = vertex.init_params(keys[i], in_types)
            self.state_list[name] = vertex.init_state(in_types)
            try:
                types[name] = vertex.output_type(in_types)
            except Exception:
                types[name] = None
        self.updater_state = {
            name: {pname: updater_init(_updater_spec(self.conf.vertices[name].layer), p)
                   for pname, p in params.items()}
            if isinstance(self.conf.vertices[name], LayerVertex) else {}
            for name, params in self.params_list.items()
        }
        return self

    def set_listeners(self, *listeners) -> None:
        self.listeners = list(listeners)

    # ------------------------------------------------------------------ params API
    def params(self) -> Array:
        return flatten_params(self.params_list, jnp.float32)

    def set_params(self, flat: Array) -> None:
        self.params_list = unflatten_params(self.params_list, flat)

    def num_params(self) -> int:
        return num_params(self.params_list)

    # ------------------------------------------------------------------ inference
    def output(self, *inputs) -> list:
        """Forward pass returning all network outputs (reference output:1520)."""
        self._require_init()
        xs = [jnp.asarray(x) for x in inputs]
        fn = self._jit("output", self._output_pure)
        outs, _ = fn(self.params_list, self.state_list, xs)
        return outs

    def _output_pure(self, params, states, xs):
        acts, ns, _ = graph_forward(self.conf, params, states, xs, train=False,
                                    rng=None)
        return [acts[o] for o in self.conf.network_outputs], ns

    def score(self, mds: MultiDataSet) -> float:
        self._require_init()
        xs = [jnp.asarray(f) for f in mds.features]
        ys = [jnp.asarray(l) for l in mds.labels]
        fn = self._jit("score", self._score_pure)
        return float(fn(self.params_list, self.state_list, xs, ys))

    def _score_pure(self, params, states, xs, ys):
        # evaluation loss: eval-mode forward (no dropout, running BN stats,
        # no MoE aux term) + data losses + regularization — mirrors
        # MultiLayerNetwork.score and the reference's score():1704 semantics
        conf = self.conf
        _, _, loss_inputs = graph_forward(conf, params, states, xs,
                                          train=False, rng=None,
                                          collect_loss_inputs=True)
        total = jnp.float32(0.0)
        for i, out_name in enumerate(conf.network_outputs):
            vertex = conf.vertices[out_name]
            if not (isinstance(vertex, LayerVertex) and vertex.layer.has_loss()):
                raise ValueError(f"Output vertex '{out_name}' has no loss function")
            total = total + vertex.layer.compute_loss(
                params[out_name], loss_inputs[out_name], ys[i], None)
        return total + _graph_regularization(conf, params)

    # ------------------------------------------------------------------ training
    def _next_rng(self):
        self._require_init()
        if self._rng is None:
            raise RuntimeError(self.NOT_INITIALIZED_MSG)
        self._rng, sub = jax.random.split(self._rng)
        return sub

    @_dump_on_unhandled("ComputationGraph.fit")
    def fit(self, data, labels=None, *, epochs: int = 1) -> None:
        """Fit on a MultiDataSet, DataSet, iterator, or (inputs, labels) lists
        (reference fit:670/747)."""
        from deeplearning4j_tpu.datasets.dataset import DataSet

        if isinstance(data, (MultiDataSet, DataSet)):
            xs, ys, fm, lm = _coerce_graph_batch(data)
            if epochs > 1 and fm is None and lm is None \
                    and self._repeat_multistep_ok():
                self._fit_repeated(xs, ys, epochs)
            else:
                for _ in range(epochs):
                    self._fit_batch(xs, ys, fm, lm)
            return
        if labels is not None:
            xs = list(data if isinstance(data, (list, tuple)) else [data])
            ys = list(labels if isinstance(labels, (list, tuple)) else [labels])
            if epochs > 1 and self._repeat_multistep_ok():
                self._fit_repeated(xs, ys, epochs)
            else:
                for _ in range(epochs):
                    self._fit_batch(xs, ys)
            return
        self.fit_iterator(data, epochs=epochs)

    def _repeat_multistep_ok(self) -> bool:
        return (self.dispatch_ksteps > 1 and self._uses_sgd()
                and self.conf.global_conf.iterations <= 1
                and not self._tbptt_active())

    def _fit_repeated(self, xs, ys, epochs: int) -> None:
        """Repeated steps on one device-resident multi-IO batch, K per
        dispatch (see MultiLayerNetwork._fit_repeated)."""
        from deeplearning4j_tpu.nn.multilayer import _stage_host

        with _t_staging.time():
            xd = [jnp.asarray(_stage_host(a, self.stage_dtype)) for a in xs]
            yd = [jnp.asarray(a) for a in ys]
        self.last_batch_size = int(xd[0].shape[0]) if xd and xd[0].ndim else 0
        remaining = epochs
        while remaining > 0:
            k = min(self.dispatch_ksteps, remaining)
            xk = [jnp.broadcast_to(a[None], (k,) + a.shape) for a in xd]
            yk = [jnp.broadcast_to(a[None], (k,) + a.shape) for a in yd]
            losses = self._run_multistep(xk, yk, k)
            with _t_listeners.time():
                for i in range(k):
                    self.iteration += 1
                    self.score_value = (lambda ls=losses, j=i: ls[j])
                    for listener in self.listeners:
                        listener.iteration_done(self, self.iteration)
            _wd_beat(self.iteration)
            remaining -= k

    #: train steps fused per host dispatch in fit_iterator (see
    #: MultiLayerNetwork.dispatch_ksteps); 1 disables the K-step path
    dispatch_ksteps: int = 8

    #: host-side feature staging dtype for the fused fit path (see
    #: MultiLayerNetwork.stage_dtype); None keeps exact f32 staging
    stage_dtype = None

    #: staged K-groups prefetched ahead of the dispatch loop (see
    #: MultiLayerNetwork.prefetch_depth); 0 = synchronous staging
    prefetch_depth: int = 2

    @_dump_on_unhandled("ComputationGraph.fit_iterator")
    def fit_iterator(self, iterator, epochs: int = 1,
                     ksteps: Optional[int] = None) -> None:
        """Iterator fit with K-step fused dispatch (TPU fast path — see
        MultiLayerNetwork.fit_iterator; reference fit(DataSetIterator):747).
        Falls back to per-batch dispatch for masked or ragged batches."""
        k = self.dispatch_ksteps if ksteps is None else max(1, ksteps)
        multistep_ok = (k > 1 and self._uses_sgd()
                        and self.conf.global_conf.iterations <= 1
                        and not self._tbptt_active())
        for _ in range(epochs):
            for listener in self.listeners:
                if hasattr(listener, "on_epoch_start"):
                    listener.on_epoch_start(self)
            if hasattr(iterator, "reset"):
                iterator.reset()
            if self.conf.pretrain:
                self.pretrain(iterator)
                if hasattr(iterator, "reset"):
                    iterator.reset()
            if multistep_ok:
                self._fit_epoch_multistep(iterator, k)
            else:
                for ds in iterator:
                    xs, ys, fm, lm = _coerce_graph_batch(ds)
                    self._fit_batch(xs, ys, fm, lm)
            for listener in self.listeners:
                if hasattr(listener, "on_epoch_end"):
                    listener.on_epoch_end(self)
            self.epoch += 1

    def _fit_epoch_multistep(self, iterator, k: int) -> None:
        from deeplearning4j_tpu.datasets.prefetch import DevicePrefetcher
        from deeplearning4j_tpu.nn.multilayer import _stage_host
        from deeplearning4j_tpu.utils.batching import k_step_groups

        def to_batch(ds):
            xs, ys, fm, lm = _coerce_graph_batch(ds)
            if fm is not None or lm is not None:
                return None  # masked -> per-batch fallback
            # lint: host-sync-in-hot-loop-ok (producer-thread host staging of iterator output, not a device sync)
            return ([np.asarray(x) for x in xs], [np.asarray(y) for y in ys])

        def stage(kind_item):
            # producer thread: per-stream stack + cast + non-blocking
            # device_put (see MultiLayerNetwork._fit_epoch_multistep)
            kind, item = kind_item
            if kind != "group" or len(item) < 2:
                return kind_item
            n_in, n_out = len(item[0][0]), len(item[0][1])
            xs = [jax.device_put(_stage_host(
                      np.stack([b[0][i] for b in item]), self.stage_dtype))
                  for i in range(n_in)]
            ys = [jax.device_put(np.stack([b[1][i] for b in item]))
                  for i in range(n_out)]
            return "staged", (xs, ys, len(item))

        pf = DevicePrefetcher(k_step_groups(iterator, k, to_batch), stage,
                              depth=self.prefetch_depth, path="graph",
                              wait_series=_t_staging)
        for kind, item in pf:
            if kind == "single":
                self._fit_batch(*_coerce_graph_batch(item))
            elif kind == "group":
                if item:
                    self._fit_batch(item[0][0], item[0][1])
            else:
                self._dispatch_staged(*item)

    def _dispatch_multistep(self, batches: list) -> None:
        """Synchronous-staging compatibility path (prefetch_depth=0 semantics
        for a pre-built group)."""
        if not batches:
            return
        if len(batches) == 1:
            self._fit_batch(batches[0][0], batches[0][1])
            return
        n_in, n_out = len(batches[0][0]), len(batches[0][1])

        from deeplearning4j_tpu.nn.multilayer import _stage_host

        with _t_staging.time():
            xs = [jnp.asarray(_stage_host(np.stack([b[0][i] for b in batches]),
                                          self.stage_dtype))
                  for i in range(n_in)]
            ys = [jnp.asarray(np.stack([b[1][i] for b in batches]))
                  for i in range(n_out)]
        self._dispatch_staged(xs, ys, len(batches))

    def _dispatch_staged(self, xs, ys, n: int) -> None:
        # donated params/states/updater: in-place XLA update; staged xs/ys
        # are fresh, non-donated buffers so prefetched groups never alias
        # what the in-flight step consumes (see
        # MultiLayerNetwork._dispatch_staged)
        self.last_batch_size = int(xs[0].shape[1]) if xs else 0
        losses = self._run_multistep(xs, ys, n)
        with _t_listeners.time():
            for i in range(n):
                self.iteration += 1
                self.score_value = (lambda ls=losses, j=i: ls[j])
                for listener in self.listeners:
                    listener.iteration_done(self, self.iteration)
        _wd_beat(self.iteration)

    #: Solver facade instance when optimization_algo != SGD (built lazily)
    _solver = None

    def _uses_sgd(self) -> bool:
        algo = self.conf.global_conf.optimization_algo
        return algo in (None, "stochastic_gradient_descent")

    def _tbptt_active(self) -> bool:
        return (self.conf.backprop_type == "TruncatedBPTT"
                and any(_is_streaming_lstm(v)
                        for v in self.conf.vertices.values()))

    def _fit_batch(self, xs, ys, fmasks=None, lmasks=None) -> None:
        if not self._uses_sgd():
            # honor optimization_algo (reference Solver.java:55); see
            # MultiLayerNetwork._fit_batch
            from deeplearning4j_tpu.optimize.solvers import Solver

            if self._solver is None:
                self._solver = Solver(self)
            self._solver.optimize(list(xs), list(ys))
            return
        if self._tbptt_active():
            self._fit_tbptt(xs, ys, fmasks, lmasks)
            return
        with _t_staging.time():
            xs = [jnp.asarray(x) for x in xs]
            ys = [jnp.asarray(y) for y in ys]
            fmasks = [jnp.asarray(m) for m in fmasks] if fmasks else None
            lmasks = [jnp.asarray(m) for m in lmasks] if lmasks else None
        self.last_batch_size = int(xs[0].shape[0]) if xs and xs[0].ndim else 0
        for _ in range(max(1, self.conf.global_conf.iterations)):
            hm = self.health_monitor
            use_health = hm is not None and hm.due(self.iteration)
            name = "train_step_health" if use_health else "train_step"
            step = self._jit(name, make_graph_train_step(self.conf,
                                                         health=use_health))
            t0 = time.perf_counter()
            out = step(self.params_list, self.state_list,
                       self.updater_state, xs, ys, self._next_rng(),
                       jnp.int32(self.iteration), fmasks, lmasks)
            dt = time.perf_counter() - t0
            _t_dispatch.observe(dt)
            if use_health:
                (self.params_list, self.state_list, self.updater_state,
                 loss, haux) = out
                hm.offer(haux, self.iteration)
            else:
                (self.params_list, self.state_list, self.updater_state,
                 loss) = out
            wrap_name = f"{type(self).__name__}.{name}"
            _compile_tracker().note_step(fn=wrap_name)
            _flight_recorder().record(
                "step", path=wrap_name, it=self.iteration,
                batch=self.last_batch_size, dispatch_s=dt)
            self.score_value = loss  # device scalar; synced lazily (LazyScore)
            self.iteration += 1
            with _t_listeners.time():
                for listener in self.listeners:
                    listener.iteration_done(self, self.iteration)
            _wd_beat(self.iteration)

    # ------------------------------------------------------------------ pretrain
    def pretrain(self, iterator) -> None:
        """Greedy layerwise unsupervised pretraining over every pretrainable
        vertex in topological order (reference ComputationGraph.pretrain:509):
        earlier vertices are frozen features for later ones."""
        from deeplearning4j_tpu.nn.conf.layers.base import PretrainLayer

        for name in self.conf.topological_order or self.conf.topo_sort():
            vertex = self.conf.vertices[name]
            if (isinstance(vertex, LayerVertex)
                    and isinstance(vertex.layer, PretrainLayer)):
                self.pretrain_layer(name, iterator)

    def pretrain_layer(self, name: str, iterator) -> None:
        """Pretrain ONE vertex layer unsupervised (reference
        ComputationGraph.pretrainLayer:540). Ancestor vertices run in eval
        mode to produce its input; only the named vertex's params update."""
        from deeplearning4j_tpu.nn.conf.layers.base import PretrainLayer

        self._require_init()
        if name not in self.conf.vertices:
            raise ValueError(
                f"Unknown vertex '{name}' — graph vertices: "
                f"{sorted(self.conf.vertices)}")
        vertex = self.conf.vertices[name]
        if not (isinstance(vertex, LayerVertex)
                and isinstance(vertex.layer, PretrainLayer)):
            raise ValueError(
                f"Vertex '{name}' is not pretrainable — layerwise pretraining "
                "needs an unsupervised layer (VAE, RBM, AutoEncoder)")
        step = self._jit(f"pretrain:{name}",
                         make_graph_pretrain_step(self.conf, name))
        if hasattr(iterator, "reset"):
            iterator.reset()
        for ds in iterator:
            xs, _, _, _ = _coerce_graph_batch(ds)
            xs = [jnp.asarray(x) for x in xs]
            (self.params_list[name], self.updater_state[name], loss) = step(
                self.params_list, self.state_list, self.updater_state[name],
                xs, self._next_rng(), jnp.int32(self.iteration))
            self.score_value = loss  # synced lazily (LazyScore)

    # ------------------------------------------------------------------ evaluation
    def evaluate(self, iterator, labels_list=None, top_n: int = 1):
        """Evaluate the network's outputs against a (Multi)DataSet iterator
        (reference ComputationGraph.evaluate:2230,2253).

        Label masks are threaded per output stream — masked timesteps do not
        count — and every network output is scored against its matching label
        array into one accumulated Evaluation (single-output graphs behave
        exactly as before). ``labels_list``/``top_n`` attach class-label names
        and top-N accuracy, as in MultiLayerNetwork.evaluate.
        """
        from deeplearning4j_tpu.eval.evaluation import Evaluation

        ev = Evaluation(labels=labels_list, top_n=top_n)
        if hasattr(iterator, "reset"):
            iterator.reset()
        for ds in iterator:
            feats, labels, fmasks, lmasks = _coerce_graph_batch(ds)
            outs = self._output_for_eval(feats, fmasks)
            n_cls = np.asarray(labels[0]).shape[-1]
            for i, out in enumerate(outs):
                if i >= len(labels):
                    break
                if np.asarray(labels[i]).shape[-1] != n_cls:
                    # one Evaluation holds one confusion matrix; streams with
                    # a different class count need their own pass (evaluate a
                    # single-output view or use eval/ directly)
                    continue
                lm = (np.asarray(lmasks[i])
                      if lmasks and i < len(lmasks) and lmasks[i] is not None
                      else None)
                ev.eval(np.asarray(labels[i]), np.asarray(out), mask=lm)
        return ev

    def _output_for_eval(self, feats, fmasks):
        """Eval-mode forward that honors feature masks (evaluate's path;
        output() stays the mask-free public inference entry)."""
        self._require_init()
        xs = [jnp.asarray(f) for f in feats]
        if fmasks is None:
            fn = self._jit("output", self._output_pure)
            outs, _ = fn(self.params_list, self.state_list, xs)
            return outs
        ms = [jnp.asarray(m) if m is not None else None for m in fmasks]
        fn = self._jit("output_masked", self._output_masked_pure)
        outs, _ = fn(self.params_list, self.state_list, xs, ms)
        return outs

    def _output_masked_pure(self, params, states, xs, masks):
        acts, ns, _ = graph_forward(self.conf, params, states, xs, train=False,
                                    rng=None, masks=masks)
        return [acts[o] for o in self.conf.network_outputs], ns

    # ------------------------------------------------------------------ TBPTT
    def _fit_tbptt(self, xs, ys, fmasks=None, lmasks=None) -> None:
        """Truncated BPTT on graphs (reference ComputationGraph fit with
        BackpropType.TruncatedBPTT): slice every input/label/mask along the
        time axis into tbptt_fwd_length chunks; LSTM-vertex state carries
        across chunks via stop_gradient (the truncation). Time axis = 1."""
        xs = [jnp.asarray(x) for x in xs]
        ys = [jnp.asarray(y) for y in ys]
        T = xs[0].shape[1]
        L = self.conf.tbptt_fwd_length
        n_chunks = max(1, math.ceil(T / L))
        step = self._jit("tbptt_step", make_graph_tbptt_step(self.conf))
        rnn_state = _init_graph_rnn_states(self.conf, xs[0].shape[0],
                                           xs[0].dtype)
        for c in range(n_chunks):
            sl = slice(c * L, min((c + 1) * L, T))
            xc = [x[:, sl] for x in xs]
            yc = [y[:, sl] for y in ys]
            fm = [m[:, sl] for m in fmasks] if fmasks else None
            lm = [m[:, sl] for m in lmasks] if lmasks else None
            (self.params_list, self.state_list, self.updater_state, rnn_state,
             loss) = step(self.params_list, self.state_list,
                          self.updater_state, rnn_state, xc, yc,
                          self._next_rng(), jnp.int32(self.iteration), fm, lm)
            _compile_tracker().note_step(fn=f"{type(self).__name__}.tbptt_step")
            _flight_recorder().record(
                "step", path=f"{type(self).__name__}.tbptt_step",
                it=self.iteration, batch=self.last_batch_size)
            self.score_value = loss  # synced lazily (LazyScore)
            self.iteration += 1
            for listener in self.listeners:
                listener.iteration_done(self, self.iteration)
            _wd_beat(self.iteration)

    # ------------------------------------------------------------------ rnn API
    def rnn_time_step(self, *inputs) -> list:
        """Streaming inference carrying LSTM-vertex hidden state across calls
        (reference ComputationGraph.rnnTimeStep:1788). Each input: [B,T,F]
        (T may be 1). Returns the list of network outputs."""
        self._require_init()
        xs = [jnp.asarray(x) for x in inputs]
        if self._rnn_state is None:
            self._rnn_state = _init_graph_rnn_states(self.conf, xs[0].shape[0],
                                                     xs[0].dtype)
        fn = self._jit("rnn_time_step", self._rnn_step_pure)
        outs, self._rnn_state = fn(self.params_list, self.state_list,
                                   self._rnn_state, xs)
        return outs

    def _rnn_step_pure(self, params, states, rnn_states, xs):
        acts, _, _, new_rnn = graph_forward_streaming(
            self.conf, params, states, rnn_states, xs, train=False, rng=None)
        return [acts[o] for o in self.conf.network_outputs], new_rnn

    def rnn_get_previous_state(self):
        """Per-vertex streaming LSTM state (reference
        ComputationGraph.rnnGetPreviousState:1873)."""
        return self._rnn_state

    def rnn_set_previous_state(self, state) -> None:
        """Install streaming state (reference rnnSetPreviousState:1912)."""
        self._rnn_state = (jax.tree_util.tree_map(jnp.asarray, state)
                           if state is not None else None)

    def rnn_clear_previous_state(self) -> None:
        self._rnn_state = None

    def clone(self) -> "ComputationGraph":
        """Deep copy with REAL buffer copies (see MultiLayerNetwork.clone:
        the fused fit path donates param buffers to XLA, so clones must not
        alias arrays). Reference ComputationGraph.clone:1249."""
        import copy

        net = ComputationGraph(copy.deepcopy(self.conf))
        cp = lambda a: jnp.array(a)
        net.params_list = jax.tree_util.tree_map(cp, self.params_list)
        net.state_list = jax.tree_util.tree_map(cp, self.state_list)
        net.updater_state = jax.tree_util.tree_map(cp, self.updater_state)
        net.iteration = self.iteration
        net.epoch = self.epoch
        net._rng = self._rng
        if self._rnn_state is not None:  # mid-stream serving handoff
            net._rnn_state = jax.tree_util.tree_map(cp, self._rnn_state)
        return net

    def score_examples(self, data, add_regularization: bool = False):
        """Per-example loss scores, un-reduced, summed over the graph's
        outputs (reference ComputationGraph.scoreExamples:1485/1502).
        Feature masks route through the forward walk, label masks weight
        each example's own loss — as in fit()."""
        self._require_init()
        xs, ys, fms, lms = _coerce_graph_batch(data)
        asarray_opt = lambda m: jnp.asarray(m) if m is not None else None
        fn = self._jit("score_examples", self._score_examples_pure)
        per = fn(self.params_list, self.state_list,
                 [jnp.asarray(x) for x in xs], [jnp.asarray(y) for y in ys],
                 [asarray_opt(m) for m in fms] if fms else None,
                 [asarray_opt(m) for m in lms] if lms else None)
        if add_regularization:
            per = per + _graph_regularization(self.conf, self.params_list)
        return np.asarray(per)

    def _score_examples_pure(self, params, states, xs, ys, fms, lms):
        conf = self.conf
        _, _, loss_inputs = graph_forward(conf, params, states, xs,
                                          train=False, rng=None, masks=fms,
                                          collect_loss_inputs=True)
        total = None
        for i, out_name in enumerate(conf.network_outputs):
            vertex = conf.vertices[out_name]
            if not (isinstance(vertex, LayerVertex) and vertex.layer.has_loss()):
                raise ValueError(
                    f"Output vertex '{out_name}' has no loss function")
            layer = vertex.layer
            lm = lms[i] if lms and i < len(lms) and lms[i] is not None else None

            def one(hi, yi, mi=None, _l=layer, _n=out_name):
                return _l.compute_loss(params[_n], hi[None], yi[None],
                                       mi[None] if mi is not None else None)

            per = (jax.vmap(one)(loss_inputs[out_name], ys[i], lm)
                   if lm is not None
                   else jax.vmap(one)(loss_inputs[out_name], ys[i]))
            total = per if total is None else total + per
        return total

    def gradient_and_score(self, xs, ys):
        self._require_init()
        xs = [jnp.asarray(x) for x in xs]
        ys = [jnp.asarray(y) for y in ys]

        def lf(p):
            loss, _ = graph_loss(self.conf, p, self.state_list, xs, ys, None)
            return loss

        loss, grads = jax.value_and_grad(lf)(self.params_list)
        return grads, float(loss)
