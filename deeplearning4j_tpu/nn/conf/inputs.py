"""InputType system: shape inference between layers.

Parity with reference nn/conf/inputs/InputType.java + nn/conf/layers/InputTypeUtil.java.
Used by MultiLayerConfiguration/GraphBuilder ``set_input_type`` to (a) infer each layer's
n_in from the previous layer's output type and (b) auto-insert InputPreProcessors at
layer-family boundaries (CNN<->FF, CNN<->RNN, FF<->RNN).

TPU-native layout conventions (differ from the reference's on purpose):
  - convolutional activations are NHWC (XLA:TPU's preferred layout; reference is NCHW)
  - recurrent activations are [batch, time, features] (reference is [batch, features, time])
"""
from __future__ import annotations

import dataclasses
from typing import Optional

from deeplearning4j_tpu.nn.conf.serde import register_config


@dataclasses.dataclass
class InputType:
    kind: str = "feedforward"  # feedforward | recurrent | convolutional | convolutionalflat
    size: int = 0              # feature dim (ff / recurrent)
    height: int = 0
    width: int = 0
    channels: int = 0
    timesteps: Optional[int] = None  # recurrent, None = variable

    # ---- factories (mirror reference InputType.feedForward/recurrent/convolutional) ----
    @staticmethod
    def feed_forward(size: int) -> "InputType":
        return InputType(kind="feedforward", size=int(size))

    @staticmethod
    def recurrent(size: int, timesteps: Optional[int] = None) -> "InputType":
        return InputType(kind="recurrent", size=int(size), timesteps=timesteps)

    @staticmethod
    def convolutional(height: int, width: int, channels: int) -> "InputType":
        return InputType(kind="convolutional", height=int(height), width=int(width),
                         channels=int(channels))

    @staticmethod
    def convolutional_flat(height: int, width: int, channels: int) -> "InputType":
        return InputType(kind="convolutionalflat", height=int(height), width=int(width),
                         channels=int(channels),
                         size=int(height) * int(width) * int(channels))

    # ---- helpers ----
    def flat_size(self) -> int:
        if self.kind in ("feedforward", "recurrent", "convolutionalflat"):
            return self.size if self.size else self.height * self.width * self.channels
        return self.height * self.width * self.channels

    def array_shape(self, batch: int = 1) -> tuple:
        """Concrete array shape for this type (NHWC / BTF conventions)."""
        if self.kind == "feedforward" or self.kind == "convolutionalflat":
            return (batch, self.flat_size())
        if self.kind == "recurrent":
            return (batch, self.timesteps or 1, self.size)
        if self.kind == "convolutional":
            return (batch, self.height, self.width, self.channels)
        raise ValueError(self.kind)


register_config("InputType")(InputType)
