"""InputPreProcessors: rank/layout adapters auto-inserted between layer families.

Reference: nn/conf/preprocessor/*.java (12 impls) — each has preProcess + backprop;
here only the forward reshape is needed (autodiff reverses it). Auto-insertion logic
mirrors reference InputTypeUtil / MultiLayerConfiguration.ListBuilder behaviour when
``set_input_type`` is used.

Layouts: FF [B,F]; CNN NHWC [B,H,W,C]; RNN [B,T,F].
"""
from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.serde import register_config

Array = jax.Array


@dataclasses.dataclass
class InputPreProcessor:
    def pre_process(self, x: Array, mask: Optional[Array] = None) -> Array:
        raise NotImplementedError

    def output_type(self, itype: InputType) -> InputType:
        raise NotImplementedError


@register_config("FeedForwardToCnn")
@dataclasses.dataclass
class FeedForwardToCnnPreProcessor(InputPreProcessor):
    height: int = 0
    width: int = 0
    channels: int = 1

    def pre_process(self, x, mask=None):
        return jnp.reshape(x, (x.shape[0], self.height, self.width, self.channels))

    def output_type(self, itype):
        return InputType.convolutional(self.height, self.width, self.channels)


@register_config("CnnToFeedForward")
@dataclasses.dataclass
class CnnToFeedForwardPreProcessor(InputPreProcessor):
    height: int = 0
    width: int = 0
    channels: int = 0

    def pre_process(self, x, mask=None):
        return jnp.reshape(x, (x.shape[0], -1))

    def output_type(self, itype):
        return InputType.feed_forward(itype.flat_size())


@register_config("RnnToFeedForward")
@dataclasses.dataclass
class RnnToFeedForwardPreProcessor(InputPreProcessor):
    """[B,T,F] -> [B*T,F] (reference RnnToFeedForwardPreProcessor: 2d<->3d merge)."""

    def pre_process(self, x, mask=None):
        return jnp.reshape(x, (-1, x.shape[-1]))

    def output_type(self, itype):
        return InputType.feed_forward(itype.size)


@register_config("FeedForwardToRnn")
@dataclasses.dataclass
class FeedForwardToRnnPreProcessor(InputPreProcessor):
    """[B*T,F] -> [B,T,F]; needs the original timesteps, carried via partner layers.

    In this framework RNN sequences stay rank-3 end-to-end (dense layers broadcast over
    time), so this preprocessor is only exercised by explicitly-configured FF->RNN
    boundaries where timesteps is known from set_input_type.
    """

    timesteps: int = 0

    def pre_process(self, x, mask=None):
        return jnp.reshape(x, (-1, self.timesteps, x.shape[-1]))

    def output_type(self, itype):
        return InputType.recurrent(itype.size, self.timesteps or None)


@register_config("CnnToRnn")
@dataclasses.dataclass
class CnnToRnnPreProcessor(InputPreProcessor):
    """[B,H,W,C] -> [B,1,H*W*C] single-timestep sequence (reference CnnToRnnPreProcessor
    reshapes per-timestep conv activations; with NHWC batch-major we treat batch dim as
    [B*T] when driven from sequence data)."""

    timesteps: int = 1

    def pre_process(self, x, mask=None):
        flat = jnp.reshape(x, (x.shape[0], -1))
        return jnp.reshape(flat, (-1, self.timesteps, flat.shape[-1]))

    def output_type(self, itype):
        return InputType.recurrent(itype.flat_size(), self.timesteps or None)


@register_config("RnnToCnn")
@dataclasses.dataclass
class RnnToCnnPreProcessor(InputPreProcessor):
    height: int = 0
    width: int = 0
    channels: int = 1

    def pre_process(self, x, mask=None):
        return jnp.reshape(x, (-1, self.height, self.width, self.channels))

    def output_type(self, itype):
        return InputType.convolutional(self.height, self.width, self.channels)


def infer_preprocessor(prev: InputType, layer) -> Optional[InputPreProcessor]:
    """Auto-insert a preprocessor between ``prev`` output type and ``layer``
    (reference InputTypeUtil.getPreProcessorForInputType*)."""
    from deeplearning4j_tpu.nn.conf.layers.base import FeedForwardLayer
    from deeplearning4j_tpu.nn.conf.layers.convolutional import (
        ConvolutionLayer, SubsamplingLayer, Upsampling2D, ZeroPaddingLayer,
    )
    from deeplearning4j_tpu.nn.conf.layers.recurrent import LSTM, RnnOutputLayer
    from deeplearning4j_tpu.nn.conf.layers.normalization import (
        BatchNormalization, LocalResponseNormalization,
    )

    conv_like = (ConvolutionLayer, SubsamplingLayer, Upsampling2D, ZeroPaddingLayer,
                 LocalResponseNormalization)
    rnn_like = (LSTM, RnnOutputLayer)

    if isinstance(layer, conv_like):
        if prev.kind == "convolutionalflat":
            return FeedForwardToCnnPreProcessor(prev.height, prev.width, prev.channels)
        if prev.kind == "feedforward":
            return None  # cannot infer spatial dims; user must set explicitly
        return None
    if isinstance(layer, rnn_like):
        if prev.kind == "convolutional":
            return CnnToRnnPreProcessor()
        return None
    if isinstance(layer, BatchNormalization):
        return None  # works on both CNN and FF input
    if isinstance(layer, FeedForwardLayer):
        if prev.kind == "convolutional":
            return CnnToFeedForwardPreProcessor(prev.height, prev.width, prev.channels)
        # recurrent input to dense layers: rank-3 tensors broadcast through matmul,
        # no preprocessor needed (TPU-native simplification)
        return None
    return None
