"""MultiLayerConfiguration: serializable sequential-network config.

Reference: nn/conf/MultiLayerConfiguration.java (toYaml:79, toJson:108, fromJson:122).
The JSON form is the checkpoint schema (written into model archives by ModelSerializer)
and must round-trip exactly: to_json(from_json(s)) == s structurally.
"""
from __future__ import annotations

import dataclasses
from typing import Optional

from deeplearning4j_tpu.nn.conf.builders import GlobalConf
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers.base import Layer
from deeplearning4j_tpu.nn.conf import serde


@serde.register_config("MultiLayerConfiguration")
@dataclasses.dataclass
class MultiLayerConfiguration:
    global_conf: GlobalConf = dataclasses.field(default_factory=GlobalConf)
    layers: list = dataclasses.field(default_factory=list)
    preprocessors: dict = dataclasses.field(default_factory=dict)  # str(idx) -> pp
    input_type: Optional[InputType] = None
    backprop: bool = True
    pretrain: bool = False
    backprop_type: str = "Standard"       # Standard | TruncatedBPTT
    tbptt_fwd_length: int = 20
    tbptt_back_length: int = 20

    def to_json(self) -> str:
        return serde.to_json(self)

    def to_yaml(self) -> str:
        return serde.to_yaml(self)

    @staticmethod
    def from_json(s: str) -> "MultiLayerConfiguration":
        conf = serde.from_json(s)
        if not isinstance(conf, MultiLayerConfiguration):
            raise ValueError("JSON does not encode a MultiLayerConfiguration")
        return conf

    @staticmethod
    def from_yaml(s: str) -> "MultiLayerConfiguration":
        conf = serde.from_yaml(s)
        if not isinstance(conf, MultiLayerConfiguration):
            raise ValueError("YAML does not encode a MultiLayerConfiguration")
        return conf

    def preprocessor(self, idx: int):
        return self.preprocessors.get(str(idx))

    @property
    def n_layers(self) -> int:
        return len(self.layers)
