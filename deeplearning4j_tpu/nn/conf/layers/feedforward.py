"""Dense-family layers: Dense, Output, Loss, Activation, Dropout, Embedding,
AutoEncoder, RBM.

Reference impls: nn/layers/feedforward/dense/DenseLayer.java, nn/layers/OutputLayer.java,
nn/layers/feedforward/embedding/EmbeddingLayer.java,
nn/layers/feedforward/autoencoder/AutoEncoder.java, nn/layers/feedforward/rbm/RBM.java.
Forward math is a jnp matmul (MXU) + fused activation; backprop is autodiff.
"""
from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.common import accum_dtype, get_policy
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers.base import FeedForwardLayer, Layer, PretrainLayer
from deeplearning4j_tpu.nn.conf.serde import register_config
from deeplearning4j_tpu.ops.losses import get_loss

Array = jax.Array


def _dense(params: dict, x: Array) -> Array:
    """x @ W + b with the configured MXU compute dtype.

    ``preferred_element_type`` follows the policy's grad_accum_dtype: JAX's
    transpose rule carries it into the dW/dx contractions, pinning wide
    accumulation of the weight gradients without a post-hoc upcast-reduce.
    """
    pol = get_policy()
    w = params["W"].astype(pol.compute_dtype)
    out = jnp.matmul(x.astype(pol.compute_dtype), w,
                     preferred_element_type=accum_dtype(pol.compute_dtype))
    return (out.astype(pol.compute_dtype)
            + params["b"].astype(pol.compute_dtype)).astype(pol.output_dtype)


@register_config("Dense")
@dataclasses.dataclass
class DenseLayer(FeedForwardLayer):
    """Fully-connected layer (reference nn/conf/layers/DenseLayer.java)."""

    def init_params(self, key, itype: InputType) -> dict:
        return {"W": self._init_w(key, (self.n_in, self.n_out)),
                "b": self._init_b((self.n_out,))}

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        x = self.apply_dropout(x, rng, train)
        return self.act_fn()(_dense(params, x)), state


@register_config("Output")
@dataclasses.dataclass
class OutputLayer(FeedForwardLayer):
    """Dense layer + loss function; terminates backprop
    (reference nn/conf/layers/OutputLayer.java, nn/layers/OutputLayer.java)."""

    loss: str = "mcxent"

    def has_loss(self) -> bool:
        return True

    def init_params(self, key, itype: InputType) -> dict:
        return {"W": self._init_w(key, (self.n_in, self.n_out)),
                "b": self._init_b((self.n_out,))}

    def preout(self, params, x):
        return _dense(params, x)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        x = self.apply_dropout(x, rng, train)
        return self.act_fn()(_dense(params, x)), state

    def compute_loss(self, params, x, labels, mask=None) -> Array:
        return get_loss(self.loss)(labels, _dense(params, x), self.act_fn(), mask)


@register_config("Loss")
@dataclasses.dataclass
class LossLayer(Layer):
    """Parameter-free loss layer (reference nn/conf/layers/LossLayer.java)."""

    loss: str = "mcxent"

    def has_loss(self) -> bool:
        return True

    def regularizable_params(self):
        return ()

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        return self.act_fn()(x), state

    def compute_loss(self, params, x, labels, mask=None) -> Array:
        return get_loss(self.loss)(labels, x, self.act_fn(), mask)


@register_config("Activation")
@dataclasses.dataclass
class ActivationLayer(Layer):
    """Standalone activation (reference nn/conf/layers/ActivationLayer.java)."""

    def regularizable_params(self):
        return ()

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        return self.act_fn()(x), state


@register_config("Dropout")
@dataclasses.dataclass
class DropoutLayer(Layer):
    """Standalone dropout (reference nn/conf/layers/DropoutLayer.java).
    ``dropout`` is the retain probability, matching the reference."""

    def regularizable_params(self):
        return ()

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        return self.apply_dropout(x, rng, train), state


@register_config("Embedding")
@dataclasses.dataclass
class EmbeddingLayer(FeedForwardLayer):
    """Index -> vector lookup (reference nn/conf/layers/EmbeddingLayer.java:
    expects integer-index input, mathematically a one-hot matmul but implemented as a
    gather — on TPU a gather from an [vocab, dim] table in HBM)."""

    def init_params(self, key, itype: InputType) -> dict:
        return {"W": self._init_w(key, (self.n_in, self.n_out)),
                "b": self._init_b((self.n_out,))}

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        # one-hot input: rank >= 3 ([B, T, V] sequences), or a floating-point
        # [B, V] matrix — integer-id input is never mistaken for one-hot even
        # when a sequence length coincides with the vocab size
        one_hot = (x.shape[-1] == self.n_in and self.n_in > 1
                   and (x.ndim >= 3
                        or (x.ndim == 2
                            and jnp.issubdtype(x.dtype, jnp.floating))))
        if one_hot:
            idx = jnp.argmax(x, axis=-1).astype(jnp.int32)
        else:
            idx = x.astype(jnp.int32)
            if idx.ndim > 1 and idx.shape[-1] == 1:
                idx = idx[..., 0]
        pol = get_policy()
        emb = (params["W"][idx] + params["b"]).astype(pol.output_dtype)
        return self.act_fn()(emb), state


@register_config("AutoEncoder")
@dataclasses.dataclass
class AutoEncoder(PretrainLayer):
    """Denoising autoencoder (reference nn/layers/feedforward/autoencoder/AutoEncoder.java):
    encode = act(xW+b), decode = act(hW^T+vb); pretrain objective = reconstruction loss
    on corrupted input (corruption_level = probability an input unit is zeroed)."""

    corruption_level: float = 0.3
    sparsity: float = 0.0
    pretrain_loss_fn: str = "mse"

    def init_params(self, key, itype: InputType) -> dict:
        k1, _ = jax.random.split(key)
        return {"W": self._init_w(k1, (self.n_in, self.n_out)),
                "b": self._init_b((self.n_out,)),
                "vb": self._init_b((self.n_in,))}

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        x = self.apply_dropout(x, rng, train)
        return self.act_fn()(_dense(params, x)), state

    def encode(self, params, x):
        return self.act_fn()(_dense(params, x))

    def decode(self, params, h):
        return self.act_fn()(jnp.matmul(h, params["W"].T) + params["vb"])

    def pretrain_loss(self, params, x, *, rng):
        if self.corruption_level > 0:
            keep = jax.random.bernoulli(rng, 1.0 - self.corruption_level, x.shape)
            corrupted = jnp.where(keep, x, 0.0)
        else:
            corrupted = x
        recon = self.decode(params, self.encode(params, corrupted))
        loss = get_loss(self.pretrain_loss_fn)(x, recon, lambda v: v, None)
        if self.sparsity > 0:
            h_mean = jnp.mean(self.encode(params, x), axis=0)
            rho = self.sparsity
            h_c = jnp.clip(h_mean, 1e-7, 1 - 1e-7)
            loss = loss + jnp.sum(rho * jnp.log(rho / h_c)
                                  + (1 - rho) * jnp.log((1 - rho) / (1 - h_c)))
        return loss


@register_config("RBM")
@dataclasses.dataclass
class RBM(PretrainLayer):
    """Restricted Boltzmann machine trained by CD-k
    (reference nn/layers/feedforward/rbm/RBM.java, 501 LoC: gibbhVh, contrastive
    divergence in computeGradientAndScore). Supervised forward = propUp.

    The CD gradient is not a true autodiff gradient; pretraining computes the CD-k
    parameter deltas directly (positive phase minus negative phase), expressed as a
    surrogate loss whose autodiff gradient equals the CD update so the standard
    pretrain machinery applies.
    """

    k: int = 1
    visible_unit: str = "binary"   # binary | gaussian
    hidden_unit: str = "binary"

    def init_params(self, key, itype: InputType) -> dict:
        return {"W": self._init_w(key, (self.n_in, self.n_out)),
                "b": self._init_b((self.n_out,)),     # hidden bias
                "vb": self._init_b((self.n_in,))}     # visible bias

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        x = self.apply_dropout(x, rng, train)
        return self.act_fn()(_dense(params, x)), state

    def prop_up(self, params, v):
        return jax.nn.sigmoid(jnp.matmul(v, params["W"]) + params["b"])

    def prop_down(self, params, h):
        pre = jnp.matmul(h, params["W"].T) + params["vb"]
        return pre if self.visible_unit == "gaussian" else jax.nn.sigmoid(pre)

    def pretrain_loss(self, params, x, *, rng):
        def sample(key, p):
            return jax.random.bernoulli(key, p).astype(p.dtype)

        keys = jax.random.split(rng, 2 * self.k + 1)
        ph = self.prop_up(params, x)
        # Gibbs chain, gradients stopped (CD treats the chain as data)
        vk = x
        hk = sample(keys[0], ph)
        for i in range(self.k):
            vk = self.prop_down(params, hk)
            if self.visible_unit == "binary":
                vk = sample(keys[2 * i + 1], vk)
            hk_prob = self.prop_up(params, vk)
            hk = sample(keys[2 * i + 2], hk_prob) if i < self.k - 1 else hk_prob
        vk = jax.lax.stop_gradient(vk)
        hk = jax.lax.stop_gradient(hk)
        ph_d = jax.lax.stop_gradient(ph)
        n = x.shape[0]
        # Surrogate whose gradient wrt params is the negative CD update:
        #   dW = <v+ h+> - <v- h->, dvb = <v+> - <v->, db = <h+> - <h->
        w_term = (jnp.sum(jnp.matmul(x.T, ph_d) * params["W"])
                  - jnp.sum(jnp.matmul(vk.T, hk) * params["W"])) / n
        vb_term = jnp.sum((jnp.mean(x, 0) - jnp.mean(vk, 0)) * params["vb"])
        b_term = jnp.sum((jnp.mean(ph_d, 0) - jnp.mean(hk, 0)) * params["b"])
        return -(w_term + vb_term + b_term)
