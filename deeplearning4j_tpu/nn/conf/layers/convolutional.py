"""Convolution / pooling layers.

Reference: nn/conf/layers/ConvolutionLayer.java + nn/layers/convolution/ConvolutionLayer.java
(im2col+gemm at :172-215) and SubsamplingLayer. TPU-native: no im2col — XLA's
``lax.conv_general_dilated`` maps convs straight onto the MXU, and pooling is
``lax.reduce_window``; this single choice replaces both the reference's built-in path and
its cuDNN helper seam (deeplearning4j-cuda CudnnConvolutionHelper.java:49), since XLA:TPU
*is* the accelerated backend.

Layout: NHWC activations, HWIO kernels (XLA:TPU preferred). ConvolutionMode parity:
'truncate'/'strict' -> VALID, 'same' -> SAME (reference nn/conf/ConvolutionMode.java).
"""
from __future__ import annotations

import dataclasses
import functools
from typing import Sequence

import jax
import jax.numpy as jnp
from jax import lax

from deeplearning4j_tpu.common import accum_dtype, get_policy
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers.base import Layer
from deeplearning4j_tpu.nn.conf.serde import register_config

Array = jax.Array


def _pair(v) -> tuple[int, int]:
    if isinstance(v, (list, tuple)):
        return (int(v[0]), int(v[1]))
    return (int(v), int(v))


def _out_dim(size: int, k: int, s: int, p: int, mode: str) -> int:
    if mode == "same":
        return -(-size // s)  # ceil
    return (size + 2 * p - k) // s + 1


def _padding_config(mode: str, pad: tuple[int, int]):
    if mode == "same":
        return "SAME"
    return ((pad[0], pad[0]), (pad[1], pad[1]))


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4, 5, 6))
def _conv_wide(x, w, strides, padding, rhs_dilation, compute, accum):
    """Conv with policy-routed wide accumulation: compute-dtype operands on
    the MXU, ``preferred_element_type=accum`` output. A custom vjp because
    the builtin conv transpose rule feeds the wide cotangent straight back
    into ``conv_general_dilated`` against a compute-dtype operand, and conv
    (unlike dot_general) rejects mixed operand dtypes. The gradient convs
    instead run with both operands upcast to ``accum`` — on TPU an f32
    conv at DEFAULT precision lowers to the same bf16-multiply /
    f32-accumulate MXU passes, so the weight gradient still accumulates
    wide without a post-hoc upcast-reduce."""
    return lax.conv_general_dilated(
        x.astype(compute), w.astype(compute), window_strides=strides,
        padding=padding, rhs_dilation=rhs_dilation,
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        preferred_element_type=accum)


def _conv_wide_fwd(x, w, strides, padding, rhs_dilation, compute, accum):
    return _conv_wide(x, w, strides, padding, rhs_dilation, compute,
                      accum), (x, w)


def _conv_wide_bwd(strides, padding, rhs_dilation, compute, accum, res, g):
    x, w = res

    def conv(xa, wa):
        return lax.conv_general_dilated(
            xa, wa, window_strides=strides, padding=padding,
            rhs_dilation=rhs_dilation,
            dimension_numbers=("NHWC", "HWIO", "NHWC"))

    _, vjp = jax.vjp(conv, x.astype(accum), w.astype(accum))
    dx, dw = vjp(g.astype(accum))
    return dx.astype(x.dtype), dw.astype(w.dtype)


_conv_wide.defvjp(_conv_wide_fwd, _conv_wide_bwd)


@register_config("Convolution")
@dataclasses.dataclass
class ConvolutionLayer(Layer):
    """2-D convolution. n_in = input channels (auto-inferred), n_out = filters."""

    n_in: int = 0
    n_out: int = 0
    kernel_size: Sequence[int] = (5, 5)
    stride: Sequence[int] = (1, 1)
    padding: Sequence[int] = (0, 0)
    dilation: Sequence[int] = (1, 1)
    convolution_mode: str = "truncate"  # truncate | strict | same
    has_bias: bool = True

    def set_n_in(self, itype: InputType) -> None:
        if not self.n_in:
            if itype.kind not in ("convolutional", "convolutionalflat"):
                raise ValueError(f"ConvolutionLayer needs convolutional input, got {itype.kind}")
            self.n_in = itype.channels

    def init_params(self, key, itype: InputType) -> dict:
        kh, kw = _pair(self.kernel_size)
        params = {"W": self._init_w(key, (kh, kw, self.n_in, self.n_out))}
        if self.has_bias:
            params["b"] = self._init_b((self.n_out,))
        return params

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        x = self.apply_dropout(x, rng, train)
        pol = get_policy()
        kh, kw = _pair(self.kernel_size)
        mode = self.convolution_mode.lower()
        accum = accum_dtype(pol.compute_dtype)
        if accum is None:
            out = lax.conv_general_dilated(
                x.astype(pol.compute_dtype),
                params["W"].astype(pol.compute_dtype),
                window_strides=_pair(self.stride),
                padding=_padding_config(mode, _pair(self.padding)),
                rhs_dilation=_pair(self.dilation),
                dimension_numbers=("NHWC", "HWIO", "NHWC"),
            ).astype(pol.output_dtype)
        else:
            # policy-routed wide accumulation; raw (uncast) params in so the
            # weight cotangent stays in accum dtype end-to-end (see _conv_wide)
            out = _conv_wide(
                x, params["W"], _pair(self.stride),
                _padding_config(mode, _pair(self.padding)),
                _pair(self.dilation), pol.compute_dtype, accum,
            ).astype(pol.output_dtype)
        if self.has_bias:
            out = out + params["b"].astype(out.dtype)
        return self.act_fn()(out), state

    def output_type(self, itype: InputType) -> InputType:
        kh, kw = _pair(self.kernel_size)
        sh, sw = _pair(self.stride)
        ph, pw = _pair(self.padding)
        dh, dw = _pair(self.dilation)
        mode = self.convolution_mode.lower()
        # effective kernel size under dilation, matching XLA's rhs_dilation
        h = _out_dim(itype.height, (kh - 1) * dh + 1, sh, ph, mode)
        w = _out_dim(itype.width, (kw - 1) * dw + 1, sw, pw, mode)
        return InputType.convolutional(h, w, self.n_out)


@register_config("Subsampling")
@dataclasses.dataclass
class SubsamplingLayer(Layer):
    """Pooling: max | avg | sum | pnorm (reference nn/conf/layers/SubsamplingLayer.java,
    PoolingType). lax.reduce_window on TPU."""

    pooling_type: str = "max"
    kernel_size: Sequence[int] = (2, 2)
    stride: Sequence[int] = (2, 2)
    padding: Sequence[int] = (0, 0)
    convolution_mode: str = "truncate"
    pnorm: int = 2

    def regularizable_params(self):
        return ()

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        kh, kw = _pair(self.kernel_size)
        sh, sw = _pair(self.stride)
        ph, pw = _pair(self.padding)
        mode = self.convolution_mode.lower()
        window = (1, kh, kw, 1)
        strides = (1, sh, sw, 1)
        if mode == "same":
            padding = "SAME"
        else:
            padding = ((0, 0), (ph, ph), (pw, pw), (0, 0))
        ptype = self.pooling_type.lower()
        if ptype == "max":
            out = lax.reduce_window(x, -jnp.inf, lax.max, window, strides, padding)
        elif ptype in ("avg", "average"):
            s = lax.reduce_window(x, 0.0, lax.add, window, strides, padding)
            out = s / (kh * kw)
        elif ptype == "sum":
            out = lax.reduce_window(x, 0.0, lax.add, window, strides, padding)
        elif ptype == "pnorm":
            p = float(self.pnorm)
            s = lax.reduce_window(jnp.abs(x) ** p, 0.0, lax.add, window, strides, padding)
            out = s ** (1.0 / p)
        else:
            raise ValueError(f"Unknown pooling type '{self.pooling_type}'")
        return out, state

    def output_type(self, itype: InputType) -> InputType:
        kh, kw = _pair(self.kernel_size)
        sh, sw = _pair(self.stride)
        ph, pw = _pair(self.padding)
        mode = self.convolution_mode.lower()
        h = _out_dim(itype.height, kh, sh, ph, mode)
        w = _out_dim(itype.width, kw, sw, pw, mode)
        return InputType.convolutional(h, w, itype.channels)


@register_config("Upsampling2D")
@dataclasses.dataclass
class Upsampling2D(Layer):
    """Nearest-neighbor upsampling (capability parity for Keras import)."""

    size: Sequence[int] = (2, 2)

    def regularizable_params(self):
        return ()

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        sh, sw = _pair(self.size)
        out = jnp.repeat(jnp.repeat(x, sh, axis=1), sw, axis=2)
        return out, state

    def output_type(self, itype: InputType) -> InputType:
        sh, sw = _pair(self.size)
        return InputType.convolutional(itype.height * sh, itype.width * sw, itype.channels)


@register_config("ZeroPadding")
@dataclasses.dataclass
class ZeroPaddingLayer(Layer):
    """Explicit spatial zero padding (Keras ZeroPadding2D parity)."""

    padding: Sequence[int] = (1, 1)

    def regularizable_params(self):
        return ()

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        ph, pw = _pair(self.padding)
        out = jnp.pad(x, ((0, 0), (ph, ph), (pw, pw), (0, 0)))
        return out, state

    def output_type(self, itype: InputType) -> InputType:
        ph, pw = _pair(self.padding)
        return InputType.convolutional(itype.height + 2 * ph, itype.width + 2 * pw,
                                       itype.channels)


@register_config("GlobalPooling")
@dataclasses.dataclass
class GlobalPoolingLayer(Layer):
    """Global spatial/temporal pooling: CNN [B,H,W,C]->[B,C]; RNN [B,T,F]->[B,F]
    (reference nn/conf/layers/GlobalPoolingLayer in later versions; included for
    ResNet-style heads). Honors time-series masks for RNN input."""

    pooling_type: str = "avg"

    def regularizable_params(self):
        return ()

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        axes = tuple(range(1, x.ndim - 1))
        ptype = self.pooling_type.lower()
        if mask is not None and x.ndim == 3:
            m = mask.astype(x.dtype)[..., None]
            if ptype in ("avg", "average"):
                out = jnp.sum(x * m, axis=1) / jnp.maximum(jnp.sum(m, axis=1), 1.0)
            elif ptype == "max":
                out = jnp.max(jnp.where(m > 0, x, -jnp.inf), axis=1)
            else:
                out = jnp.sum(x * m, axis=1)
        elif ptype in ("avg", "average"):
            out = jnp.mean(x, axis=axes)
        elif ptype == "max":
            out = jnp.max(x, axis=axes)
        elif ptype == "sum":
            out = jnp.sum(x, axis=axes)
        else:
            raise ValueError(f"Unknown pooling type '{self.pooling_type}'")
        return out, state

    def output_type(self, itype: InputType) -> InputType:
        if itype.kind == "convolutional":
            return InputType.feed_forward(itype.channels)
        return InputType.feed_forward(itype.size)
