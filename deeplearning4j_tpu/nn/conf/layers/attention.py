"""Attention and transformer layers (TPU-native additions).

The reference's sequence modeling stops at LSTM + truncated BPTT (SURVEY.md
§5); long-context attention is a required first-class TPU capability here.
These layers ride the accelerated seam: ``flash_attention`` (Pallas tiled
kernel on TPU, identical XLA math elsewhere — ops/pallas_kernels.py), and
under a sequence-parallel mesh the same math runs as ring or Ulysses
attention (parallel/ring_attention.py).

Layout: [batch, time, features] like the recurrent layers.
"""
from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.common import accum_dtype, at_least_f32, get_policy
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers.base import FeedForwardLayer
from deeplearning4j_tpu.nn.conf.serde import register_config

Array = jax.Array


def attend(q: Array, k: Array, v: Array, causal: bool, mask=None) -> Array:
    """The ONE attention-core dispatch every attention-bearing layer uses.

    Single device (no active ParallelContext): flash_attention (Pallas on
    TPU) or masked_attention. Under a trainer-published sequence-parallel
    context (parallel/context.py) the same math runs distributed over the
    mesh's sequence axis — Ulysses all_to_all by default, ring ppermute on
    request — so a plain ``transformer_lm`` config becomes long-context
    sequence-parallel through fit() alone, the way reference
    ParallelWrapper.java:44 wraps any net without touching model code.
    Masked (variable-length) batches fall back to the dense masked kernel:
    correctness over parallelism, mirroring ParallelWrapper's own fallback
    for semantics its sharded step doesn't cover.
    """
    from deeplearning4j_tpu.ops.pallas_kernels import (
        flash_attention, masked_attention,
    )
    from deeplearning4j_tpu.parallel import context as pctx

    ctx = pctx.current()
    if ctx is not None and ctx.seq_axis is not None and mask is None:
        from deeplearning4j_tpu.parallel.ring_attention import (
            ring_attention_sharded, ulysses_attention_sharded)
        if ctx.seq_mode == "ring":
            return ring_attention_sharded(q, k, v, ctx.mesh, ctx.seq_axis,
                                          causal, batch_axis=ctx.data_axis)
        return ulysses_attention_sharded(q, k, v, ctx.mesh, ctx.seq_axis,
                                         causal, ctx.interpret,
                                         batch_axis=ctx.data_axis)
    if mask is not None:
        return masked_attention(q, k, v, mask, causal)
    return flash_attention(q, k, v, causal)


@register_config("SelfAttention")
@dataclasses.dataclass
class SelfAttentionLayer(FeedForwardLayer):
    """Multi-head self-attention with fused QKV projection.

    n_out is the model width; params: "Wqkv" [F, 3F] fused projection (one
    MXU matmul), "Wo" [F, F], "b" [F]. The attention core is flash_attention.
    """

    n_heads: int = 4
    causal: bool = False

    def set_n_in(self, itype: InputType) -> None:
        if not self.n_in:
            self.n_in = itype.size if itype.kind == "recurrent" else itype.flat_size()
        if not self.n_out:
            self.n_out = self.n_in

    def init_params(self, key, itype: InputType) -> dict:
        if self.n_out % self.n_heads:
            raise ValueError(f"n_out {self.n_out} not divisible by "
                             f"n_heads {self.n_heads}")
        k1, k2 = jax.random.split(key)
        return {"Wqkv": self._init_w(k1, (self.n_in, 3 * self.n_out)),
                "Wo": self._init_w(k2, (self.n_out, self.n_out)),
                "b": self._init_b((self.n_out,))}

    def regularizable_params(self):
        return ("Wqkv", "Wo")

    def output_type(self, itype: InputType) -> InputType:
        return InputType.recurrent(self.n_out, itype.timesteps)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        pol = get_policy()
        x = self.apply_dropout(x, rng, train)
        B, T, _ = x.shape
        H = self.n_heads
        D = self.n_out // H
        qkv = jnp.matmul(x.astype(pol.compute_dtype),
                         params["Wqkv"].astype(pol.compute_dtype),
                         preferred_element_type=accum_dtype(pol.compute_dtype))
        q, k, v = jnp.split(qkv.astype(pol.output_dtype), 3, axis=-1)
        q = q.reshape(B, T, H, D)
        k = k.reshape(B, T, H, D)
        v = v.reshape(B, T, H, D)
        o = attend(q, k, v, self.causal, mask)
        o = o.reshape(B, T, self.n_out)
        out = jnp.matmul(o.astype(pol.compute_dtype),
                         params["Wo"].astype(pol.compute_dtype),
                         preferred_element_type=accum_dtype(pol.compute_dtype))
        out = out.astype(pol.output_dtype) + params["b"].astype(pol.output_dtype)
        return self.act_fn()(out), state


@register_config("TransformerBlock")
@dataclasses.dataclass
class TransformerBlock(FeedForwardLayer):
    """Pre-LN transformer block: LN -> MHA -> residual, LN -> MLP -> residual.

    Homogeneous width (n_in == n_out == model width) so blocks stack and can
    be pipeline-parallelized as identical stages (parallel/pipeline.py).
    Params: ln1/ln2 scales+biases, attention Wqkv/Wo/bo, MLP W1/b1/W2/b2.
    """

    n_heads: int = 4
    ffn_multiplier: int = 4
    causal: bool = True

    def set_n_in(self, itype: InputType) -> None:
        if not self.n_in:
            self.n_in = itype.size if itype.kind == "recurrent" else itype.flat_size()
        if not self.n_out:
            self.n_out = self.n_in

    def init_params(self, key, itype: InputType) -> dict:
        F = self.n_out
        if F % self.n_heads:
            raise ValueError(f"width {F} not divisible by heads {self.n_heads}")
        ks = jax.random.split(key, 4)
        hidden = self.ffn_multiplier * F
        return {
            "ln1_g": jnp.ones((F,), jnp.float32),
            "ln1_b": jnp.zeros((F,), jnp.float32),
            "Wqkv": self._init_w(ks[0], (F, 3 * F)),
            "Wo": self._init_w(ks[1], (F, F)),
            "bo": jnp.zeros((F,), jnp.float32),
            "ln2_g": jnp.ones((F,), jnp.float32),
            "ln2_b": jnp.zeros((F,), jnp.float32),
            "W1": self._init_w(ks[2], (F, hidden)),
            "b1": jnp.zeros((hidden,), jnp.float32),
            "W2": self._init_w(ks[3], (hidden, F)),
            "b2": jnp.zeros((F,), jnp.float32),
        }

    def regularizable_params(self):
        return ("Wqkv", "Wo", "W1", "W2")

    def output_type(self, itype: InputType) -> InputType:
        return InputType.recurrent(self.n_out, itype.timesteps)

    @staticmethod
    def _ln(x, g, b, eps=1e-5):
        # statistics in at least float32 even when activations flow as bf16
        xf = x.astype(at_least_f32(x.dtype))
        mu = jnp.mean(xf, axis=-1, keepdims=True)
        var = jnp.var(xf, axis=-1, keepdims=True)
        xhat = ((xf - mu) * jax.lax.rsqrt(var + eps)).astype(x.dtype)
        return xhat * g.astype(x.dtype) + b.astype(x.dtype)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        pol = get_policy()
        B, T, F = x.shape
        H = self.n_heads
        D = F // H
        h = self._ln(x, params["ln1_g"], params["ln1_b"])
        qkv = jnp.matmul(h.astype(pol.compute_dtype),
                         params["Wqkv"].astype(pol.compute_dtype),
                         preferred_element_type=accum_dtype(pol.compute_dtype))
        q, k, v = jnp.split(qkv.astype(pol.output_dtype), 3, axis=-1)
        q = q.reshape(B, T, H, D)
        k = k.reshape(B, T, H, D)
        v = v.reshape(B, T, H, D)
        # padded keys must not absorb softmax mass (LN/MLP are per-token on
        # the last axis, so attention is the only cross-token leak); attend
        # also dispatches sequence-parallel under an active ParallelContext
        o = attend(q, k, v, self.causal, mask)
        o = o.reshape(B, T, F)
        att = jnp.matmul(o.astype(pol.compute_dtype),
                         params["Wo"].astype(pol.compute_dtype),
                         preferred_element_type=accum_dtype(pol.compute_dtype))
        x = x + att.astype(pol.output_dtype) + params["bo"].astype(pol.output_dtype)
        h = self._ln(x, params["ln2_g"], params["ln2_b"])
        h = jnp.matmul(h.astype(pol.compute_dtype),
                       params["W1"].astype(pol.compute_dtype),
                       preferred_element_type=accum_dtype(pol.compute_dtype))
        h = jax.nn.gelu(h.astype(pol.output_dtype) + params["b1"].astype(pol.output_dtype))
        h = self.apply_dropout(h, rng, train)
        h = jnp.matmul(h.astype(pol.compute_dtype),
                       params["W2"].astype(pol.compute_dtype),
                       preferred_element_type=accum_dtype(pol.compute_dtype))
        return x + h.astype(pol.output_dtype) + params["b2"].astype(pol.output_dtype), state
