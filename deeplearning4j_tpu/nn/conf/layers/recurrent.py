"""Recurrent layers: LSTM (Graves variant with peepholes), bidirectional LSTM,
RnnOutputLayer.

Reference: nn/layers/recurrent/LSTMHelpers.java (activateHelper:58,
backpropGradientHelper:248 — hand-written BPTT) and GravesLSTM/GravesBidirectionalLSTM
configs. TPU-native: the time recursion runs through the three-variant recurrent
engine in ``ops/lstm.py`` (fused scan / Pallas persistent cell / reference scan,
selected by ``DL4J_LSTM_IMPL`` + calibrated thresholds at trace time);
backprop-through-time is autodiff through the scan body or the kernel's custom
VJP — this *is* the accelerated LSTM path the cuDNN-helper seam
(CudnnLSTMHelper) would otherwise provide (SURVEY.md §2.3 note).

Layout: [batch, time, features] (reference uses [batch, features, time]).
Param names: "W" [n_in,4H] input weights, "RW" [H,4H] recurrent, "b" [4H],
"pI"/"pF"/"pO" [H] peepholes (Graves 2013). Gate order: input, forget, cell(g), output.
State pytree carries the streaming-inference hidden state for rnn_time_step
(reference rnnTimeStep:2196 stateMap) — functional instead of mutable.
"""
from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers.base import FeedForwardLayer
from deeplearning4j_tpu.nn.conf.layers.feedforward import _dense
from deeplearning4j_tpu.nn.conf.serde import register_config
from deeplearning4j_tpu.ops.losses import get_loss
from deeplearning4j_tpu.ops.lstm import lstm_sequence
# back-compat alias: the scan implementation (now the engine's reference
# oracle) used to live here
from deeplearning4j_tpu.ops.lstm import lstm_scan as _lstm_scan  # noqa: F401

Array = jax.Array


@register_config("LSTM")
@dataclasses.dataclass
class LSTM(FeedForwardLayer):
    """Standard LSTM (no peepholes)."""

    forget_gate_bias_init: float = 1.0
    gate_activation: str = "sigmoid"
    peephole: bool = False

    def set_n_in(self, itype: InputType) -> None:
        if not self.n_in:
            self.n_in = itype.size if itype.kind == "recurrent" else itype.flat_size()

    def init_params(self, key, itype: InputType) -> dict:
        k1, k2 = jax.random.split(key)
        h = self.n_out
        b = jnp.zeros((4 * h,), jnp.float32)
        b = b.at[h:2 * h].set(self.forget_gate_bias_init)
        params = {"W": self._init_w(k1, (self.n_in, 4 * h)),
                  "RW": self._init_w(k2, (h, 4 * h)),
                  "b": b}
        if self.peephole:
            params["pI"] = jnp.zeros((h,), jnp.float32)
            params["pF"] = jnp.zeros((h,), jnp.float32)
            params["pO"] = jnp.zeros((h,), jnp.float32)
        return params

    def regularizable_params(self):
        return ("W", "RW")

    def output_type(self, itype: InputType) -> InputType:
        return InputType.recurrent(self.n_out, itype.timesteps)

    def _acts(self):
        from deeplearning4j_tpu.ops.activations import get_activation
        return get_activation(self.activation or "tanh"), get_activation(self.gate_activation)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        # Training/inference over full sequences starts from zero state each batch
        # (reference LSTMHelpers.activateHelper); streaming state is apply_streaming.
        x = self.apply_dropout(x, rng, train)
        act, gate = self._acts()
        B = x.shape[0]
        zeros = jnp.zeros((B, self.n_out), x.dtype)
        ys, _ = lstm_sequence(params, x, act, gate, zeros, zeros,
                              self.peephole, mask,
                              act_name=self.activation or "tanh",
                              gate_name=self.gate_activation)
        return ys, state

    def apply_streaming(self, params, state, x, *, mask=None):
        """rnnTimeStep equivalent: carry (h,c) across calls (reference
        MultiLayerNetwork.rnnTimeStep:2196). Routed through the same engine
        as full sequences, so serving single steps take the fused cell and a
        T-step rnnTimeStep loop reproduces the fused-scan forward bitwise."""
        act, gate = self._acts()
        B = x.shape[0]
        h0 = state.get("h", jnp.zeros((B, self.n_out), x.dtype))
        c0 = state.get("c", jnp.zeros((B, self.n_out), x.dtype))
        ys, (h, c) = lstm_sequence(params, x, act, gate, h0, c0,
                                   self.peephole, mask,
                                   act_name=self.activation or "tanh",
                                   gate_name=self.gate_activation)
        return ys, {"h": h, "c": c}


@register_config("GravesLSTM")
@dataclasses.dataclass
class GravesLSTM(LSTM):
    """LSTM with peephole connections (Graves 2013; reference GravesLSTM.java)."""

    peephole: bool = True


@register_config("GravesBidirectionalLSTM")
@dataclasses.dataclass
class GravesBidirectionalLSTM(LSTM):
    """Bidirectional Graves LSTM (reference GravesBidirectionalLSTM.java). Output is the
    SUM of forward and backward passes, matching the reference's ADD mode."""

    peephole: bool = True

    def init_params(self, key, itype: InputType) -> dict:
        kf, kb = jax.random.split(key)
        fwd = LSTM.init_params(self, kf, itype)
        bwd = LSTM.init_params(self, kb, itype)
        return ({f"F{k}": v for k, v in fwd.items()}
                | {f"B{k}": v for k, v in bwd.items()})

    def regularizable_params(self):
        return ("FW", "FRW", "BW", "BRW")

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        x = self.apply_dropout(x, rng, train)
        act, gate = self._acts()
        B = x.shape[0]
        zeros = jnp.zeros((B, self.n_out), x.dtype)
        fwd_p = {k[1:]: v for k, v in params.items() if k.startswith("F")}
        bwd_p = {k[1:]: v for k, v in params.items() if k.startswith("B")}
        names = dict(act_name=self.activation or "tanh",
                     gate_name=self.gate_activation)
        ys_f, _ = lstm_sequence(fwd_p, x, act, gate, zeros, zeros,
                                self.peephole, mask, **names)
        x_rev = jnp.flip(x, axis=1)
        mask_rev = jnp.flip(mask, axis=1) if mask is not None else None
        ys_b, _ = lstm_sequence(bwd_p, x_rev, act, gate, zeros, zeros,
                                self.peephole, mask_rev, **names)
        return ys_f + jnp.flip(ys_b, axis=1), state


@register_config("RnnOutput")
@dataclasses.dataclass
class RnnOutputLayer(FeedForwardLayer):
    """Time-distributed output layer with loss (reference nn/conf/layers/RnnOutputLayer.java):
    dense applied at every timestep of [B,T,F], loss masked by the time-series mask."""

    loss: str = "mcxent"

    def has_loss(self) -> bool:
        return True

    def set_n_in(self, itype: InputType) -> None:
        if not self.n_in:
            self.n_in = itype.size if itype.kind == "recurrent" else itype.flat_size()

    def init_params(self, key, itype: InputType) -> dict:
        return {"W": self._init_w(key, (self.n_in, self.n_out)),
                "b": self._init_b((self.n_out,))}

    def output_type(self, itype: InputType) -> InputType:
        return InputType.recurrent(self.n_out, itype.timesteps)

    def preout(self, params, x):
        return _dense(params, x)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        x = self.apply_dropout(x, rng, train)
        return self.act_fn()(_dense(params, x)), state

    def compute_loss(self, params, x, labels, mask=None) -> Array:
        return get_loss(self.loss)(labels, _dense(params, x), self.act_fn(), mask)
