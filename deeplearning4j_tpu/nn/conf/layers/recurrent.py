"""Recurrent layers: LSTM (Graves variant with peepholes), bidirectional LSTM,
RnnOutputLayer.

Reference: nn/layers/recurrent/LSTMHelpers.java (activateHelper:58,
backpropGradientHelper:248 — hand-written BPTT) and GravesLSTM/GravesBidirectionalLSTM
configs. TPU-native: the time recursion is a ``lax.scan`` whose body is one fused
[B, n_in+H] x [n_in+H, 4H] matmul on the MXU; backprop-through-time is autodiff through
the scan (XLA generates the reverse scan) — this *is* the accelerated LSTM path the
cuDNN-helper seam would otherwise provide (SURVEY.md §2.3 note).

Layout: [batch, time, features] (reference uses [batch, features, time]).
Param names: "W" [n_in,4H] input weights, "RW" [H,4H] recurrent, "b" [4H],
"pI"/"pF"/"pO" [H] peepholes (Graves 2013). Gate order: input, forget, cell(g), output.
State pytree carries the streaming-inference hidden state for rnn_time_step
(reference rnnTimeStep:2196 stateMap) — functional instead of mutable.
"""
from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from deeplearning4j_tpu.common import accum_dtype, get_policy
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers.base import FeedForwardLayer
from deeplearning4j_tpu.nn.conf.layers.feedforward import _dense
from deeplearning4j_tpu.nn.conf.serde import register_config
from deeplearning4j_tpu.ops.losses import get_loss

Array = jax.Array


def _lstm_scan(params: dict, x: Array, act, gate_act, h0: Array, c0: Array,
               peephole: bool, mask: Optional[Array]):
    """Run the LSTM over time with lax.scan. x: [B,T,F]. Returns (outputs [B,T,H], (h,c))."""
    pol = get_policy()
    w = params["W"].astype(pol.compute_dtype)
    rw = params["RW"].astype(pol.compute_dtype)
    b = params["b"].astype(pol.compute_dtype)
    hidden = rw.shape[0]

    # Precompute input contributions for all timesteps in one big MXU matmul:
    # [B,T,4H]. preferred_element_type routes the dW contraction through the
    # policy's grad-accum dtype; cast straight back so the scan carry dtype
    # below never changes.
    xw = jnp.einsum("btf,fg->btg", x.astype(pol.compute_dtype), w,
                    preferred_element_type=accum_dtype(pol.compute_dtype)
                    ).astype(pol.compute_dtype) + b

    def step(carry, inputs):
        h, c = carry
        xw_t, m_t = inputs
        z = xw_t + jnp.matmul(h.astype(pol.compute_dtype), rw)
        zi, zf, zg, zo = jnp.split(z.astype(pol.output_dtype), 4, axis=-1)
        if peephole:
            # cast peephole params to the gate dtype: a silent bf16*f32
            # promotion here would flip the scan carry dtype mid-trace
            zi = zi + c * params["pI"].astype(zi.dtype)
            zf = zf + c * params["pF"].astype(zf.dtype)
        i = gate_act(zi)
        f = gate_act(zf)
        g = act(zg)
        c_new = f * c + i * g
        if peephole:
            zo = zo + c_new * params["pO"].astype(zo.dtype)
        o = gate_act(zo)
        h_new = o * act(c_new)
        if m_t is not None:
            m = m_t[:, None]
            h_new = jnp.where(m > 0, h_new, h)
            c_new = jnp.where(m > 0, c_new, c)
        return (h_new, c_new), h_new

    xw_t = jnp.moveaxis(xw, 1, 0)  # [T,B,4H]
    mask_t = jnp.moveaxis(mask, 1, 0) if mask is not None else None
    if mask_t is None:
        (h, c), ys = lax.scan(lambda cr, xi: step(cr, (xi, None)), (h0, c0), xw_t)
    else:
        (h, c), ys = lax.scan(step, (h0, c0), (xw_t, mask_t))
    return jnp.moveaxis(ys, 0, 1), (h, c)


@register_config("LSTM")
@dataclasses.dataclass
class LSTM(FeedForwardLayer):
    """Standard LSTM (no peepholes)."""

    forget_gate_bias_init: float = 1.0
    gate_activation: str = "sigmoid"
    peephole: bool = False

    def set_n_in(self, itype: InputType) -> None:
        if not self.n_in:
            self.n_in = itype.size if itype.kind == "recurrent" else itype.flat_size()

    def init_params(self, key, itype: InputType) -> dict:
        k1, k2 = jax.random.split(key)
        h = self.n_out
        b = jnp.zeros((4 * h,), jnp.float32)
        b = b.at[h:2 * h].set(self.forget_gate_bias_init)
        params = {"W": self._init_w(k1, (self.n_in, 4 * h)),
                  "RW": self._init_w(k2, (h, 4 * h)),
                  "b": b}
        if self.peephole:
            params["pI"] = jnp.zeros((h,), jnp.float32)
            params["pF"] = jnp.zeros((h,), jnp.float32)
            params["pO"] = jnp.zeros((h,), jnp.float32)
        return params

    def regularizable_params(self):
        return ("W", "RW")

    def output_type(self, itype: InputType) -> InputType:
        return InputType.recurrent(self.n_out, itype.timesteps)

    def _acts(self):
        from deeplearning4j_tpu.ops.activations import get_activation
        return get_activation(self.activation or "tanh"), get_activation(self.gate_activation)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        # Training/inference over full sequences starts from zero state each batch
        # (reference LSTMHelpers.activateHelper); streaming state is apply_streaming.
        x = self.apply_dropout(x, rng, train)
        act, gate = self._acts()
        B = x.shape[0]
        zeros = jnp.zeros((B, self.n_out), x.dtype)
        ys, _ = _lstm_scan(params, x, act, gate, zeros, zeros, self.peephole, mask)
        return ys, state

    def apply_streaming(self, params, state, x, *, mask=None):
        """rnnTimeStep equivalent: carry (h,c) across calls (reference
        MultiLayerNetwork.rnnTimeStep:2196)."""
        act, gate = self._acts()
        B = x.shape[0]
        h0 = state.get("h", jnp.zeros((B, self.n_out), x.dtype))
        c0 = state.get("c", jnp.zeros((B, self.n_out), x.dtype))
        ys, (h, c) = _lstm_scan(params, x, act, gate, h0, c0, self.peephole, mask)
        return ys, {"h": h, "c": c}


@register_config("GravesLSTM")
@dataclasses.dataclass
class GravesLSTM(LSTM):
    """LSTM with peephole connections (Graves 2013; reference GravesLSTM.java)."""

    peephole: bool = True


@register_config("GravesBidirectionalLSTM")
@dataclasses.dataclass
class GravesBidirectionalLSTM(LSTM):
    """Bidirectional Graves LSTM (reference GravesBidirectionalLSTM.java). Output is the
    SUM of forward and backward passes, matching the reference's ADD mode."""

    peephole: bool = True

    def init_params(self, key, itype: InputType) -> dict:
        kf, kb = jax.random.split(key)
        fwd = LSTM.init_params(self, kf, itype)
        bwd = LSTM.init_params(self, kb, itype)
        return ({f"F{k}": v for k, v in fwd.items()}
                | {f"B{k}": v for k, v in bwd.items()})

    def regularizable_params(self):
        return ("FW", "FRW", "BW", "BRW")

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        x = self.apply_dropout(x, rng, train)
        act, gate = self._acts()
        B = x.shape[0]
        zeros = jnp.zeros((B, self.n_out), x.dtype)
        fwd_p = {k[1:]: v for k, v in params.items() if k.startswith("F")}
        bwd_p = {k[1:]: v for k, v in params.items() if k.startswith("B")}
        ys_f, _ = _lstm_scan(fwd_p, x, act, gate, zeros, zeros, self.peephole, mask)
        x_rev = jnp.flip(x, axis=1)
        mask_rev = jnp.flip(mask, axis=1) if mask is not None else None
        ys_b, _ = _lstm_scan(bwd_p, x_rev, act, gate, zeros, zeros, self.peephole, mask_rev)
        return ys_f + jnp.flip(ys_b, axis=1), state


@register_config("RnnOutput")
@dataclasses.dataclass
class RnnOutputLayer(FeedForwardLayer):
    """Time-distributed output layer with loss (reference nn/conf/layers/RnnOutputLayer.java):
    dense applied at every timestep of [B,T,F], loss masked by the time-series mask."""

    loss: str = "mcxent"

    def has_loss(self) -> bool:
        return True

    def set_n_in(self, itype: InputType) -> None:
        if not self.n_in:
            self.n_in = itype.size if itype.kind == "recurrent" else itype.flat_size()

    def init_params(self, key, itype: InputType) -> dict:
        return {"W": self._init_w(key, (self.n_in, self.n_out)),
                "b": self._init_b((self.n_out,))}

    def output_type(self, itype: InputType) -> InputType:
        return InputType.recurrent(self.n_out, itype.timesteps)

    def preout(self, params, x):
        return _dense(params, x)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        x = self.apply_dropout(x, rng, train)
        return self.act_fn()(_dense(params, x)), state

    def compute_loss(self, params, x, labels, mask=None) -> Array:
        return get_loss(self.loss)(labels, _dense(params, x), self.act_fn(), mask)
