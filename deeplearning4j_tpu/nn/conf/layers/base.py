"""Layer base classes.

Reference contract: nn/api/Layer.java:37 (activate/backpropGradient/preOutput) +
nn/conf/layers/Layer.java config hierarchy. Here a layer is a frozen-ish dataclass of
hyperparameters with pure functions over explicit param/state pytrees:

  init_params(key, input_type)  -> dict[str, Array]     (named param views; reference
                                                         nn/params/*ParamInitializer)
  init_state(input_type)        -> dict[str, Array]     (e.g. batchnorm running stats)
  apply(params, state, x, ...)  -> (activations, state) (reference Layer.activate:192)
  output_type(input_type)       -> InputType            (shape inference,
                                                         reference InputTypeUtil)

Fields set to None inherit network-level defaults; NeuralNetConfiguration's builder bakes
the resolved values in at build time (the reference does the same via config cloning,
nn/conf/NeuralNetConfiguration.java:478+).

Dropout semantics follow the reference (inverted dropout where the configured value is
the RETAIN probability — reference org.nd4j.linalg DropOutInverted as used by
nn/layers/BaseLayer): keep with prob p, scale kept units by 1/p.
"""
from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.weights import init_weights
from deeplearning4j_tpu.ops.activations import get_activation

Array = jax.Array


@dataclasses.dataclass
class Layer:
    """Base hyperparameters shared by all layers (None = inherit network default)."""

    name: Optional[str] = None
    activation: Optional[str] = None
    weight_init: Optional[str] = None
    dist: Optional[dict] = None
    bias_init: Optional[float] = None
    l1: Optional[float] = None
    l2: Optional[float] = None
    dropout: Optional[float] = None          # retain probability; None/0 = no dropout
    learning_rate: Optional[float] = None    # per-layer lr override
    bias_learning_rate: Optional[float] = None
    updater: Optional[str] = None            # per-layer updater override
    momentum: Optional[float] = None
    rho: Optional[float] = None
    rms_decay: Optional[float] = None
    adam_mean_decay: Optional[float] = None
    adam_var_decay: Optional[float] = None
    epsilon: Optional[float] = None
    gradient_normalization: Optional[str] = None
    gradient_normalization_threshold: Optional[float] = None

    # ------------------------------------------------------------------ contracts
    def init_params(self, key: jax.Array, itype: InputType) -> dict:
        return {}

    def init_state(self, itype: InputType) -> dict:
        return {}

    def apply(self, params: dict, state: dict, x: Array, *, train: bool = False,
              rng: Optional[jax.Array] = None, mask: Optional[Array] = None):
        raise NotImplementedError

    def output_type(self, itype: InputType) -> InputType:
        return itype

    def set_n_in(self, itype: InputType) -> None:
        """Infer input-size fields from the incoming InputType (override where relevant)."""

    def regularizable_params(self) -> Sequence[str]:
        """Param names subject to l1/l2 (weights, not biases — reference semantics)."""
        return ("W",)

    def is_pretrain_layer(self) -> bool:
        return False

    def has_loss(self) -> bool:
        """True for output/loss layers that terminate backprop with a loss function."""
        return False

    # ------------------------------------------------------------------ helpers
    def act_fn(self):
        return get_activation(self.activation or "identity")

    def _init_w(self, key: jax.Array, shape, dtype=jnp.float32) -> Array:
        return init_weights(key, shape, self.weight_init or "xavier", self.dist, dtype)

    def _init_b(self, shape, dtype=jnp.float32) -> Array:
        return jnp.full(shape, self.bias_init or 0.0, dtype)

    def apply_dropout(self, x: Array, rng: Optional[jax.Array], train: bool) -> Array:
        p = self.dropout
        if not train or p is None or p == 0.0 or p >= 1.0 or rng is None:
            return x
        keep = jax.random.bernoulli(rng, p, x.shape)
        return jnp.where(keep, x / p, 0.0)


@dataclasses.dataclass
class FeedForwardLayer(Layer):
    """Layers with an nIn->nOut dense-like shape contract (reference
    nn/conf/layers/FeedForwardLayer.java)."""

    n_in: int = 0
    n_out: int = 0

    def set_n_in(self, itype: InputType) -> None:
        if not self.n_in:
            self.n_in = itype.flat_size() if itype.kind != "recurrent" else itype.size

    def output_type(self, itype: InputType) -> InputType:
        if itype.kind == "recurrent":
            return InputType.recurrent(self.n_out, itype.timesteps)
        return InputType.feed_forward(self.n_out)


@dataclasses.dataclass
class PretrainLayer(FeedForwardLayer):
    """Layers supporting unsupervised layerwise pretraining (AutoEncoder/RBM/VAE).
    Reference: nn/api/Layer pretrain path, MultiLayerNetwork.pretrainLayer:183."""

    def is_pretrain_layer(self) -> bool:
        return True

    def pretrain_loss(self, params: dict, x: Array, *, rng: jax.Array) -> Array:
        """Unsupervised objective minimized during layerwise pretraining."""
        raise NotImplementedError
