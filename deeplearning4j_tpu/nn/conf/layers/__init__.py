"""Layer configuration + functional implementation classes.

Unlike the reference, which splits layer *config* (nn/conf/layers/*.java) from layer
*implementation* (nn/layers/**), the TPU-native design merges them: each dataclass is a
JSON-serializable config AND owns pure functions ``init_params`` / ``apply`` /
``output_type``. Backprop comes from JAX autodiff instead of hand-written
``backpropGradient`` — correctness is enforced by the same numeric gradient-check
strategy the reference uses (reference gradientcheck/GradientCheckUtil.java:62).
"""
from deeplearning4j_tpu.nn.conf.layers.base import Layer, FeedForwardLayer, PretrainLayer
from deeplearning4j_tpu.nn.conf.layers.feedforward import (
    DenseLayer, OutputLayer, LossLayer, ActivationLayer, DropoutLayer,
    EmbeddingLayer, AutoEncoder, RBM,
)
from deeplearning4j_tpu.nn.conf.layers.convolutional import (
    ConvolutionLayer, SubsamplingLayer, Upsampling2D, ZeroPaddingLayer, GlobalPoolingLayer,
)
from deeplearning4j_tpu.nn.conf.layers.normalization import (
    BatchNormalization, LocalResponseNormalization,
)
from deeplearning4j_tpu.nn.conf.layers.recurrent import (
    GravesLSTM, LSTM, GravesBidirectionalLSTM, RnnOutputLayer,
)
from deeplearning4j_tpu.nn.conf.layers.variational import (
    BernoulliReconstructionDistribution, CompositeReconstructionDistribution,
    ExponentialReconstructionDistribution, GaussianReconstructionDistribution,
    ReconstructionDistribution, VariationalAutoencoder,
)
from deeplearning4j_tpu.nn.conf.layers.attention import (
    SelfAttentionLayer, TransformerBlock,
)
# imported for registration side effects too: a saved MoE model zip must
# restore without the caller having imported the module first
from deeplearning4j_tpu.nn.conf.layers.moe import MoELayer, MoETransformerBlock

__all__ = [
    "Layer", "FeedForwardLayer", "PretrainLayer",
    "DenseLayer", "OutputLayer", "LossLayer", "ActivationLayer", "DropoutLayer",
    "EmbeddingLayer", "AutoEncoder", "RBM",
    "ConvolutionLayer", "SubsamplingLayer", "Upsampling2D", "ZeroPaddingLayer",
    "GlobalPoolingLayer",
    "BatchNormalization", "LocalResponseNormalization",
    "GravesLSTM", "LSTM", "GravesBidirectionalLSTM", "RnnOutputLayer",
    "VariationalAutoencoder", "SelfAttentionLayer", "TransformerBlock", "MoELayer", "MoETransformerBlock",
]
