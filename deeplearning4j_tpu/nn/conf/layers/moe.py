"""Mixture-of-Experts layer with top-1 (Switch-style) routing.

Absent in the reference; part of the TPU-native parallelism surface (expert
parallelism — SURVEY.md §2.4 note). The layer itself is mesh-agnostic: the
dense ``apply`` computes the routed FFN on one device (every expert evaluated
via batched einsum — fine at test scale), while
``parallel/moe.py::ExpertParallelMoE`` runs the same parameters across an
``expert`` mesh axis with all_to_all dispatch/combine (GShard-style) and
matches the dense math exactly when no tokens overflow capacity.

Params: "Wg" [F, E] router; experts batched on the leading axis —
"W1" [E, F, H], "b1" [E, H], "W2" [E, H, F], "b2" [E, F].
"""
from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.common import accum_dtype, get_policy
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers.base import FeedForwardLayer
from deeplearning4j_tpu.nn.conf.serde import register_config


@register_config("MoE")
@dataclasses.dataclass
class MoELayer(FeedForwardLayer):
    n_experts: int = 4
    expert_hidden: int = 0          # 0 -> 4 * width
    router_noise: float = 0.0       # jitter stddev at train time
    #: Switch-transformer auxiliary load-balance loss weight, added to the
    #: training objective (without it top-1 routing collapses onto one
    #: expert). The term rides the layer-state pytree as "aux_loss" and is
    #: summed by loss_fn/graph_loss.
    aux_loss_weight: float = 0.01

    def init_state(self, itype: InputType) -> dict:
        return {"aux_loss": jnp.zeros((), jnp.float32)}

    def set_n_in(self, itype: InputType) -> None:
        if not self.n_in:
            self.n_in = itype.size if itype.kind == "recurrent" else itype.flat_size()
        if not self.n_out:
            self.n_out = self.n_in

    def _hidden(self) -> int:
        return self.expert_hidden or 4 * self.n_out

    def init_params(self, key, itype: InputType) -> dict:
        E, F, H = self.n_experts, self.n_in, self._hidden()
        kg, k1, k2 = jax.random.split(key, 3)
        w1 = jax.vmap(lambda k: self._init_w(k, (F, H)))(
            jax.random.split(k1, E))
        w2 = jax.vmap(lambda k: self._init_w(k, (H, F)))(
            jax.random.split(k2, E))
        return {"Wg": self._init_w(kg, (F, E)),
                "W1": w1, "b1": jnp.zeros((E, H), jnp.float32),
                "W2": w2, "b2": jnp.zeros((E, F), jnp.float32)}

    def regularizable_params(self):
        return ("W1", "W2")

    def output_type(self, itype: InputType) -> InputType:
        if itype is not None and itype.kind == "recurrent":
            return InputType.recurrent(self.n_out, itype.timesteps)
        return InputType.feed_forward(self.n_out)

    def route(self, params, x2d, *, train=False, rng=None):
        """Top-1 router: returns (expert_index [S], gate [S], probs [S, E])."""
        logits = x2d @ params["Wg"]
        if train and self.router_noise > 0 and rng is not None:
            logits = logits + self.router_noise * jax.random.normal(
                rng, logits.shape)
        probs = jax.nn.softmax(logits, axis=-1)
        eidx = jnp.argmax(probs, axis=-1)
        gate = jnp.max(probs, axis=-1)
        return eidx, gate, probs

    def expert_ffn(self, params, buf):
        """Apply every expert to its token buffer: buf [E, C, F] -> [E, C, F]."""
        pol = get_policy()
        h = (jnp.einsum("ecf,efh->ech", buf.astype(pol.compute_dtype),
                        params["W1"].astype(pol.compute_dtype),
                        preferred_element_type=accum_dtype(pol.compute_dtype))
             .astype(pol.output_dtype) + params["b1"][:, None].astype(pol.output_dtype))
        h = jax.nn.relu(h)
        return (jnp.einsum("ech,ehf->ecf", h.astype(pol.compute_dtype),
                           params["W2"].astype(pol.compute_dtype),
                           preferred_element_type=accum_dtype(pol.compute_dtype))
                .astype(pol.output_dtype)
                + params["b2"][:, None].astype(pol.output_dtype))

    def moe_ffn_2d(self, params, x2d, *, train=False, rng=None):
        """Core top-1 expert FFN on flattened tokens: (y2d, aux_term).

        ONE implementation shared by MoELayer.apply and MoETransformerBlock's
        residual sublayer (dense evaluation: every expert on every token,
        select by routing — exact, and XLA-friendly on a single chip; the
        sparse dispatch lives in parallel/moe.ExpertParallelMoE)."""
        pol = get_policy()
        eidx, gate, probs = self.route(params, x2d, train=train, rng=rng)
        # load-balance term from THIS routing decision (same rng/noise the
        # tokens were actually dispatched with)
        aux = self._balance_term(eidx, probs)
        h = (jnp.einsum("sf,efh->esh", x2d.astype(pol.compute_dtype),
                        params["W1"].astype(pol.compute_dtype),
                        preferred_element_type=accum_dtype(pol.compute_dtype))
             .astype(pol.output_dtype) + params["b1"][:, None].astype(pol.output_dtype))
        h = jax.nn.relu(h)
        y_all = (jnp.einsum("esh,ehf->esf", h.astype(pol.compute_dtype),
                            params["W2"].astype(pol.compute_dtype),
                            preferred_element_type=accum_dtype(pol.compute_dtype))
                 .astype(pol.output_dtype)
                 + params["b2"][:, None].astype(pol.output_dtype))  # [E, S, F]
        sel = jax.nn.one_hot(eidx, self.n_experts, dtype=y_all.dtype)  # [S, E]
        y = jnp.einsum("se,esf->sf", sel, y_all) * gate[:, None].astype(y_all.dtype)
        return y, aux

    def _ep_context(self):
        """Active expert-parallel context, if a trainer published one while
        tracing (parallel/context.py). None -> dense single-device path."""
        from deeplearning4j_tpu.parallel import context as pctx
        ctx = pctx.current()
        if ctx is not None and ctx.expert_axis is not None \
                and self.n_experts % ctx.mesh.shape[ctx.expert_axis] == 0:
            return ctx
        return None

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        shape = x.shape
        ctx = self._ep_context()
        if ctx is not None:
            from deeplearning4j_tpu.parallel.moe import expert_parallel_ffn
            y, aux = expert_parallel_ffn(self, params, x, ctx.mesh,
                                         ctx.expert_axis,
                                         ctx.capacity_factor,
                                         train=train, rng=rng,
                                         seq_axis=ctx.seq_axis)
            new_state = {"aux_loss": aux if train else jnp.zeros_like(aux)}
            return self.act_fn()(y.reshape(shape)), new_state
        x2d = x.reshape(-1, shape[-1])
        y, aux = self.moe_ffn_2d(params, x2d, train=train, rng=rng)
        # aux keeps its natural dtype (f32 in training, f64 under the
        # gradient checker — a forced f32 cast would truncate the f64 path
        # and make numeric-vs-analytic gradients disagree)
        new_state = {"aux_loss": aux if train else jnp.zeros_like(aux)}
        return self.act_fn()(y.reshape(shape)), new_state

    def _balance_term(self, eidx, probs) -> jax.Array:
        """Switch-transformer balance term E * sum_e f_e * P_e from a routing
        decision — the ONE formula both training (apply) and
        load_balance_loss optimize."""
        frac = jnp.mean(jax.nn.one_hot(eidx, self.n_experts), axis=0)
        return self.n_experts * jnp.sum(frac * jnp.mean(probs, axis=0))

    def load_balance_loss(self, params, x2d) -> jax.Array:
        """Switch-transformer auxiliary loss: E * sum_e f_e * P_e."""
        eidx, _, probs = self.route(params, x2d)
        return self._balance_term(eidx, probs)


@register_config("MoETransformerBlock")
@dataclasses.dataclass
class MoETransformerBlock(MoELayer):
    """Switch-transformer block: pre-LN residual attention, then a pre-LN
    residual top-1 MoE FFN (Fedus et al.; the dense-FFN analog is
    TransformerBlock). Publishes the load-balance term like MoELayer.

    Params: ln1/ln2 scale+bias, fused Wqkv + Wo/bo attention projections,
    and MoELayer's router/expert tensors.
    """

    n_heads: int = 4
    causal: bool = True
    #: residual-stream blocks take no output nonlinearity by default; an
    #: explicit non-identity default here keeps bake_layer_defaults from
    #: filling None with the global activation (sigmoid) and squashing the
    #: residual stream. A user-set activation is still honored in apply().
    activation: Optional[str] = "identity"

    def init_params(self, key, itype: InputType) -> dict:
        F = self.n_out
        if F % self.n_heads:
            raise ValueError(f"width {F} not divisible by heads {self.n_heads}")
        k_attn, k_moe = jax.random.split(key)
        ka, kb = jax.random.split(k_attn)
        params = MoELayer.init_params(self, k_moe, itype)
        params.update({
            "ln1_g": jnp.ones((F,), jnp.float32),
            "ln1_b": jnp.zeros((F,), jnp.float32),
            "Wqkv": self._init_w(ka, (F, 3 * F)),
            "Wo": self._init_w(kb, (F, F)),
            "bo": jnp.zeros((F,), jnp.float32),
            "ln2_g": jnp.ones((F,), jnp.float32),
            "ln2_b": jnp.zeros((F,), jnp.float32),
        })
        return params

    def regularizable_params(self):
        return ("Wqkv", "Wo", "W1", "W2")

    def output_type(self, itype: InputType) -> InputType:
        return InputType.recurrent(self.n_out, itype.timesteps)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        from deeplearning4j_tpu.nn.conf.layers.attention import (
            TransformerBlock, attend)

        pol = get_policy()
        B, T, F = x.shape
        H = self.n_heads
        D = F // H
        h = TransformerBlock._ln(x, params["ln1_g"], params["ln1_b"])
        qkv = jnp.matmul(h.astype(pol.compute_dtype),
                         params["Wqkv"].astype(pol.compute_dtype),
                         preferred_element_type=accum_dtype(pol.compute_dtype))
        q, k, v = jnp.split(qkv.astype(pol.output_dtype), 3, axis=-1)
        q, k, v = (a.reshape(B, T, H, D) for a in (q, k, v))
        o = attend(q, k, v, self.causal, mask)
        att = jnp.matmul(o.reshape(B, T, F).astype(pol.compute_dtype),
                         params["Wo"].astype(pol.compute_dtype),
                         preferred_element_type=accum_dtype(pol.compute_dtype))
        x = x + att.astype(pol.output_dtype) + params["bo"].astype(pol.output_dtype)

        h = TransformerBlock._ln(x, params["ln2_g"], params["ln2_b"])
        ctx = self._ep_context()
        if ctx is not None:
            from deeplearning4j_tpu.parallel.moe import expert_parallel_ffn
            y, aux = expert_parallel_ffn(self, params, h, ctx.mesh,
                                         ctx.expert_axis,
                                         ctx.capacity_factor,
                                         train=train, rng=rng,
                                         seq_axis=ctx.seq_axis)
        else:
            y2d, aux = self.moe_ffn_2d(params, h.reshape(-1, F), train=train,
                                       rng=rng)
            y = y2d.reshape(B, T, F)
        new_state = {"aux_loss": aux if train else jnp.zeros_like(aux)}
        # honor a user-configured activation on the block output (default is
        # identity — the standard residual-stream semantics)
        return self.act_fn()(x + y.reshape(B, T, F)), new_state
