"""Normalization layers: BatchNormalization, LocalResponseNormalization.

Reference: nn/layers/normalization/BatchNormalization.java (preOutput:398 with global
mean/var EMA, backprop:91) and LocalResponseNormalization.java; cuDNN helpers in
deeplearning4j-cuda. On TPU, XLA fuses the normalize+scale+shift elementwise chain into
neighbouring ops, which is what the cuDNN helper bought the reference.

BatchNorm running statistics live in the layer *state* pytree (mean/var), updated
functionally during training — the pure-function equivalent of the reference's mutable
global-mean/var fields. ``decay`` matches the reference's EMA decay semantics:
new_mean = decay * old + (1-decay) * batch_mean.
"""
from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.common import at_least_f32, get_policy
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers.base import Layer
from deeplearning4j_tpu.nn.conf.serde import register_config

Array = jax.Array


@register_config("BatchNormalization")
@dataclasses.dataclass
class BatchNormalization(Layer):
    """Batch norm over the channel axis (last axis in NHWC / feature axis in FF)."""

    decay: float = 0.9
    eps: float = 1e-5
    gamma: float = 1.0       # init values when lock_gamma_beta
    beta: float = 0.0
    lock_gamma_beta: bool = False
    n_in: int = 0

    def set_n_in(self, itype: InputType) -> None:
        if not self.n_in:
            self.n_in = itype.channels if itype.kind == "convolutional" else itype.flat_size()

    def regularizable_params(self):
        return ()

    def init_params(self, key, itype: InputType) -> dict:
        if self.lock_gamma_beta:
            return {}
        return {"gamma": jnp.full((self.n_in,), self.gamma, jnp.float32),
                "beta": jnp.full((self.n_in,), self.beta, jnp.float32)}

    def init_state(self, itype: InputType) -> dict:
        return {"mean": jnp.zeros((self.n_in,), jnp.float32),
                "var": jnp.ones((self.n_in,), jnp.float32)}

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        from deeplearning4j_tpu.ops.pallas_kernels import batch_norm_train

        axes = tuple(range(x.ndim - 1))
        # statistics dtype comes from the policy: at-least-f32 by default
        # (bf16's 8-bit mantissa is exactly where mean/var of many small
        # values loses training accuracy; the float64 gradient-check path
        # flows through undowncast), bf16 under the flagship reduction
        # policy — then the whole stat pass is convert-free single-pass
        # (batch_norm_train: one variadic reduce fwd, one bwd) instead of
        # the standalone f32 upcast-reduce fusions of jnp.mean + jnp.var
        stat_dtype = get_policy().stat_dtype(x.dtype)
        if self.lock_gamma_beta:
            gamma = jnp.full((self.n_in,), self.gamma, jnp.float32)
            beta = jnp.full((self.n_in,), self.beta, jnp.float32)
        else:
            gamma, beta = params["gamma"], params["beta"]
        if train:
            out, mean, var = batch_norm_train(x, gamma, beta, axes,
                                              self.eps, stat_dtype)
            mean = jax.lax.stop_gradient(mean).astype(state["mean"].dtype)
            var = jax.lax.stop_gradient(var).astype(state["var"].dtype)
            # EMA update in the f32 state dtype regardless of stat precision
            new_state = {
                "mean": self.decay * state["mean"] + (1 - self.decay) * mean,
                "var": self.decay * state["var"] + (1 - self.decay) * var,
            }
        else:
            mean, var = state["mean"], state["var"]
            new_state = state
            # inference: fold to one channel-sized scale/shift, elementwise
            # pass stays in x.dtype (no full-tensor upcast); channel math in
            # at-least-f32 (f64 under the gradient-check policy)
            wide = at_least_f32(x.dtype)
            inv = jax.lax.rsqrt(var.astype(wide) + self.eps)
            scale = gamma.astype(wide) * inv
            shift = beta.astype(wide) - mean.astype(wide) * scale
            out = x * scale.astype(x.dtype) + shift.astype(x.dtype)
        return self.act_fn()(out), new_state


@register_config("LocalResponseNormalization")
@dataclasses.dataclass
class LocalResponseNormalization(Layer):
    """Cross-channel LRN (reference nn/layers/normalization/LocalResponseNormalization.java):
    out = x / (k + alpha * sum_{adjacent n channels} x^2)^beta."""

    k: float = 2.0
    n: int = 5
    alpha: float = 1e-4
    beta: float = 0.75

    def regularizable_params(self):
        return ()

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        # x is NHWC; sum x^2 over a window of n adjacent channels. Like the
        # other norms, the square/sum/power statistics run in at least f32
        # under the bf16 activation policy.
        half = self.n // 2
        xf = x.astype(at_least_f32(x.dtype))
        sq = xf * xf
        padded = jnp.pad(sq, ((0, 0),) * (x.ndim - 1) + ((half, half),))
        windowed = sum(padded[..., i:i + x.shape[-1]] for i in range(self.n))
        denom = (self.k + self.alpha * windowed) ** self.beta
        return (xf / denom).astype(x.dtype), state
