"""Variational autoencoder layer.

Reference: nn/layers/variational/VariationalAutoencoder.java (1055 LoC) +
nn/conf/layers/variational/ (ReconstructionDistribution family). Pretrain objective is
the negative ELBO with the reparameterization trick; supervised forward propagates the
mean of q(z|x) through the encoder (reference behaviour: activate() returns the latent
mean when used as a frozen feature extractor).

Encoder/decoder are MLPs given by ``encoder_layer_sizes`` / ``decoder_layer_sizes``.
Reconstruction distributions are a pluggable family (reference
nn/conf/layers/variational/ReconstructionDistribution.java SPI with Gaussian,
Bernoulli, Exponential, and Composite implementations): pass a distribution
object, or the string shortcuts 'gaussian' | 'bernoulli' | 'exponential'.
"""
from __future__ import annotations

import dataclasses
from typing import Any, List, Sequence

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers.base import PretrainLayer
from deeplearning4j_tpu.nn.conf.serde import register_config
from deeplearning4j_tpu.ops.activations import get_activation

Array = jax.Array


# ---------------------------------------------------------------------------
# Reconstruction distribution family (reference nn/conf/layers/variational/)
# ---------------------------------------------------------------------------

class ReconstructionDistribution:
    """p(x|z) SPI (reference ReconstructionDistribution.java): maps the
    decoder's pre-output ``pre`` to a negative log-likelihood and a mean."""

    def input_size(self, data_size: int) -> int:
        """Decoder output units needed to parameterize p(x|z) for
        ``data_size`` features (reference distributionInputSize)."""
        raise NotImplementedError

    def nll(self, x: Array, pre: Array) -> Array:
        """Per-example negative log p(x|z), summed over features
        (reference negLogProbability)."""
        raise NotImplementedError

    def mean(self, pre: Array) -> Array:
        """E[x|z] (reference generateAtMean)."""
        raise NotImplementedError


@register_config("GaussianReconstruction")
@dataclasses.dataclass
class GaussianReconstructionDistribution(ReconstructionDistribution):
    """Diagonal gaussian with learned variance (reference
    GaussianReconstructionDistribution.java). ``pre`` packs [mean | logvar];
    ``activation`` applies to the mean half only."""

    activation: str = "identity"

    def input_size(self, data_size: int) -> int:
        return 2 * data_size

    def _split(self, pre):
        d = pre.shape[-1] // 2
        act = get_activation(self.activation)
        return act(pre[..., :d]), pre[..., d:]

    def nll(self, x, pre):
        rmean, rlogvar = self._split(pre)
        return 0.5 * jnp.sum(rlogvar + (x - rmean) ** 2 / jnp.exp(rlogvar)
                             + jnp.log(2 * jnp.pi), axis=-1)

    def mean(self, pre):
        return self._split(pre)[0]


@register_config("BernoulliReconstruction")
@dataclasses.dataclass
class BernoulliReconstructionDistribution(ReconstructionDistribution):
    """Bernoulli over logits (reference
    BernoulliReconstructionDistribution.java, sigmoid parameterization)."""

    def input_size(self, data_size: int) -> int:
        return data_size

    def nll(self, x, pre):
        # stable cross-entropy on logits
        return jnp.sum(x * jax.nn.softplus(-pre)
                       + (1 - x) * jax.nn.softplus(pre), axis=-1)

    def mean(self, pre):
        return jax.nn.sigmoid(pre)


@register_config("ExponentialReconstruction")
@dataclasses.dataclass
class ExponentialReconstructionDistribution(ReconstructionDistribution):
    """Exponential with rate lambda = exp(gamma) (reference
    ExponentialReconstructionDistribution.java): log p(x) = gamma - exp(gamma)*x
    for x >= 0; mean = exp(-gamma)."""

    activation: str = "identity"

    def input_size(self, data_size: int) -> int:
        return data_size

    def nll(self, x, pre):
        gamma = get_activation(self.activation)(pre)
        return jnp.sum(jnp.exp(gamma) * x - gamma, axis=-1)

    def mean(self, pre):
        gamma = get_activation(self.activation)(pre)
        return jnp.exp(-gamma)


@register_config("CompositeReconstruction")
@dataclasses.dataclass
class CompositeReconstructionDistribution(ReconstructionDistribution):
    """Different distributions over feature slices (reference
    CompositeReconstructionDistribution.java): ``components`` is a list of
    [data_size, distribution] pairs, in feature order."""

    components: List = dataclasses.field(default_factory=list)

    def add(self, data_size: int,
            dist: ReconstructionDistribution) -> "CompositeReconstructionDistribution":
        self.components.append([int(data_size), dist])
        return self

    def input_size(self, data_size: int) -> int:
        total_data = sum(int(s) for s, _ in self.components)
        if total_data != data_size:
            raise ValueError(
                f"composite components cover {total_data} features, "
                f"layer has {data_size}")
        return sum(d.input_size(int(s)) for s, d in self.components)

    def nll(self, x, pre):
        total = 0.0
        xo = po = 0
        for s, d in self.components:
            s = int(s)
            ins = d.input_size(s)
            total = total + d.nll(x[..., xo:xo + s], pre[..., po:po + ins])
            xo += s
            po += ins
        return total

    def mean(self, pre):
        outs = []
        po = 0
        for s, d in self.components:
            ins = d.input_size(int(s))
            outs.append(d.mean(pre[..., po:po + ins]))
            po += ins
        return jnp.concatenate(outs, axis=-1)


_DIST_SHORTCUTS = {
    "gaussian": GaussianReconstructionDistribution,
    "bernoulli": BernoulliReconstructionDistribution,
    "exponential": ExponentialReconstructionDistribution,
}


def resolve_reconstruction_distribution(rd) -> ReconstructionDistribution:
    if isinstance(rd, ReconstructionDistribution):
        return rd
    if isinstance(rd, str):
        if rd not in _DIST_SHORTCUTS:
            raise ValueError(f"unknown reconstruction distribution {rd!r}; "
                             f"known: {sorted(_DIST_SHORTCUTS)}")
        return _DIST_SHORTCUTS[rd]()
    raise TypeError(f"reconstruction_distribution must be a string or "
                    f"ReconstructionDistribution, got {type(rd)}")


@register_config("VariationalAutoencoder")
@dataclasses.dataclass
class VariationalAutoencoder(PretrainLayer):
    encoder_layer_sizes: Sequence[int] = (100,)
    decoder_layer_sizes: Sequence[int] = (100,)
    #: string shortcut or ReconstructionDistribution instance (incl. Composite)
    reconstruction_distribution: Any = "gaussian"
    pzx_activation: str = "identity"
    num_samples: int = 1

    def _dist(self) -> ReconstructionDistribution:
        return resolve_reconstruction_distribution(
            self.reconstruction_distribution)

    def regularizable_params(self):
        return tuple(k for k in self._param_names() if k.startswith("eW") or
                     k.startswith("dW") or k in ("zMeanW", "zLogVarW", "outW"))

    def _param_names(self):
        names = []
        for i in range(len(self.encoder_layer_sizes)):
            names += [f"eW{i}", f"eb{i}"]
        names += ["zMeanW", "zMeanb", "zLogVarW", "zLogVarb"]
        for i in range(len(self.decoder_layer_sizes)):
            names += [f"dW{i}", f"db{i}"]
        names += ["outW", "outb"]
        return names

    def init_params(self, key, itype: InputType) -> dict:
        params = {}
        sizes_in = [self.n_in] + list(self.encoder_layer_sizes)
        keys = jax.random.split(key, len(self.encoder_layer_sizes)
                                + len(self.decoder_layer_sizes) + 3)
        ki = 0
        for i, (a, b) in enumerate(zip(sizes_in[:-1], sizes_in[1:])):
            params[f"eW{i}"] = self._init_w(keys[ki], (a, b)); ki += 1
            params[f"eb{i}"] = self._init_b((b,))
        enc_out = sizes_in[-1]
        params["zMeanW"] = self._init_w(keys[ki], (enc_out, self.n_out)); ki += 1
        params["zMeanb"] = self._init_b((self.n_out,))
        params["zLogVarW"] = self._init_w(keys[ki], (enc_out, self.n_out)); ki += 1
        params["zLogVarb"] = self._init_b((self.n_out,))
        dsizes = [self.n_out] + list(self.decoder_layer_sizes)
        for i, (a, b) in enumerate(zip(dsizes[:-1], dsizes[1:])):
            params[f"dW{i}"] = self._init_w(keys[ki], (a, b)); ki += 1
            params[f"db{i}"] = self._init_b((b,))
        out_units = self._dist().input_size(self.n_in)
        params["outW"] = self._init_w(keys[-1], (dsizes[-1], out_units))
        params["outb"] = self._init_b((out_units,))
        return params

    def _encode(self, params, x):
        act = self.act_fn()
        h = x
        for i in range(len(self.encoder_layer_sizes)):
            h = act(jnp.matmul(h, params[f"eW{i}"]) + params[f"eb{i}"])
        pz = get_activation(self.pzx_activation)
        mean = pz(jnp.matmul(h, params["zMeanW"]) + params["zMeanb"])
        logvar = jnp.matmul(h, params["zLogVarW"]) + params["zLogVarb"]
        return mean, logvar

    def _decode(self, params, z):
        act = self.act_fn()
        h = z
        for i in range(len(self.decoder_layer_sizes)):
            h = act(jnp.matmul(h, params[f"dW{i}"]) + params[f"db{i}"])
        return jnp.matmul(h, params["outW"]) + params["outb"]

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        mean, _ = self._encode(params, x)
        return mean, state

    def reconstruct(self, params, x):
        mean, _ = self._encode(params, x)
        return self._dist().mean(self._decode(params, mean))

    def reconstruction_log_probability(self, params, x, *, rng,
                                       num_samples: int = None):
        """Per-example log p(x) estimate via importance-free MC over q(z|x)
        (reference VariationalAutoencoder.reconstructionLogProbability)."""
        n = num_samples or self.num_samples
        mean, logvar = self._encode(params, x)
        dist = self._dist()
        total = 0.0
        for k in jax.random.split(rng, n):
            eps = jax.random.normal(k, mean.shape, mean.dtype)
            z = mean + jnp.exp(0.5 * logvar) * eps
            total = total - dist.nll(x, self._decode(params, z))
        return total / n

    def pretrain_loss(self, params, x, *, rng):
        """Negative ELBO = reconstruction NLL + KL(q(z|x) || N(0,I))."""
        mean, logvar = self._encode(params, x)
        dist = self._dist()
        total = 0.0
        keys = jax.random.split(rng, self.num_samples)
        for k in keys:
            eps = jax.random.normal(k, mean.shape, mean.dtype)
            z = mean + jnp.exp(0.5 * logvar) * eps
            total = total + jnp.mean(dist.nll(x, self._decode(params, z)))
        recon = total / self.num_samples
        kl = 0.5 * jnp.mean(jnp.sum(jnp.exp(logvar) + mean ** 2 - 1.0 - logvar, axis=-1))
        return recon + kl

    def output_type(self, itype: InputType) -> InputType:
        return InputType.feed_forward(self.n_out)
