"""Variational autoencoder layer.

Reference: nn/layers/variational/VariationalAutoencoder.java (1055 LoC) +
nn/conf/layers/variational/ (ReconstructionDistribution family). Pretrain objective is
the negative ELBO with the reparameterization trick; supervised forward propagates the
mean of q(z|x) through the encoder (reference behaviour: activate() returns the latent
mean when used as a frozen feature extractor).

Encoder/decoder are MLPs given by ``encoder_layer_sizes`` / ``decoder_layer_sizes``.
Reconstruction distributions: 'gaussian' (diagonal, learned variance), 'bernoulli'.
"""
from __future__ import annotations

import dataclasses
from typing import Sequence

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers.base import PretrainLayer
from deeplearning4j_tpu.nn.conf.serde import register_config
from deeplearning4j_tpu.ops.activations import get_activation

Array = jax.Array


@register_config("VariationalAutoencoder")
@dataclasses.dataclass
class VariationalAutoencoder(PretrainLayer):
    encoder_layer_sizes: Sequence[int] = (100,)
    decoder_layer_sizes: Sequence[int] = (100,)
    reconstruction_distribution: str = "gaussian"  # gaussian | bernoulli
    pzx_activation: str = "identity"
    num_samples: int = 1

    def regularizable_params(self):
        return tuple(k for k in self._param_names() if k.startswith("eW") or
                     k.startswith("dW") or k in ("zMeanW", "zLogVarW", "outW"))

    def _param_names(self):
        names = []
        for i in range(len(self.encoder_layer_sizes)):
            names += [f"eW{i}", f"eb{i}"]
        names += ["zMeanW", "zMeanb", "zLogVarW", "zLogVarb"]
        for i in range(len(self.decoder_layer_sizes)):
            names += [f"dW{i}", f"db{i}"]
        names += ["outW", "outb"]
        return names

    def init_params(self, key, itype: InputType) -> dict:
        params = {}
        sizes_in = [self.n_in] + list(self.encoder_layer_sizes)
        keys = jax.random.split(key, len(self.encoder_layer_sizes)
                                + len(self.decoder_layer_sizes) + 3)
        ki = 0
        for i, (a, b) in enumerate(zip(sizes_in[:-1], sizes_in[1:])):
            params[f"eW{i}"] = self._init_w(keys[ki], (a, b)); ki += 1
            params[f"eb{i}"] = self._init_b((b,))
        enc_out = sizes_in[-1]
        params["zMeanW"] = self._init_w(keys[ki], (enc_out, self.n_out)); ki += 1
        params["zMeanb"] = self._init_b((self.n_out,))
        params["zLogVarW"] = self._init_w(keys[ki], (enc_out, self.n_out)); ki += 1
        params["zLogVarb"] = self._init_b((self.n_out,))
        dsizes = [self.n_out] + list(self.decoder_layer_sizes)
        for i, (a, b) in enumerate(zip(dsizes[:-1], dsizes[1:])):
            params[f"dW{i}"] = self._init_w(keys[ki], (a, b)); ki += 1
            params[f"db{i}"] = self._init_b((b,))
        out_units = self.n_in * (2 if self.reconstruction_distribution == "gaussian" else 1)
        params["outW"] = self._init_w(keys[-1], (dsizes[-1], out_units))
        params["outb"] = self._init_b((out_units,))
        return params

    def _encode(self, params, x):
        act = self.act_fn()
        h = x
        for i in range(len(self.encoder_layer_sizes)):
            h = act(jnp.matmul(h, params[f"eW{i}"]) + params[f"eb{i}"])
        pz = get_activation(self.pzx_activation)
        mean = pz(jnp.matmul(h, params["zMeanW"]) + params["zMeanb"])
        logvar = jnp.matmul(h, params["zLogVarW"]) + params["zLogVarb"]
        return mean, logvar

    def _decode(self, params, z):
        act = self.act_fn()
        h = z
        for i in range(len(self.decoder_layer_sizes)):
            h = act(jnp.matmul(h, params[f"dW{i}"]) + params[f"db{i}"])
        return jnp.matmul(h, params["outW"]) + params["outb"]

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        mean, _ = self._encode(params, x)
        return mean, state

    def reconstruct(self, params, x):
        mean, _ = self._encode(params, x)
        out = self._decode(params, mean)
        if self.reconstruction_distribution == "gaussian":
            return out[..., :self.n_in]
        return jax.nn.sigmoid(out)

    def pretrain_loss(self, params, x, *, rng):
        """Negative ELBO = reconstruction NLL + KL(q(z|x) || N(0,I))."""
        mean, logvar = self._encode(params, x)
        total = 0.0
        keys = jax.random.split(rng, self.num_samples)
        for k in keys:
            eps = jax.random.normal(k, mean.shape, mean.dtype)
            z = mean + jnp.exp(0.5 * logvar) * eps
            out = self._decode(params, z)
            if self.reconstruction_distribution == "gaussian":
                rmean, rlogvar = out[..., :self.n_in], out[..., self.n_in:]
                nll = 0.5 * jnp.sum(rlogvar + (x - rmean) ** 2 / jnp.exp(rlogvar)
                                    + jnp.log(2 * jnp.pi), axis=-1)
            else:
                p = out  # logits
                nll = jnp.sum(x * jax.nn.softplus(-p) + (1 - x) * jax.nn.softplus(p), axis=-1)
            total = total + jnp.mean(nll)
        recon = total / self.num_samples
        kl = 0.5 * jnp.mean(jnp.sum(jnp.exp(logvar) + mean ** 2 - 1.0 - logvar, axis=-1))
        return recon + kl

    def output_type(self, itype: InputType) -> InputType:
        return InputType.feed_forward(self.n_out)
