"""Graph vertices for ComputationGraph DAGs.

Reference: nn/graph/vertex/impl/ — ElementWiseVertex, MergeVertex, SubsetVertex,
L2NormalizeVertex, ScaleVertex, ShiftVertex, StackVertex, UnstackVertex,
PreprocessorVertex, LayerVertex, rnn/{LastTimeStepVertex, DuplicateToTimeSeriesVertex}.
Each is a pure function over its input activations; LayerVertex wraps a Layer config.
"""
from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers.base import Layer
from deeplearning4j_tpu.nn.conf.serde import register_config

Array = jax.Array


@dataclasses.dataclass
class GraphVertex:
    """Base vertex: pure apply over a list of input activations."""

    def init_params(self, key: jax.Array, itypes: list) -> dict:
        return {}

    def init_state(self, itypes: list) -> dict:
        return {}

    def apply(self, params: dict, state: dict, inputs: list, *, train=False,
              rng=None, mask=None):
        raise NotImplementedError

    def output_type(self, itypes: list) -> InputType:
        return itypes[0]

    def n_inputs(self) -> Optional[int]:
        return None  # None = any


@register_config("LayerVertex")
@dataclasses.dataclass
class LayerVertex(GraphVertex):
    """Wraps a Layer config (reference nn/graph/vertex/impl/LayerVertex.java)."""

    layer: Optional[Layer] = None

    def init_params(self, key, itypes):
        return self.layer.init_params(key, itypes[0])

    def init_state(self, itypes):
        return self.layer.init_state(itypes[0])

    def apply(self, params, state, inputs, *, train=False, rng=None, mask=None):
        return self.layer.apply(params, state, inputs[0], train=train, rng=rng,
                                mask=mask)

    def output_type(self, itypes):
        return self.layer.output_type(itypes[0])

    def n_inputs(self):
        return 1


@register_config("MergeVertex")
@dataclasses.dataclass
class MergeVertex(GraphVertex):
    """Concatenate along the feature/channel axis (reference MergeVertex.java).
    NHWC/BTF layouts put that at axis -1 for all ranks."""

    def apply(self, params, state, inputs, *, train=False, rng=None, mask=None):
        return jnp.concatenate(inputs, axis=-1), state

    def output_type(self, itypes):
        first = itypes[0]
        if first.kind == "convolutional":
            return InputType.convolutional(first.height, first.width,
                                           sum(t.channels for t in itypes))
        if first.kind == "recurrent":
            return InputType.recurrent(sum(t.size for t in itypes), first.timesteps)
        return InputType.feed_forward(sum(t.flat_size() for t in itypes))


@register_config("ElementWiseVertex")
@dataclasses.dataclass
class ElementWiseVertex(GraphVertex):
    """Elementwise Add/Subtract/Product/Max/Average (reference ElementWiseVertex.java)."""

    op: str = "add"

    def apply(self, params, state, inputs, *, train=False, rng=None, mask=None):
        op = self.op.lower()
        if op == "add":
            out = sum(inputs)
        elif op == "subtract":
            out = inputs[0] - inputs[1]
        elif op in ("product", "mul"):
            out = inputs[0]
            for x in inputs[1:]:
                out = out * x
            # product of >2 fine
        elif op == "max":
            out = inputs[0]
            for x in inputs[1:]:
                out = jnp.maximum(out, x)
        elif op in ("average", "avg"):
            out = sum(inputs) / len(inputs)
        else:
            raise ValueError(f"Unknown elementwise op '{self.op}'")
        return out, state


@register_config("SubsetVertex")
@dataclasses.dataclass
class SubsetVertex(GraphVertex):
    """Select feature range [start, end] inclusive (reference SubsetVertex.java)."""

    start: int = 0
    end: int = 0

    def apply(self, params, state, inputs, *, train=False, rng=None, mask=None):
        return inputs[0][..., self.start:self.end + 1], state

    def output_type(self, itypes):
        n = self.end - self.start + 1
        t = itypes[0]
        if t.kind == "recurrent":
            return InputType.recurrent(n, t.timesteps)
        if t.kind == "convolutional":
            return InputType.convolutional(t.height, t.width, n)
        return InputType.feed_forward(n)

    def n_inputs(self):
        return 1


@register_config("L2NormalizeVertex")
@dataclasses.dataclass
class L2NormalizeVertex(GraphVertex):
    """x / ||x||_2 over feature dims (reference L2NormalizeVertex.java)."""

    eps: float = 1e-8

    def apply(self, params, state, inputs, *, train=False, rng=None, mask=None):
        x = inputs[0]
        axes = tuple(range(1, x.ndim))
        norm = jnp.sqrt(jnp.sum(x * x, axis=axes, keepdims=True) + self.eps)
        return x / norm, state

    def n_inputs(self):
        return 1


@register_config("L2Vertex")
@dataclasses.dataclass
class L2Vertex(GraphVertex):
    """Pairwise L2 distance between two inputs (reference L2Vertex.java) -> [B,1]."""

    eps: float = 1e-8

    def apply(self, params, state, inputs, *, train=False, rng=None, mask=None):
        a, b = inputs[0], inputs[1]
        d = a - b
        axes = tuple(range(1, d.ndim))
        return jnp.sqrt(jnp.sum(d * d, axis=axes, keepdims=False)[..., None] + self.eps), state

    def output_type(self, itypes):
        return InputType.feed_forward(1)

    def n_inputs(self):
        return 2


@register_config("ScaleVertex")
@dataclasses.dataclass
class ScaleVertex(GraphVertex):
    scale: float = 1.0

    def apply(self, params, state, inputs, *, train=False, rng=None, mask=None):
        return inputs[0] * self.scale, state

    def n_inputs(self):
        return 1


@register_config("ShiftVertex")
@dataclasses.dataclass
class ShiftVertex(GraphVertex):
    shift: float = 0.0

    def apply(self, params, state, inputs, *, train=False, rng=None, mask=None):
        return inputs[0] + self.shift, state

    def n_inputs(self):
        return 1


@register_config("StackVertex")
@dataclasses.dataclass
class StackVertex(GraphVertex):
    """Stack along batch dim (reference StackVertex.java — used for sharing one layer
    across several inputs)."""

    def apply(self, params, state, inputs, *, train=False, rng=None, mask=None):
        return jnp.concatenate(inputs, axis=0), state


@register_config("UnstackVertex")
@dataclasses.dataclass
class UnstackVertex(GraphVertex):
    """Take slice ``index`` of ``num_stacks`` along batch dim (reference
    UnstackVertex.java)."""

    index: int = 0
    num_stacks: int = 1

    def apply(self, params, state, inputs, *, train=False, rng=None, mask=None):
        x = inputs[0]
        size = x.shape[0] // self.num_stacks
        return x[self.index * size:(self.index + 1) * size], state

    def n_inputs(self):
        return 1


@register_config("PreprocessorVertex")
@dataclasses.dataclass
class PreprocessorVertex(GraphVertex):
    """Apply an InputPreProcessor standalone (reference PreprocessorVertex.java)."""

    preprocessor: Optional[object] = None

    def apply(self, params, state, inputs, *, train=False, rng=None, mask=None):
        return self.preprocessor.pre_process(inputs[0], mask), state

    def output_type(self, itypes):
        return self.preprocessor.output_type(itypes[0])

    def n_inputs(self):
        return 1


@register_config("LastTimeStepVertex")
@dataclasses.dataclass
class LastTimeStepVertex(GraphVertex):
    """[B,T,F] -> [B,F] taking the last (or last-unmasked) step (reference
    rnn/LastTimeStepVertex.java)."""

    mask_input: Optional[str] = None

    def apply(self, params, state, inputs, *, train=False, rng=None, mask=None):
        x = inputs[0]
        if mask is not None:
            idx = jnp.maximum(jnp.sum(mask.astype(jnp.int32), axis=1) - 1, 0)
            return x[jnp.arange(x.shape[0]), idx], state
        return x[:, -1], state

    def output_type(self, itypes):
        return InputType.feed_forward(itypes[0].size)

    def n_inputs(self):
        return 1


@register_config("DuplicateToTimeSeriesVertex")
@dataclasses.dataclass
class DuplicateToTimeSeriesVertex(GraphVertex):
    """[B,F] -> [B,T,F] broadcast over time of a reference input (reference
    rnn/DuplicateToTimeSeriesVertex.java). Needs two inputs: (vector, timeseries)."""

    ts_input: Optional[str] = None

    def apply(self, params, state, inputs, *, train=False, rng=None, mask=None):
        x, ts = inputs[0], inputs[1]
        return jnp.broadcast_to(x[:, None, :], (x.shape[0], ts.shape[1], x.shape[-1])), state

    def output_type(self, itypes):
        return InputType.recurrent(itypes[0].flat_size(), itypes[1].timesteps)

    def n_inputs(self):
        return 2
