"""Config serialization: dataclass <-> JSON/YAML with a polymorphic type registry.

The reference relies on Jackson polymorphic subtype registration discovered by classpath
scan (reference nn/conf/NeuralNetConfiguration.java:329-476, ``registerSubtypes``:369) so
user-defined custom layers serialize. The TPU-native equivalent is an explicit registry:
``@register_config("Dense")`` adds a dataclass to the registry; ``to_dict`` stamps
``"@type"``; ``from_dict`` dispatches on it. Custom layers register the same way, no
scanning needed.

Config JSON is the checkpoint schema (reference util/ModelSerializer.java writes
configuration.json into the model zip) — keep it stable.
"""
from __future__ import annotations

import dataclasses
import enum
import json
from typing import Any, Optional, Type

_REGISTRY: dict[str, type] = {}
TYPE_KEY = "@type"


def register_config(name: Optional[str] = None):
    """Class decorator registering a dataclass config under ``name`` (default: class name)."""

    def wrap(cls):
        key = name or cls.__name__
        if key in _REGISTRY and _REGISTRY[key] is not cls:
            raise ValueError(f"Config type '{key}' already registered to {_REGISTRY[key]}")
        _REGISTRY[key] = cls
        cls._config_type_name = key
        return cls

    return wrap


def registered_name(cls: type) -> str:
    return getattr(cls, "_config_type_name", cls.__name__)


def lookup(name: str) -> type:
    if name not in _REGISTRY:
        raise KeyError(f"Unknown config type '{name}'. Registered: {sorted(_REGISTRY)}")
    return _REGISTRY[name]


def to_dict(obj: Any) -> Any:
    """Recursively convert a registered dataclass (or plain value) to JSON-native data."""
    if obj is None or isinstance(obj, (bool, int, float, str)):
        return obj
    if isinstance(obj, enum.Enum):
        return obj.value
    if isinstance(obj, (list, tuple)):
        return [to_dict(v) for v in obj]
    if isinstance(obj, dict):
        return {k: to_dict(v) for k, v in obj.items()}
    if dataclasses.is_dataclass(obj):
        d = {TYPE_KEY: registered_name(type(obj))}
        for f in dataclasses.fields(obj):
            if not f.metadata.get("serde", True):
                continue
            d[f.name] = to_dict(getattr(obj, f.name))
        return d
    raise TypeError(f"Cannot serialize {type(obj)} to config JSON")


def from_dict(data: Any) -> Any:
    """Inverse of to_dict: dispatch on '@type' for registered dataclasses."""
    if isinstance(data, list):
        return [from_dict(v) for v in data]
    if isinstance(data, dict):
        if TYPE_KEY in data:
            cls = lookup(data[TYPE_KEY])
            field_names = {f.name for f in dataclasses.fields(cls)}
            kwargs = {k: from_dict(v) for k, v in data.items()
                      if k != TYPE_KEY and k in field_names}
            return cls(**kwargs)
        return {k: from_dict(v) for k, v in data.items()}
    return data


def to_json(obj: Any, indent: int = 2) -> str:
    return json.dumps(to_dict(obj), indent=indent)


def from_json(s: str) -> Any:
    return from_dict(json.loads(s))


def to_yaml(obj: Any) -> str:
    """YAML serde (reference MultiLayerConfiguration.toYaml:79); gated on PyYAML."""
    import yaml  # baked into most images; gate at call time

    return yaml.safe_dump(to_dict(obj), sort_keys=False)


def from_yaml(s: str) -> Any:
    import yaml

    return from_dict(yaml.safe_load(s))
