"""ComputationGraphConfiguration + GraphBuilder DSL.

Reference: nn/conf/ComputationGraphConfiguration.java (664 LoC, GraphBuilder DSL used as
NeuralNetConfiguration.builder()...graphBuilder().addInputs("in").addLayer("L1", layer,
"in")...setOutputs("out")). Build-time work: topological sort, InputType propagation
through vertices (n_in inference + auto preprocessor insertion), validation.
"""
from __future__ import annotations

import dataclasses

from deeplearning4j_tpu.nn.conf.builders import GlobalConf, bake_layer_defaults
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers.base import Layer
from deeplearning4j_tpu.nn.conf.preprocessors import infer_preprocessor
from deeplearning4j_tpu.nn.conf.serde import register_config
from deeplearning4j_tpu.nn.conf import serde
from deeplearning4j_tpu.nn.conf.vertices import GraphVertex, LayerVertex, PreprocessorVertex


@register_config("ComputationGraphConfiguration")
@dataclasses.dataclass
class ComputationGraphConfiguration:
    global_conf: GlobalConf = dataclasses.field(default_factory=GlobalConf)
    vertices: dict = dataclasses.field(default_factory=dict)       # name -> GraphVertex
    vertex_inputs: dict = dataclasses.field(default_factory=dict)  # name -> [input names]
    network_inputs: list = dataclasses.field(default_factory=list)
    network_outputs: list = dataclasses.field(default_factory=list)
    input_types: list = dataclasses.field(default_factory=list)
    topological_order: list = dataclasses.field(default_factory=list)
    backprop: bool = True
    pretrain: bool = False
    backprop_type: str = "Standard"
    tbptt_fwd_length: int = 20
    tbptt_back_length: int = 20

    def to_json(self) -> str:
        return serde.to_json(self)

    @staticmethod
    def from_json(s: str) -> "ComputationGraphConfiguration":
        conf = serde.from_json(s)
        if not isinstance(conf, ComputationGraphConfiguration):
            raise ValueError("JSON does not encode a ComputationGraphConfiguration")
        return conf

    def topo_sort(self) -> list:
        """Kahn topological order over vertices (reference
        ComputationGraph.topologicalSortOrder:849)."""
        indeg = {name: 0 for name in self.vertices}
        children: dict[str, list] = {name: [] for name in self.vertices}
        for name, ins in self.vertex_inputs.items():
            for src in ins:
                if src in self.vertices:
                    indeg[name] += 1
                    children[src].append(name)
        ready = sorted(n for n, d in indeg.items() if d == 0)
        order = []
        while ready:
            n = ready.pop(0)
            order.append(n)
            for c in children[n]:
                indeg[c] -= 1
                if indeg[c] == 0:
                    ready.append(c)
            ready.sort()
        if len(order) != len(self.vertices):
            cyc = set(self.vertices) - set(order)
            raise ValueError(f"Graph has a cycle involving: {sorted(cyc)}")
        return order


class GraphBuilder:
    """Reference ComputationGraphConfiguration.GraphBuilder DSL."""

    def __init__(self, g: GlobalConf):
        self._g = g
        self._vertices: dict[str, GraphVertex] = {}
        self._vertex_inputs: dict[str, list] = {}
        self._inputs: list = []
        self._outputs: list = []
        self._input_types: list = []
        self._backprop = True
        self._pretrain = False
        self._backprop_type = "Standard"
        self._tbptt_fwd = 20
        self._tbptt_back = 20

    def add_inputs(self, *names: str) -> "GraphBuilder":
        self._inputs.extend(names)
        return self

    def add_layer(self, name: str, layer: Layer, *inputs: str) -> "GraphBuilder":
        bake_layer_defaults(layer, self._g)
        if layer.name is None:
            layer.name = name
        self._vertices[name] = LayerVertex(layer=layer)
        self._vertex_inputs[name] = list(inputs)
        return self

    def add_vertex(self, name: str, vertex: GraphVertex, *inputs: str) -> "GraphBuilder":
        self._vertices[name] = vertex
        self._vertex_inputs[name] = list(inputs)
        return self

    def set_outputs(self, *names: str) -> "GraphBuilder":
        self._outputs = list(names)
        return self

    def set_input_types(self, *itypes: InputType) -> "GraphBuilder":
        self._input_types = list(itypes)
        return self

    def backprop(self, flag: bool) -> "GraphBuilder":
        self._backprop = flag
        return self

    def pretrain(self, flag: bool) -> "GraphBuilder":
        self._pretrain = flag
        return self

    def backprop_type(self, t: str) -> "GraphBuilder":
        self._backprop_type = t
        return self

    def t_bptt_forward_length(self, n: int) -> "GraphBuilder":
        self._tbptt_fwd = n
        return self

    def t_bptt_backward_length(self, n: int) -> "GraphBuilder":
        self._tbptt_back = n
        return self

    def build(self) -> ComputationGraphConfiguration:
        from deeplearning4j_tpu.nn.conf.builders import validate_global_conf
        validate_global_conf(self._g)
        conf = ComputationGraphConfiguration(
            global_conf=self._g,
            vertices=self._vertices,
            vertex_inputs=self._vertex_inputs,
            network_inputs=self._inputs,
            network_outputs=self._outputs,
            input_types=self._input_types,
            backprop=self._backprop,
            pretrain=self._pretrain,
            backprop_type=self._backprop_type,
            tbptt_fwd_length=self._tbptt_fwd,
            tbptt_back_length=self._tbptt_back,
        )
        for out in conf.network_outputs:
            if out not in conf.vertices:
                raise ValueError(f"Output '{out}' is not a vertex")
        for name, ins in conf.vertex_inputs.items():
            for src in ins:
                if src not in conf.vertices and src not in conf.network_inputs:
                    raise ValueError(f"Vertex '{name}' input '{src}' undefined")
        conf.topological_order = conf.topo_sort()

        # InputType propagation: infer n_in + insert preprocessors inside LayerVertexes
        if self._input_types:
            types: dict[str, InputType] = dict(zip(conf.network_inputs, self._input_types))
            for name in conf.topological_order:
                v = conf.vertices[name]
                in_types = [types[src] for src in conf.vertex_inputs[name]]
                if isinstance(v, LayerVertex):
                    pp = infer_preprocessor(in_types[0], v.layer)
                    if pp is not None:
                        # wrap: preprocessor folded into the vertex via explicit chain
                        pre_name = f"{name}-preprocessor"
                        conf.vertices[pre_name] = PreprocessorVertex(preprocessor=pp)
                        conf.vertex_inputs[pre_name] = conf.vertex_inputs[name]
                        conf.vertex_inputs[name] = [pre_name]
                        in_types = [pp.output_type(in_types[0])]
                    v.layer.set_n_in(in_types[0])
                types[name] = v.output_type(in_types)
            conf.topological_order = conf.topo_sort()
        return conf
