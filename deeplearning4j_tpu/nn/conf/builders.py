"""NeuralNetConfiguration: global training hyperparameters + fluent builder DSL.

Reference: nn/conf/NeuralNetConfiguration.java:478-1100 (Builder), :194-327 (ListBuilder).
Builder method names match the reference's (snake_cased) so configs translate 1:1:

    conf = (NeuralNetConfiguration.builder()
            .seed(123).learning_rate(0.1).updater("nesterovs").momentum(0.9)
            .weight_init("xavier").activation("relu")
            .list()
            .layer(DenseLayer(n_out=500))
            .layer(OutputLayer(n_out=10, loss="mcxent", activation="softmax"))
            .set_input_type(InputType.convolutional_flat(28, 28, 1))
            .backprop(True).pretrain(False)
            .build())

Global defaults are *baked into* each layer at build() (the reference clones the config
per layer the same way), so a serialized MultiLayerConfiguration is self-contained.
"""
from __future__ import annotations

import dataclasses
from typing import Optional

from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.serde import register_config
from deeplearning4j_tpu.nn.conf.layers.base import Layer
from deeplearning4j_tpu.nn.conf.preprocessors import InputPreProcessor, infer_preprocessor


@register_config("GlobalConf")
@dataclasses.dataclass
class GlobalConf:
    """Network-wide defaults (reference NeuralNetConfiguration fields :84-121)."""

    seed: int = 12345
    optimization_algo: str = "stochastic_gradient_descent"
    iterations: int = 1                 # updates per presented minibatch (DL4J semantics)
    learning_rate: float = 0.1
    bias_learning_rate: Optional[float] = None
    lr_policy: Optional[str] = None     # exponential|inverse|poly|sigmoid|step|schedule
    lr_policy_decay_rate: float = 0.0
    lr_policy_power: float = 0.0
    lr_policy_steps: float = 1.0
    lr_schedule: Optional[dict] = None
    max_num_iterations: int = 1         # for poly policy
    updater: str = "sgd"
    momentum: float = 0.9
    momentum_schedule: Optional[dict] = None
    rho: float = 0.95
    rms_decay: float = 0.95
    adam_mean_decay: float = 0.9
    adam_var_decay: float = 0.999
    epsilon: float = 1e-8
    activation: str = "sigmoid"
    weight_init: str = "xavier"
    dist: Optional[dict] = None
    bias_init: float = 0.0
    l1: float = 0.0
    l2: float = 0.0
    dropout: float = 0.0
    gradient_normalization: Optional[str] = None
    gradient_normalization_threshold: float = 1.0
    minibatch: bool = True
    mini_batch: bool = True
    use_regularization: bool = False
    max_num_line_search_iterations: int = 5
    #: rematerialize per-layer activations in backward (jax.checkpoint):
    #: trades recompute FLOPs for activation HBM — the TPU-native memory
    #: lever for deep/long-sequence models (no reference equivalent; the
    #: JVM runtime keeps all activations)
    gradient_checkpointing: bool = False
    #: per-network dtype policy, serialized with the config (the reference's
    #: one global Nd4j data type, made declarative): None -> whatever global
    #: policy is active (common.set_policy); "float32"; "bfloat16" (bf16
    #: matmul/conv, f32 activations); "bfloat16_full" (bf16 activations too,
    #: f32 params/norm-stats/losses — common.full_bf16_policy semantics)
    dtype: Optional[str] = None


def validate_global_conf(g: GlobalConf) -> None:
    """Fail fast on config-string typos at build time, not first trace."""
    if g.dtype is not None:
        from deeplearning4j_tpu import common
        common.resolve_policy(g.dtype)  # raises ValueError with known names


_LAYER_INHERIT_FIELDS = (
    "activation", "weight_init", "dist", "l1", "l2", "dropout",
    "learning_rate", "bias_learning_rate", "updater", "momentum", "rho", "rms_decay",
    "adam_mean_decay", "adam_var_decay", "epsilon",
    "gradient_normalization", "gradient_normalization_threshold",
)


def bake_layer_defaults(layer: Layer, g: GlobalConf) -> None:
    """Fill a layer's None fields from global defaults (reference config cloning)."""
    for f in _LAYER_INHERIT_FIELDS:
        if getattr(layer, f, None) is None:
            gval = getattr(g, f, None)
            if f == "learning_rate":
                gval = g.learning_rate
            if f == "bias_learning_rate":
                gval = g.bias_learning_rate if g.bias_learning_rate is not None else g.learning_rate
                if getattr(layer, "learning_rate", None) is not None:
                    gval = layer.learning_rate
            setattr(layer, f, gval)
    if layer.bias_init is None:
        layer.bias_init = g.bias_init


class NeuralNetConfiguration:
    """Namespace mirroring the reference class; use NeuralNetConfiguration.builder()."""

    @staticmethod
    def builder() -> "Builder":
        return Builder()


class Builder:
    def __init__(self):
        self._g = GlobalConf()

    def __getattr__(self, name):
        """Fluent setter for any GlobalConf field: .seed(1).learning_rate(0.1)..."""
        if name.startswith("_"):
            raise AttributeError(name)
        fields = {f.name for f in dataclasses.fields(GlobalConf)}
        if name in fields:
            def setter(value):
                setattr(self._g, name, value)
                if name == "mini_batch":
                    self._g.minibatch = value
                return self
            return setter
        # aliases matching reference camelCase conventions
        aliases = {
            "regularization": "use_regularization",
            "optimizationAlgo": "optimization_algo",
        }
        if name in aliases:
            def setter(value):
                setattr(self._g, aliases[name], value)
                return self
            return setter
        raise AttributeError(f"No config field '{name}'")

    def list(self) -> "ListBuilder":
        return ListBuilder(self._g)

    def graph_builder(self):
        from deeplearning4j_tpu.nn.conf.graphconf import GraphBuilder
        return GraphBuilder(self._g)

    def global_conf(self) -> GlobalConf:
        return self._g


class ListBuilder:
    """Sequential-network builder (reference NeuralNetConfiguration.ListBuilder:194-327)."""

    def __init__(self, g: GlobalConf):
        self._g = g
        self._layers: list[Layer] = []
        self._preprocessors: dict[int, InputPreProcessor] = {}
        self._input_type: Optional[InputType] = None
        self._backprop = True
        self._pretrain = False
        self._backprop_type = "Standard"
        self._tbptt_fwd = 20
        self._tbptt_back = 20

    def layer(self, idx_or_layer, maybe_layer: Optional[Layer] = None) -> "ListBuilder":
        layer = maybe_layer if maybe_layer is not None else idx_or_layer
        if maybe_layer is not None:
            assert idx_or_layer == len(self._layers), "layers must be added in order"
        self._layers.append(layer)
        return self

    def input_pre_processor(self, idx: int, pp: InputPreProcessor) -> "ListBuilder":
        self._preprocessors[idx] = pp
        return self

    def set_input_type(self, itype: InputType) -> "ListBuilder":
        self._input_type = itype
        return self

    def backprop(self, flag: bool) -> "ListBuilder":
        self._backprop = flag
        return self

    def pretrain(self, flag: bool) -> "ListBuilder":
        self._pretrain = flag
        return self

    def backprop_type(self, t: str) -> "ListBuilder":
        self._backprop_type = t
        return self

    def t_bptt_forward_length(self, n: int) -> "ListBuilder":
        self._tbptt_fwd = n
        return self

    def t_bptt_backward_length(self, n: int) -> "ListBuilder":
        self._tbptt_back = n
        return self

    def build(self):
        from deeplearning4j_tpu.nn.conf.multilayer import MultiLayerConfiguration

        validate_global_conf(self._g)
        for layer in self._layers:
            bake_layer_defaults(layer, self._g)

        # propagate input types: infer preprocessors + n_in per layer
        if self._input_type is not None:
            cur = self._input_type
            for i, layer in enumerate(self._layers):
                if i not in self._preprocessors:
                    pp = infer_preprocessor(cur, layer)
                    if pp is not None:
                        self._preprocessors[i] = pp
                if i in self._preprocessors:
                    cur = self._preprocessors[i].output_type(cur)
                layer.set_n_in(cur)
                cur = layer.output_type(cur)

        return MultiLayerConfiguration(
            global_conf=self._g,
            layers=self._layers,
            preprocessors={str(k): v for k, v in self._preprocessors.items()},
            input_type=self._input_type,
            backprop=self._backprop,
            pretrain=self._pretrain,
            backprop_type=self._backprop_type,
            tbptt_fwd_length=self._tbptt_fwd,
            tbptt_back_length=self._tbptt_back,
        )
