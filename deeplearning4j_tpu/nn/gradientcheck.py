"""Numeric-vs-analytic gradient checking — the correctness backbone.

Reference: gradientcheck/GradientCheckUtil.java:62 (MLN), :194 (CG), :305 (pretrain) —
central finite-difference comparison used by the whole reference test suite
(SURVEY.md §4). Same contract here: perturb each parameter by +/-eps in float64,
compare (f(p+eps)-f(p-eps))/(2 eps) against the autodiff gradient, fail if max
relative error exceeds ``max_rel_error`` (absolute-error escape hatch for tiny grads).

Runs on CPU in float64 via jax.experimental.enable_x64 for numerical headroom —
float32 finite differences are too noisy for 1e-6-level checks.
"""
from __future__ import annotations

import logging
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu import jax_compat
from deeplearning4j_tpu.utils.pytree import flatten_params, unflatten_params

log = logging.getLogger(__name__)


def check_gradients(net, x, y, *, eps: float = 1e-6, max_rel_error: float = 1e-3,
                    min_abs_error: float = 1e-8, subset: Optional[int] = None,
                    seed: int = 0, verbose: bool = False) -> bool:
    """Gradient-check a MultiLayerNetwork (or any object exposing
    gradient_and_score + params_list). Checks ``subset`` randomly-chosen parameters
    (all if None).
    """
    from deeplearning4j_tpu import common

    saved_policy = common.get_policy()
    common.set_policy(jnp.float64, jnp.float64, jnp.float64)
    try:
        return _check_gradients_x64(net, x, y, eps=eps, max_rel_error=max_rel_error,
                                    min_abs_error=min_abs_error, subset=subset,
                                    seed=seed, verbose=verbose)
    finally:
        common._POLICY = saved_policy


def check_pretrain_gradients(net, layer_idx: int, x, *, eps: float = 1e-6,
                             max_rel_error: float = 1e-3,
                             min_abs_error: float = 1e-8,
                             subset: Optional[int] = None, seed: int = 0,
                             rng_seed: int = 5, verbose: bool = False) -> bool:
    """Gradient-check one pretrain layer's unsupervised objective (reference
    GradientCheckUtil.checkGradientsPretrainLayer:305): forward the input to
    the layer, then finite-difference ``pretrain_loss`` wrt THAT layer's
    params against autodiff, with the sampling rng held fixed so the
    objective is a deterministic function of the parameters."""
    from deeplearning4j_tpu import common

    saved_policy = common.get_policy()
    common.set_policy(jnp.float64, jnp.float64, jnp.float64)
    try:
        with jax_compat.enable_x64(True):
            layer = net.conf.layers[layer_idx]
            params64 = jax.tree_util.tree_map(
                lambda a: jnp.asarray(np.asarray(a), jnp.float64),
                net.params_list)
            h = jnp.asarray(np.asarray(x), jnp.float64)
            for i in range(layer_idx):
                pp = net.conf.preprocessor(i)
                if pp is not None:
                    h = pp.pre_process(h)
                h, _ = net.conf.layers[i].apply(
                    params64[i], net.state_list[i], h, train=False, rng=None)
            pp = net.conf.preprocessor(layer_idx)
            if pp is not None:
                h = pp.pre_process(h)
            key = jax.random.PRNGKey(rng_seed)

            def score(p_layer):
                return layer.pretrain_loss(p_layer, h, rng=key)

            return _fd_check_subtree(score, params64[layer_idx], eps=eps,
                                     max_rel_error=max_rel_error,
                                     min_abs_error=min_abs_error,
                                     subset=subset, seed=seed, verbose=verbose,
                                     tag="pretrain")
    finally:
        common._POLICY = saved_policy


def check_graph_pretrain_gradients(net, vertex_name: str, xs, *,
                                   eps: float = 1e-6,
                                   max_rel_error: float = 1e-3,
                                   min_abs_error: float = 1e-8,
                                   subset: Optional[int] = None, seed: int = 0,
                                   rng_seed: int = 5,
                                   verbose: bool = False) -> bool:
    """ComputationGraph twin of check_pretrain_gradients (reference
    GradientCheckUtil.checkGradientsPretrainLayer:305 applied to graph
    vertices): evaluate the vertex's ancestors in f64 eval mode, then
    finite-difference its pretrain objective wrt that vertex's params."""
    from deeplearning4j_tpu import common
    from deeplearning4j_tpu.nn.graph_network import eval_forward_to_vertex

    saved_policy = common.get_policy()
    common.set_policy(jnp.float64, jnp.float64, jnp.float64)
    try:
        with jax_compat.enable_x64(True):
            conf = net.conf
            layer = conf.vertices[vertex_name].layer
            params64 = jax.tree_util.tree_map(
                lambda a: jnp.asarray(np.asarray(a), jnp.float64),
                net.params_list)
            inputs64 = [jnp.asarray(np.asarray(x), jnp.float64) for x in xs]
            h = eval_forward_to_vertex(conf, params64, net.state_list,
                                       inputs64, vertex_name)
            key = jax.random.PRNGKey(rng_seed)

            def score(p_vertex):
                return layer.pretrain_loss(p_vertex, h, rng=key)

            return _fd_check_subtree(score, params64[vertex_name], eps=eps,
                                     max_rel_error=max_rel_error,
                                     min_abs_error=min_abs_error,
                                     subset=subset, seed=seed, verbose=verbose,
                                     tag=f"graph pretrain[{vertex_name}]")
    finally:
        common._POLICY = saved_policy


def _fd_check_subtree(score, params_subtree, *, eps, max_rel_error,
                      min_abs_error, subset, seed, verbose, tag) -> bool:
    """Central finite-difference vs autodiff over one params subtree (the
    shared core of the MLN and CG pretrain checkers)."""
    analytic = jax.grad(score)(params_subtree)
    flat_analytic = np.asarray(flatten_params(analytic), np.float64)
    flat_params = np.asarray(flatten_params(params_subtree), np.float64)
    n = len(flat_params)
    if subset is not None and subset < n:
        indices = np.random.default_rng(seed).choice(n, subset, replace=False)
    else:
        indices = np.arange(n)
    score_jit = jax.jit(lambda flat: score(  # lint: adhoc-jit-ok (float64 finite-difference probe outside every dtype policy; never serves, never warm-starts)
        unflatten_params(params_subtree, flat)))
    fails = 0
    max_err = 0.0
    for i in indices:
        plus = flat_params.copy()
        plus[i] += eps
        minus = flat_params.copy()
        minus[i] -= eps
        numeric = (float(score_jit(jnp.asarray(plus)))
                   - float(score_jit(jnp.asarray(minus)))) / (2 * eps)
        a = flat_analytic[i]
        denom = max(abs(numeric), abs(a))
        rel = abs(numeric - a) / denom if denom > 0 else 0.0
        if rel > max_rel_error and abs(numeric - a) > min_abs_error:
            fails += 1
            if verbose:
                log.info("param %d: analytic=%.8g numeric=%.8g rel=%.3g",
                         i, a, numeric, rel)
        max_err = max(max_err,
                      rel if abs(numeric - a) > min_abs_error else 0.0)
    if verbose:
        log.info("%s gradient check: %d params, max rel err %.3g, "
                 "%d failures", tag, len(indices), max_err, fails)
    return fails == 0


def _check_gradients_x64(net, x, y, *, eps, max_rel_error, min_abs_error, subset,
                         seed, verbose) -> bool:
    with jax_compat.enable_x64(True):
        params64 = jax.tree_util.tree_map(
            lambda a: jnp.asarray(np.asarray(a), jnp.float64), net.params_list)
        x64 = jnp.asarray(np.asarray(x), jnp.float64)
        y64 = jnp.asarray(np.asarray(y), jnp.float64)
        state64 = jax.tree_util.tree_map(
            lambda a: jnp.asarray(np.asarray(a), jnp.float64), net.state_list)

        from deeplearning4j_tpu.nn.multilayer import loss_fn

        def score(p):
            loss, _ = loss_fn(net.conf, p, state64, x64, y64, None, None, None)
            return loss

        analytic = jax.grad(score)(params64)
        flat_analytic = np.asarray(flatten_params(analytic), np.float64)
        flat_params = np.asarray(flatten_params(params64), np.float64)

        n = len(flat_params)
        if subset is not None and subset < n:
            rng = np.random.default_rng(seed)
            indices = rng.choice(n, subset, replace=False)
        else:
            indices = np.arange(n)

        score_jit = jax.jit(lambda flat: score(unflatten_params(params64, flat)))  # lint: adhoc-jit-ok (float64 finite-difference probe outside every dtype policy; never serves, never warm-starts)

        max_err = 0.0
        fails = 0
        for i in indices:
            plus = flat_params.copy()
            plus[i] += eps
            minus = flat_params.copy()
            minus[i] -= eps
            numeric = (float(score_jit(jnp.asarray(plus)))
                       - float(score_jit(jnp.asarray(minus)))) / (2 * eps)
            a = flat_analytic[i]
            denom = max(abs(numeric), abs(a))
            rel = abs(numeric - a) / denom if denom > 0 else 0.0
            if rel > max_rel_error and abs(numeric - a) > min_abs_error:
                fails += 1
                if verbose:
                    log.info("param %d: analytic=%.8g numeric=%.8g "
                             "rel=%.3g", i, a, numeric, rel)
            max_err = max(max_err, rel if abs(numeric - a) > min_abs_error else 0.0)
        if verbose:
            log.info("gradient check: %d params, max rel err %.3g, "
                     "%d failures", len(indices), max_err, fails)
        return fails == 0
