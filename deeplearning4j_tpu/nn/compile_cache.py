"""Warm-start compile plane: persistent executable cache + AOT warmup.

Every cold path in the system is compile-bound — elastic respawn recovery,
replica spawn and hot swap, decode-engine bucket growth all stall on XLA
rebuilding programs it has already built in a previous process (or an
earlier version of the same model). The reference stack never pays this
tax twice: cuDNN persists its algorithm-selection cache and DL4J
pre-allocates workspaces before training starts. This module is that
analog for the jit seams.

Two halves:

* ``CompileCache`` — a bounded on-disk store of serialized XLA executables
  (``jax.experimental.serialize_executable``), keyed by a fingerprint of
  everything that could change the compiled program: abstract input
  signature, donation config, seam cache key (dtype policy et al.), model
  config hash, jax version, backend platform/device kind/device count.
  Writes are atomic (tmp + ``os.replace``); torn, truncated, or
  version-mismatched entries are quarantined and fall back to a normal
  compile — corruption is never an error, only a cache miss.

* ``CachedProgram`` — the callable the three compile seams hand out
  (``LazyScore._jit``, ``compile_seam.compile_step``, and through them the
  decode engine's per-bucket step builders). Per abstract signature it
  resolves ONE executable: disk hit -> ``deserialize_and_load`` (recorded
  as a cache-hit compile so storm warnings don't fire), miss ->
  ``jitted.lower().compile()`` AOT, serialized back to disk. Dispatch
  after resolution is a dict lookup + the executable call — measured at
  parity with jit's own dispatch on CPU. ``warm()`` resolves a signature
  from ShapeDtypeStructs without executing, which is what parallel AOT
  warmup (ModelRegistry pin, ReplicaSet construction, decode pre-warm)
  builds on.

Kill switch: ``DL4J_COMPILE_CACHE=0`` makes ``build_program`` return the
exact pre-existing ``tracker.wrap(jax.jit(...))`` path — no disk, no AOT.
``DL4J_COMPILE_CACHE_DIR`` overrides the store location (the test suite
points it at a per-test tmp dir; elastic ships the resolved dir to spawned
workers). ``DL4J_COMPILE_CACHE_EPOCH`` salts the fingerprint for manual
invalidation without deleting files.
"""
from __future__ import annotations

import hashlib
import logging
import os
import pickle
import tempfile
import threading
import time
from typing import Any, Callable, Dict, Optional, Tuple

from deeplearning4j_tpu.observability.compile_tracker import (_signature,
                                                              global_tracker)
from deeplearning4j_tpu.observability.metrics import global_registry
from deeplearning4j_tpu.observability.names import (
    COMPILE_CACHE_BYTES, COMPILE_CACHE_HITS_TOTAL, COMPILE_CACHE_LOAD_SECONDS,
    COMPILE_CACHE_MISSES_TOTAL, WARMUP_SECONDS)

log = logging.getLogger(__name__)

#: on-disk entry format: MAGIC + sha256(body) + body. Bump the magic when
#: the pickle layout changes — old entries then read as version-mismatched
#: and are quarantined on first touch.
MAGIC = b"DL4JXC01"
_DIGEST_LEN = 32

_DEFAULT_MAX_MB = 512.0


def enabled() -> bool:
    """The kill switch: ``DL4J_COMPILE_CACHE=0`` restores the plain
    ``tracker.wrap(jax.jit(...))`` compile path everywhere."""
    return os.environ.get("DL4J_COMPILE_CACHE", "1").lower() \
        not in ("0", "off", "false")


def cache_dir() -> str:
    """Resolved store directory (not necessarily created yet)."""
    d = os.environ.get("DL4J_COMPILE_CACHE_DIR")
    if d:
        return d
    return os.path.join(os.path.expanduser("~"), ".cache",
                        "deeplearning4j_tpu", "executables")


def _max_bytes() -> int:
    try:
        mb = float(os.environ.get("DL4J_COMPILE_CACHE_MAX_MB",
                                  _DEFAULT_MAX_MB))
    except ValueError:
        mb = _DEFAULT_MAX_MB
    return int(mb * 1024 * 1024)


def _backend_key() -> Tuple:
    """Everything about the runtime that invalidates an executable: jax
    version, backend platform, device kind, and visible device count
    (a parent on an 8-device host mesh and its 1-device elastic child
    must never share entries)."""
    import jax

    devs = jax.devices()
    return (jax.__version__, jax.default_backend(),
            devs[0].device_kind if devs else "none", len(devs))


def conf_fingerprint(conf: Any) -> str:
    """Stable hash of a model configuration (serde JSON when available).
    Two structurally identical models hit each other's entries; any config
    edit — layer sizes, updater, loss — misses."""
    if conf is None:
        return "none"
    try:
        from deeplearning4j_tpu.nn.conf import serde

        return hashlib.sha256(
            serde.to_json(conf).encode("utf-8")).hexdigest()[:16]
    except Exception:
        try:
            return hashlib.sha256(repr(conf).encode("utf-8")).hexdigest()[:16]
        except Exception:
            return type(conf).__name__


def _placement_key(args: tuple, kwargs: dict) -> Optional[Tuple]:
    """Per-leaf input sharding reprs. An AOT ``Compiled`` strictly requires
    the placements it was built with — where jit would quietly re-dispatch
    (and recompile) for a resharded input, the cache must resolve a sibling
    executable. Kept separate from the tracker's shape/dtype ``_signature``
    so compile-storm accounting granularity is unchanged."""
    try:
        import jax

        leaves, _ = jax.tree_util.tree_flatten((args, kwargs))
        out = []
        for leaf in leaves:
            if hasattr(leaf, "shape") and hasattr(leaf, "dtype"):
                s = getattr(leaf, "sharding", None)
                # single-device placement normalizes to None: a host numpy
                # array and the device array a step handed back are the
                # same program to jit AND to the strict Compiled check —
                # only genuinely sharded (mesh) inputs need siblings
                if s is None or type(s).__name__ == "SingleDeviceSharding":
                    out.append(None)
                else:
                    out.append(repr(s))
        return tuple(out)
    except Exception:
        return None


def _flight(kind: str, **fields) -> None:
    try:
        from deeplearning4j_tpu.observability.flight_recorder import \
            global_recorder

        global_recorder().record(kind, **fields)
    except Exception:  # pragma: no cover - recorder import cycle guard  # lint: swallowed-exception-ok (flight forwarding is best-effort)
        pass


def observe_warmup(site: str, seconds: float) -> None:
    """Record one warmup pass in ``dl4j_warmup_seconds{site=}``."""
    global_registry().histogram(
        WARMUP_SECONDS,
        "wall time of one AOT warmup pass (all buckets, cache-backed)"
    ).labels(site=site).observe(seconds)


def warm_parallel(thunks, *, site: str, workers: int = 4) -> float:
    """Run warmup thunks concurrently (thread pool — compiles release the
    GIL inside XLA) and observe the total in ``dl4j_warmup_seconds``.
    Individual thunk failures are logged and swallowed: warmup is an
    optimization, never a correctness gate. Returns elapsed seconds."""
    from concurrent.futures import ThreadPoolExecutor

    thunks = list(thunks)
    t0 = time.perf_counter()
    if thunks:
        with ThreadPoolExecutor(
                max_workers=max(1, min(workers, len(thunks))),
                thread_name_prefix="dl4j-warmup") as ex:
            for fut in [ex.submit(t) for t in thunks]:
                try:
                    fut.result()
                except Exception as e:
                    log.debug("warmup thunk failed: %r", e)
    elapsed = time.perf_counter() - t0
    observe_warmup(site, elapsed)
    return elapsed


class CompileCache:
    """Bounded on-disk store of serialized executables.

    All operations are best-effort and never raise into the compile path:
    a failed read is a miss, a failed write is a no-op, a corrupt entry is
    deleted and flight-recorded.
    """

    def __init__(self, directory: Optional[str] = None,
                 max_bytes: Optional[int] = None):
        self.directory = directory or cache_dir()
        self.max_bytes = _max_bytes() if max_bytes is None else max_bytes
        self._lock = threading.Lock()

    def entry_path(self, fp_hex: str) -> str:
        return os.path.join(self.directory, fp_hex + ".xc")

    # ------------------------------------------------------------- read
    def get(self, fp_hex: str, name: str) -> Optional[tuple]:
        """-> (payload, in_tree, out_tree) or None. Any validation failure
        (bad magic, truncation, digest mismatch, unpicklable body)
        quarantines the entry and reads as a miss."""
        path = self.entry_path(fp_hex)
        try:
            with open(path, "rb") as f:
                raw = f.read()
        except OSError:
            return None
        why = None
        if len(raw) < len(MAGIC) + _DIGEST_LEN:
            why = "truncated"
        elif not raw.startswith(MAGIC):
            why = "version-mismatch"
        else:
            body = raw[len(MAGIC) + _DIGEST_LEN:]
            digest = raw[len(MAGIC):len(MAGIC) + _DIGEST_LEN]
            if hashlib.sha256(body).digest() != digest:
                why = "digest-mismatch"
            else:
                try:
                    payload, in_tree, out_tree, _meta = pickle.loads(body)
                    return (payload, in_tree, out_tree)
                except Exception as e:
                    why = f"unpicklable: {e!r}"
        self.quarantine(fp_hex, name=name, why=why)
        return None

    def quarantine(self, fp_hex: str, *, name: str, why: str) -> None:
        """Delete a bad entry and leave a flight-recorder trail; the caller
        falls back to a normal compile."""
        log.warning("compile cache entry %s for %s is unusable (%s); "
                    "falling back to fresh compile", fp_hex[:12], name, why)
        _flight("compile_cache_fallback", fn=name, fingerprint=fp_hex,
                why=why)
        try:
            os.remove(self.entry_path(fp_hex))
        except OSError:  # lint: swallowed-exception-ok (entry already gone or unremovable — either way it reads as a miss)
            pass

    # ------------------------------------------------------------ write
    def put(self, fp_hex: str, payload: bytes, in_tree, out_tree,
            meta: dict) -> None:
        try:
            body = pickle.dumps((payload, in_tree, out_tree, meta))
            os.makedirs(self.directory, exist_ok=True)
            fd, tmp = tempfile.mkstemp(dir=self.directory, suffix=".tmp")
            try:
                with os.fdopen(fd, "wb") as f:
                    f.write(MAGIC)
                    f.write(hashlib.sha256(body).digest())
                    f.write(body)
                os.replace(tmp, self.entry_path(fp_hex))
            except BaseException:
                try:
                    os.remove(tmp)
                except OSError:  # lint: swallowed-exception-ok (tmp-file cleanup on a failed write; the write error itself is re-raised)
                    pass
                raise
            self._prune()
        except Exception as e:
            log.debug("compile cache write failed for %s: %r", fp_hex, e)

    def _prune(self) -> None:
        """Keep the store under ``max_bytes`` by evicting oldest-mtime
        entries; publishes the resulting size gauge."""
        with self._lock:
            try:
                entries = []
                total = 0
                with os.scandir(self.directory) as it:
                    for de in it:
                        if not de.name.endswith(".xc"):
                            continue
                        st = de.stat()
                        entries.append((st.st_mtime, st.st_size, de.path))
                        total += st.st_size
                if total > self.max_bytes:
                    for _mt, size, path in sorted(entries):
                        if total <= self.max_bytes:
                            break
                        try:
                            os.remove(path)
                            total -= size
                        except OSError:  # lint: swallowed-exception-ok (concurrent prune/eviction races are benign — the entry is gone either way)
                            pass
                global_registry().gauge(
                    COMPILE_CACHE_BYTES,
                    "on-disk size of the executable cache").set(total)
            except OSError:  # lint: swallowed-exception-ok (size accounting is best-effort; a vanished dir must not fail a compile)
                pass


_instances_lock = threading.Lock()
_instances: Dict[str, CompileCache] = {}


def global_cache() -> CompileCache:
    """Store for the currently-resolved directory (env-sensitive: tests
    repoint ``DL4J_COMPILE_CACHE_DIR`` per test and get a fresh store)."""
    d = cache_dir()
    with _instances_lock:
        cache = _instances.get(d)
        if cache is None:
            cache = _instances[d] = CompileCache(d)
        return cache


class CachedProgram:
    """Callable seam product: per (abstract signature, input placement),
    one executable — disk-hit deserialized, or AOT-compiled and serialized
    back. Falls back to a plain tracked jit call if anything in the AOT
    path fails."""

    def __init__(self, name: str, jitted: Callable, *,
                 fingerprint: Optional[str] = None, cache_key: Any = None,
                 conf: Any = None, extra: Tuple = (),
                 tracker=None, cache: Optional[CompileCache] = None):
        self._name = name
        self._jitted = jitted
        #: fingerprint identity is deliberately separate from the display
        #: name: hot-swap versions (``@v2``) and replica ranks (``~r1``)
        #: decorate the name but must share warm entries
        self._fingerprint_name = fingerprint or name
        self._cache_key = cache_key
        self._extra = extra
        self._conf_fp = conf_fingerprint(conf)
        self._tracker = tracker
        self._cache = cache
        self._ready: Dict[Tuple, Callable] = {}
        self._lock = threading.Lock()
        self._sig_locks: Dict[Tuple, threading.Lock] = {}
        self._fallback: Optional[Callable] = None
        #: whether the LAST executable resolve was a persistent-cache hit
        #: (None until a signature resolves); dispatch trace spans read it
        self.cache_hit: Optional[bool] = None
        self.__name__ = getattr(jitted, "__name__", name)
        self.__wrapped__ = jitted

    # ---------------------------------------------------------- plumbing
    def _tr(self):
        return self._tracker if self._tracker is not None else global_tracker()

    def _store(self) -> CompileCache:
        return self._cache if self._cache is not None else global_cache()

    def _sig_lock(self, sig: Tuple) -> threading.Lock:
        with self._lock:
            lock = self._sig_locks.get(sig)
            if lock is None:
                lock = self._sig_locks[sig] = threading.Lock()
            return lock

    def _plain(self) -> Callable:
        """Shared tracked-jit fallback for unhashable signatures or AOT
        failures — identical to the kill-switch path."""
        with self._lock:
            if self._fallback is None:
                self._fallback = self._tr().wrap(
                    self._name, self._jitted, cache_key=self._cache_key)
            return self._fallback

    def _fp_hex(self, sig: Tuple, pk: Optional[Tuple] = None) -> Optional[str]:
        # cache_key is deliberately NOT part of the material: seams build it
        # from display names that carry per-instance decoration (@version,
        # ~replica). Fingerprint-relevant key parts (dtype policy, rule set,
        # donation, specs) arrive via ``extra``; ``pk`` keeps differently
        # placed (sharded) callers on sibling entries.
        try:
            material = repr((MAGIC, _backend_key(),
                             os.environ.get("DL4J_COMPILE_CACHE_EPOCH", ""),
                             self._fingerprint_name, sig, pk,
                             self._conf_fp, self._extra))
            return hashlib.sha256(material.encode("utf-8")).hexdigest()
        except Exception:
            return None

    # ---------------------------------------------------------- resolve
    def _entry(self, args: tuple,
               kwargs: dict) -> Tuple[Optional[Tuple], Callable]:
        try:
            sig = _signature(args, kwargs)
        except Exception:
            sig = None
        if sig is None:
            return None, self._plain()
        key = (sig, _placement_key(args, kwargs))
        entry = self._ready.get(key)
        if entry is not None:
            return key, entry
        with self._sig_lock(key):
            entry = self._ready.get(key)
            if entry is None:
                entry = self._build(sig, key[1], args, kwargs)
                # lint: lockguard-ok (one writer per key under its per-signature lock; the dict store is GIL-atomic and the lock-free fast path tolerates a miss)
                self._ready[key] = entry
        return key, entry

    def _build(self, sig: Tuple, pk: Optional[Tuple], args: tuple,
               kwargs: dict) -> Callable:
        tracker = self._tr()
        tracker._ensure_monitoring()
        fp = self._fp_hex(sig, pk)
        store = self._store()
        reg = global_registry()

        # disk hit: deserialize instead of compiling
        if fp is not None:
            t0 = time.perf_counter()
            got = store.get(fp, self._name)
            if got is not None:
                try:
                    from jax.experimental import serialize_executable as se

                    compiled = se.deserialize_and_load(*got)
                    load_s = time.perf_counter() - t0
                    reg.counter(
                        COMPILE_CACHE_HITS_TOTAL,
                        "executables loaded from the compile cache"
                    ).labels(fn=self._name).inc()
                    reg.histogram(
                        COMPILE_CACHE_LOAD_SECONDS,
                        "deserialize_and_load wall time on cache hits"
                    ).labels(fn=self._name).observe(load_s)
                    tracker.record_compile(
                        self._name, cache_key=self._cache_key, wall_s=load_s,
                        shapes=sig[0], cache_hit=True)
                    tracker.note_executable(self._name, compiled)
                    self.cache_hit = True
                    return compiled
                except Exception as e:
                    store.quarantine(fp, name=self._name,
                                     why=f"deserialize failed: {e!r}")

        # miss: AOT compile, then persist. The jit dispatch cache is NOT
        # populated by AOT compilation, so the Compiled object itself is
        # what dispatches from here on (parity measured with jit dispatch).
        stack = getattr(tracker._active, "stack", None)
        if stack is None:
            stack = tracker._active.stack = []
        stack.append(self._name)
        t0 = time.perf_counter()
        try:
            compiled = self._jitted.lower(*args, **kwargs).compile()
        except Exception as e:
            log.debug("AOT compile failed for %s (%r); using plain jit",
                      self._name, e)
            return self._plain()
        finally:
            stack.pop()
        wall = time.perf_counter() - t0
        reg.counter(COMPILE_CACHE_MISSES_TOTAL,
                    "compile-cache misses (fresh XLA compiles)"
                    ).labels(fn=self._name).inc()
        tracker.record_compile(self._name, cache_key=self._cache_key,
                               wall_s=wall, shapes=sig[0], cache_hit=False)
        tracker.note_executable(self._name, compiled)
        self.cache_hit = False
        if fp is not None:
            try:
                from jax.experimental import serialize_executable as se

                payload, in_tree, out_tree = se.serialize(compiled)
                store.put(fp, payload, in_tree, out_tree,
                          {"fn": self._fingerprint_name,
                           "wall_s": wall, "shapes": repr(sig[0])})
            except Exception as e:
                log.debug("serialize failed for %s: %r", self._name, e)
        return compiled

    # ------------------------------------------------------------ public
    def __call__(self, *args, **kwargs):
        key, entry = self._entry(args, kwargs)
        try:
            return entry(*args, **kwargs)
        except ValueError as e:
            msg = str(e)
            if key is None or ("sharding" not in msg and "layout" not in msg):
                raise
            # the AOT Compiled's strict input check tripped on a placement
            # drift the placement key could not see (committed-ness,
            # layout). Poison this key to the plain tracked jit — never an
            # error, at worst a lost warm start for this one signature.
            _flight("compile_cache_fallback", fn=self._name,
                    why="strict-input-mismatch")
            log.debug("AOT strict input check failed for %s (%s); "
                      "pinning signature to plain jit", self._name, msg)
            plain = self._plain()
            with self._lock:
                self._ready[key] = plain
            return plain(*args, **kwargs)

    def warm(self, *args, **kwargs) -> None:
        """Resolve the executable for this signature without executing it.
        Args may be concrete arrays or ``ShapeDtypeStruct``s — both lower
        identically."""
        self._entry(args, kwargs)

    def cost_flops(self, *args, **kwargs) -> Optional[float]:
        """FLOPs from the resolved executable's own cost analysis (no
        re-lowering)."""
        _key, entry = self._entry(args, kwargs)
        analysis = getattr(entry, "cost_analysis", None)
        if analysis is None:
            return None
        try:
            cost = analysis()
            if isinstance(cost, (list, tuple)):
                cost = cost[0] if cost else None
            if cost is None:
                return None
            return float(dict(cost).get("flops", 0.0))
        except Exception:
            return None


def build_program(name: str, jitted: Callable, *,
                  fingerprint: Optional[str] = None, cache_key: Any = None,
                  conf: Any = None, extra: Tuple = (),
                  tracker=None) -> Callable:
    """The factory every compile seam calls on a freshly-built jitted fn.
    Cache enabled -> a ``CachedProgram``; kill switch -> exactly the
    pre-existing ``tracker.wrap`` path."""
    tr = tracker if tracker is not None else global_tracker()
    if not enabled():
        return tr.wrap(name, jitted, cache_key=cache_key)
    return CachedProgram(name, jitted, fingerprint=fingerprint,
                         cache_key=cache_key, conf=conf, extra=extra,
                         tracker=tr)
