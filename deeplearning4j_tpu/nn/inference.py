"""Non-donated compiled inference: the serving seam.

Training dispatches donate params/states/updater into XLA
(``LazyScore._run_multistep`` jits with ``donate_argnums=(0, 1, 2)``) — the
buffers are consumed in place, which is exactly right for a fit loop and
exactly wrong for serving, where the same parameters must survive millions
of forward passes. :func:`make_predict_fn` pins a **snapshot** of a
network's parameters/states (real buffer copies, like ``clone()``) to a
compiled forward program jitted WITHOUT donation, so:

- serving a request can never invalidate the source network's buffers, and
  training the source network can never invalidate the serving snapshot;
- the compiled program is policy-keyed and compile-tracked through the same
  ``LazyScore._jit`` cache as every other program, so recompiles show up in
  ``dl4j_jit_compile_total`` and the recompile-storm detector;
- per padded-batch-bucket compiles are the ONLY compiles: a steady-state
  server replays cached executables (the MicroBatcher's contract).

The reference serves via ``KerasModelEndpoint``/``output()`` with no
donation concept; here the seam must be explicit because the fit path's
donation is what makes TPU training fast.
"""
from __future__ import annotations

import functools
import threading
from typing import Any, Optional

import jax
import jax.numpy as jnp

#: the program name every serving forward compiles under — load tests and
#: the compile-cache-bounded test filter CompileTracker events on it
PREDICT_PROGRAM_NAME = "serve_predict"


def _copy_tree(tree):
    """Real buffer copies, not aliases (same contract as clone())."""
    return jax.tree_util.tree_map(lambda a: jnp.array(a), tree)


class PredictFn:
    """A compiled, non-donated, snapshot-pinned forward pass.

    Callable: ``predict_fn(x) -> jnp array`` where ``x`` carries a leading
    batch axis. Thread-safe — concurrent calls share one compiled program
    per abstract input shape (jax's jit cache handles the rest); the pinned
    buffers are never donated so calls cannot race on buffer liveness.
    """

    def __init__(self, net, name: str = PREDICT_PROGRAM_NAME):
        net._require_init()
        self._net = net
        self._name = name
        # snapshot at pin time: a later fit() on `net` donates ITS buffers,
        # not these copies, and a hot-swap replaces this object wholesale
        self._params = _copy_tree(net.params_list)
        self._states = _copy_tree(net.state_list)
        self._graph = type(net).__name__ == "ComputationGraph"
        if self._graph:
            n_in = len(net.conf.network_inputs)
            if n_in != 1:
                raise ValueError(
                    f"serving supports single-input graphs; this graph has "
                    f"{n_in} inputs — call net.output(*inputs) directly")
            self._single_out = len(net.conf.network_outputs) == 1
            fn = net._output_pure
        else:
            fn = functools.partial(net._output_pure, train=False)
        # LazyScore._jit: policy-keyed, compile-tracked, NO donate argnums
        self._fn = net._jit(name, fn)
        self._lock = threading.Lock()
        self.calls = 0  #: dispatches served (host-side, informational)

    @property
    def name(self) -> str:
        return self._name

    def params_snapshot(self):
        """The pinned parameter pytree (tests assert bit-stability)."""
        return self._params

    def __call__(self, x) -> Any:
        x = jnp.asarray(x)
        if self._graph:
            outs, _ = self._fn(self._params, self._states, [x])
            out = outs[0] if self._single_out else outs
        else:
            out, _ = self._fn(self._params, self._states, x)
        with self._lock:
            self.calls += 1
        return out


def make_predict_fn(net, name: str = PREDICT_PROGRAM_NAME,
                    version: Optional[str] = None) -> PredictFn:
    """Pin a non-donated compiled forward for serving.

    ``version`` only decorates the program name (``serve_predict@v2``) so a
    hot-swapped model's compiles are attributable in the compile tracker;
    omit it for the plain serving program.
    """
    if version:
        name = f"{name}@{version}"
    return PredictFn(net, name=name)
