"""Non-donated compiled inference: the serving seam.

Training dispatches donate params/states/updater into XLA
(``LazyScore._run_multistep`` jits with ``donate_argnums=(0, 1, 2)``) — the
buffers are consumed in place, which is exactly right for a fit loop and
exactly wrong for serving, where the same parameters must survive millions
of forward passes. :func:`make_predict_fn` pins a **snapshot** of a
network's parameters/states (real buffer copies, like ``clone()``) to a
compiled forward program jitted WITHOUT donation, so:

- serving a request can never invalidate the source network's buffers, and
  training the source network can never invalidate the serving snapshot;
- the compiled program is policy-keyed and compile-tracked through the same
  ``LazyScore._jit`` cache as every other program, so recompiles show up in
  ``dl4j_jit_compile_total`` and the recompile-storm detector;
- per padded-batch-bucket compiles are the ONLY compiles: a steady-state
  server replays cached executables (the MicroBatcher's contract).

``sharding="dp_tp"`` + ``mesh=`` routes the pin through the partition-rule
engine instead of a single device: the snapshot is ``device_put`` per the
same rules that shard training (``parallel/partition.py``), cutting resident
bytes per device by the shard factor, and the program compiles through the
``parallel/compile_seam`` jit-with-shardings path.

**The serving bitwise contract.** Distributed *compute* (true Megatron-style
tensor parallelism) makes GSPMD insert partial-sum all-reduces that reorder
f32 accumulation — ~1-ulp accurate, never bitwise (the training suite's
dp_tp equivalence test uses atol=1e-4 for exactly this reason). Serving
promises bitwise equality with the single-device program, so the sharded
path shards params **at rest** and gathers **at use**: the first act inside
the jitted program is ``with_sharding_constraint(params, replicated)`` — an
exact all-gather layout change, no arithmetic — and each batch row then
computes with the identical single-device reduction order. The win is
resident bytes (serve models bigger than one HBM) and data-axis batch
scale-out, not distributed matmuls; do not "optimize" the gather away.

The reference serves via ``KerasModelEndpoint``/``output()`` with no
donation concept; here the seam must be explicit because the fit path's
donation is what makes TPU training fast.
"""
from __future__ import annotations

import functools
import threading
from typing import Any, Optional

import jax
import jax.numpy as jnp

#: the program name every serving forward compiles under — load tests and
#: the compile-cache-bounded test filter CompileTracker events on it
PREDICT_PROGRAM_NAME = "serve_predict"


def _copy_tree(tree):
    """Real buffer copies, not aliases (same contract as clone())."""
    return jax.tree_util.tree_map(lambda a: jnp.array(a), tree)


def _with_dequant(fn):
    """Wrap a forward so its first act is reconstituting dense params from
    the int8 snapshot — inside the jit, so the arguments stay int8."""
    @functools.wraps(fn)
    def wrapped(params, *rest, **kw):
        from deeplearning4j_tpu.ops.quant import dequantize_tree
        return fn(dequantize_tree(params), *rest, **kw)
    return wrapped


#: serving DtypePolicy values make_predict_fn accepts: None/"bf16" serve the
#: pinned snapshot at the network's policy dtype; "int8" additionally
#: quantizes large matrix leaves (ops/quant.py) so the resident params are
#: 8-bit and the dequant runs inside the compiled program
QUANT_MODES = (None, "bf16", "int8")


class PredictFn:
    """A compiled, non-donated, snapshot-pinned forward pass.

    Callable: ``predict_fn(*inputs) -> jnp array`` where each input carries
    a leading batch axis (multi-input ComputationGraphs take one positional
    array per declared graph input). Thread-safe — concurrent calls share
    one compiled program per abstract input shape (jax's jit cache handles
    the rest); the pinned buffers are never donated so calls cannot race on
    buffer liveness.

    ``quant="int8"`` is the opt-in serving DtypePolicy: per-channel scales
    are calibrated at pin time over the snapshot (ops/quant.py), the pinned
    tree holds int8 codes (4x resident-bytes cut vs f32), and the jitted
    program dequantizes lazily so XLA fuses the cast into each consumer.

    ``sharding="dp_tp"`` + ``mesh=`` pins the snapshot sharded per the
    partition rules (params live split across the mesh; int8 composes — the
    codes shard, and the gather moves int8 bytes) and compiles through the
    compile seam. Outputs are fully replicated and bitwise-equal to the
    single-device program (see the module docstring for why the params are
    gathered at use rather than compute-sharded). ``device=`` instead pins
    the snapshot onto one specific device — the ReplicaSet's per-replica
    placement on a multi-chip host.
    """

    def __init__(self, net, name: str = PREDICT_PROGRAM_NAME,
                 quant: Optional[str] = None,
                 sharding: Optional[str] = None,
                 mesh=None, device=None,
                 fingerprint: Optional[str] = None):
        net._require_init()
        if quant not in QUANT_MODES:
            raise ValueError(f"quant must be one of {QUANT_MODES}, "
                             f"got {quant!r}")
        if sharding is not None and mesh is None:
            raise ValueError("sharding requires a mesh (parallel.build_mesh)")
        if sharding is not None and device is not None:
            raise ValueError("pass sharding+mesh OR device, not both")
        self._net = net
        self._name = name
        #: executable-cache identity: the undecorated program name (no
        #: @version / ~replica), so hot swaps and replica spawns warm-hit
        self._fingerprint = fingerprint or name
        self.quant = quant if quant == "int8" else None
        self.sharding = sharding
        self.mesh = mesh
        self.device = device
        # snapshot at pin time: a later fit() on `net` donates ITS buffers,
        # not these copies, and a hot-swap replaces this object wholesale
        self._params = _copy_tree(net.params_list)
        self._states = _copy_tree(net.state_list)
        if self.quant == "int8":
            from deeplearning4j_tpu.ops.quant import quantize_tree
            self._params = quantize_tree(self._params)
        self._graph = type(net).__name__ == "ComputationGraph"
        if self._graph:
            self._n_in = len(net.conf.network_inputs)
            self._single_out = len(net.conf.network_outputs) == 1
            fn = net._output_pure
        else:
            self._n_in = 1
            fn = functools.partial(net._output_pure, train=False)
        if self.quant == "int8":
            fn = _with_dequant(fn)
        self.param_specs = None
        if sharding is not None:
            self._fn = self._compile_sharded(net, name, fn)
        else:
            if device is not None:
                self._params = jax.device_put(self._params, device)
                self._states = jax.device_put(self._states, device)
            # LazyScore._jit: policy-keyed, compile-tracked, NO donate argnums
            self._fn = net._jit(name, fn, fingerprint=self._fingerprint)
        self._lock = threading.Lock()
        self.calls = 0  #: dispatches served (host-side, informational)

    def _compile_sharded(self, net, name, fn):
        """Pin the snapshot sharded-at-rest and compile the gathered-at-use
        program through the compile seam (records the per-device bytes
        gauge for this rule set)."""
        from deeplearning4j_tpu import common
        from deeplearning4j_tpu.parallel import compile_seam, partition
        mesh = self.mesh
        specs = partition.match_partition_rules(
            partition.rules_for(self.sharding), self._params,
            mesh=mesh, conf=getattr(net, "conf", None))
        self.param_specs = specs
        self._params = partition.device_put(self._params, mesh, specs)
        self._states = partition.device_put(self._states, mesh,
                                            partition.pspec())
        gather = partition.tree_shardings(
            mesh, jax.tree_util.tree_map(lambda _: partition.pspec(), specs))

        @functools.wraps(fn)
        def gathered(params, *rest, **kw):
            # exact all-gather (layout change, no arithmetic): every device
            # then runs the identical single-device reduction order, which
            # is what keeps the sharded program bitwise-equal (int8 codes
            # gather as int8 — 4x cheaper on the wire than f32)
            return fn(jax.lax.with_sharding_constraint(params, gather),
                      *rest, **kw)

        conf_dtype = getattr(getattr(getattr(net, "conf", None),
                                     "global_conf", None), "dtype", None)
        step = compile_seam.compile_step(
            f"{type(net).__name__}.{name}",
            common.wrap_with_policy(gathered, conf_dtype),
            mesh=mesh, rule_set=self.sharding,
            # batch entries stay None: __call__ stages each input with
            # batch_spec() and jit inherits the committed placement
            in_specs=(specs, partition.pspec(), None),
            out_specs=partition.pspec(),
            cache_key=common.effective_policy_key(conf_dtype),
            params=self._params, param_specs=specs,
            conf=getattr(net, "conf", None),
            fingerprint=f"{type(net).__name__}.{self._fingerprint}")
        return step

    @property
    def name(self) -> str:
        return self._name

    @property
    def cache_hit(self) -> Optional[bool]:
        """Whether this program's LAST executable resolve came from the
        persistent compile cache (None before any resolve, or with the
        cache disabled). The batcher stamps it on dispatch trace spans so
        a slow first request is attributable to a cold compile."""
        # CompiledStep (sharded) wraps the CachedProgram as .fn
        target = getattr(self._fn, "fn", self._fn)
        return getattr(target, "cache_hit", None)

    @property
    def n_inputs(self) -> int:
        """Positional input arrays one call takes (1 for sequential nets)."""
        return self._n_in

    @property
    def param_bytes(self) -> int:
        """Resident bytes of the pinned params (int8 shows the 4x cut)."""
        from deeplearning4j_tpu.ops.quant import tree_param_bytes
        return tree_param_bytes(self._params)

    @property
    def per_device_param_bytes(self) -> Optional[int]:
        """Resident param bytes on ONE device of the mesh when sharded
        (= param_bytes / shard factor, the tensor-parallel serving win);
        None for unsharded pins."""
        if self.sharding is None:
            return None
        from deeplearning4j_tpu.parallel import partition
        return partition.per_device_bytes(self._params, self.param_specs,
                                          self.mesh)

    def params_snapshot(self):
        """The pinned parameter pytree (tests assert bit-stability).
        Under quant="int8" the matrix leaves are QuantizedLeaf records."""
        return self._params

    def _stage(self, x):
        x = jnp.asarray(x)
        if self.mesh is not None:
            from deeplearning4j_tpu.parallel import partition
            return partition.device_put(
                x, self.mesh, partition.batch_spec(self.mesh, x.shape[0]))
        if self.device is not None:
            return jax.device_put(x, self.device)
        return x

    def __call__(self, *xs) -> Any:
        if len(xs) != self._n_in:
            raise ValueError(f"model takes {self._n_in} input(s), "
                             f"got {len(xs)}")
        staged = [self._stage(x) for x in xs]
        if self._graph:
            outs, _ = self._fn(self._params, self._states, staged)
            out = outs[0] if self._single_out else outs
        else:
            out, _ = self._fn(self._params, self._states, staged[0])
        with self._lock:
            self.calls += 1
        return out

    def warm(self, *xs) -> None:
        """Pre-resolve the compiled program for these example inputs
        (AOT through the executable cache when available — no dispatch;
        one real dispatch otherwise). Registry/replica warmup calls this
        per micro-batch bucket before the pin goes live."""
        if len(xs) != self._n_in:
            raise ValueError(f"model takes {self._n_in} input(s), "
                             f"got {len(xs)}")
        staged = [self._stage(x) for x in xs]
        inputs = staged if self._graph else staged[0]
        # CompiledStep (sharded) wraps the program as .fn
        target = getattr(self._fn, "fn", self._fn)
        warm = getattr(target, "warm", None)
        if warm is not None:
            warm(self._params, self._states, inputs)
        else:
            self._fn(self._params, self._states, inputs)


def make_predict_fn(net, name: str = PREDICT_PROGRAM_NAME,
                    version: Optional[str] = None,
                    quant: Optional[str] = None,
                    sharding: Optional[str] = None,
                    mesh=None, device=None,
                    replica: Optional[int] = None) -> PredictFn:
    """Pin a non-donated compiled forward for serving.

    ``version`` only decorates the program name (``serve_predict@v2``) so a
    hot-swapped model's compiles are attributable in the compile tracker;
    omit it for the plain serving program. ``quant="int8"`` opts this pin
    into the int8 serving DtypePolicy (the program name gains ``+int8`` so
    quantized compiles stay attributable too). ``replica`` likewise only
    decorates the name (``~r0``) so each ReplicaSet member's per-bucket
    compiles count separately. ``sharding``/``mesh``/``device`` choose the
    pin placement — see :class:`PredictFn`.
    """
    # cache identity keeps the quant marker (different program) but sheds
    # version/replica decoration (same program) — that is what lets a hot
    # swap or replica respawn load the previous pin's executables
    fingerprint = f"{name}+int8" if quant == "int8" else name
    if version:
        name = f"{name}@{version}"
    if quant == "int8":
        name = f"{name}+int8"
    if replica is not None:
        name = f"{name}~r{replica}"
    return PredictFn(net, name=name, quant=quant,
                     sharding=sharding, mesh=mesh, device=device,
                     fingerprint=fingerprint)
