"""Non-donated compiled inference: the serving seam.

Training dispatches donate params/states/updater into XLA
(``LazyScore._run_multistep`` jits with ``donate_argnums=(0, 1, 2)``) — the
buffers are consumed in place, which is exactly right for a fit loop and
exactly wrong for serving, where the same parameters must survive millions
of forward passes. :func:`make_predict_fn` pins a **snapshot** of a
network's parameters/states (real buffer copies, like ``clone()``) to a
compiled forward program jitted WITHOUT donation, so:

- serving a request can never invalidate the source network's buffers, and
  training the source network can never invalidate the serving snapshot;
- the compiled program is policy-keyed and compile-tracked through the same
  ``LazyScore._jit`` cache as every other program, so recompiles show up in
  ``dl4j_jit_compile_total`` and the recompile-storm detector;
- per padded-batch-bucket compiles are the ONLY compiles: a steady-state
  server replays cached executables (the MicroBatcher's contract).

The reference serves via ``KerasModelEndpoint``/``output()`` with no
donation concept; here the seam must be explicit because the fit path's
donation is what makes TPU training fast.
"""
from __future__ import annotations

import functools
import threading
from typing import Any, Optional

import jax
import jax.numpy as jnp

#: the program name every serving forward compiles under — load tests and
#: the compile-cache-bounded test filter CompileTracker events on it
PREDICT_PROGRAM_NAME = "serve_predict"


def _copy_tree(tree):
    """Real buffer copies, not aliases (same contract as clone())."""
    return jax.tree_util.tree_map(lambda a: jnp.array(a), tree)


def _with_dequant(fn):
    """Wrap a forward so its first act is reconstituting dense params from
    the int8 snapshot — inside the jit, so the arguments stay int8."""
    @functools.wraps(fn)
    def wrapped(params, *rest, **kw):
        from deeplearning4j_tpu.ops.quant import dequantize_tree
        return fn(dequantize_tree(params), *rest, **kw)
    return wrapped


#: serving DtypePolicy values make_predict_fn accepts: None/"bf16" serve the
#: pinned snapshot at the network's policy dtype; "int8" additionally
#: quantizes large matrix leaves (ops/quant.py) so the resident params are
#: 8-bit and the dequant runs inside the compiled program
QUANT_MODES = (None, "bf16", "int8")


class PredictFn:
    """A compiled, non-donated, snapshot-pinned forward pass.

    Callable: ``predict_fn(x) -> jnp array`` where ``x`` carries a leading
    batch axis. Thread-safe — concurrent calls share one compiled program
    per abstract input shape (jax's jit cache handles the rest); the pinned
    buffers are never donated so calls cannot race on buffer liveness.

    ``quant="int8"`` is the opt-in serving DtypePolicy: per-channel scales
    are calibrated at pin time over the snapshot (ops/quant.py), the pinned
    tree holds int8 codes (4x resident-bytes cut vs f32), and the jitted
    program dequantizes lazily so XLA fuses the cast into each consumer.
    """

    def __init__(self, net, name: str = PREDICT_PROGRAM_NAME,
                 quant: Optional[str] = None):
        net._require_init()
        if quant not in QUANT_MODES:
            raise ValueError(f"quant must be one of {QUANT_MODES}, "
                             f"got {quant!r}")
        self._net = net
        self._name = name
        self.quant = quant if quant == "int8" else None
        # snapshot at pin time: a later fit() on `net` donates ITS buffers,
        # not these copies, and a hot-swap replaces this object wholesale
        self._params = _copy_tree(net.params_list)
        self._states = _copy_tree(net.state_list)
        if self.quant == "int8":
            from deeplearning4j_tpu.ops.quant import quantize_tree
            self._params = quantize_tree(self._params)
        self._graph = type(net).__name__ == "ComputationGraph"
        if self._graph:
            n_in = len(net.conf.network_inputs)
            if n_in != 1:
                raise ValueError(
                    f"serving supports single-input graphs; this graph has "
                    f"{n_in} inputs — call net.output(*inputs) directly")
            self._single_out = len(net.conf.network_outputs) == 1
            fn = net._output_pure
        else:
            fn = functools.partial(net._output_pure, train=False)
        if self.quant == "int8":
            fn = _with_dequant(fn)
        # LazyScore._jit: policy-keyed, compile-tracked, NO donate argnums
        self._fn = net._jit(name, fn)
        self._lock = threading.Lock()
        self.calls = 0  #: dispatches served (host-side, informational)

    @property
    def name(self) -> str:
        return self._name

    @property
    def param_bytes(self) -> int:
        """Resident bytes of the pinned params (int8 shows the 4x cut)."""
        from deeplearning4j_tpu.ops.quant import tree_param_bytes
        return tree_param_bytes(self._params)

    def params_snapshot(self):
        """The pinned parameter pytree (tests assert bit-stability).
        Under quant="int8" the matrix leaves are QuantizedLeaf records."""
        return self._params

    def __call__(self, x) -> Any:
        x = jnp.asarray(x)
        if self._graph:
            outs, _ = self._fn(self._params, self._states, [x])
            out = outs[0] if self._single_out else outs
        else:
            out, _ = self._fn(self._params, self._states, x)
        with self._lock:
            self.calls += 1
        return out


def make_predict_fn(net, name: str = PREDICT_PROGRAM_NAME,
                    version: Optional[str] = None,
                    quant: Optional[str] = None) -> PredictFn:
    """Pin a non-donated compiled forward for serving.

    ``version`` only decorates the program name (``serve_predict@v2``) so a
    hot-swapped model's compiles are attributable in the compile tracker;
    omit it for the plain serving program. ``quant="int8"`` opts this pin
    into the int8 serving DtypePolicy (the program name gains ``+int8`` so
    quantized compiles stay attributable too).
    """
    if version:
        name = f"{name}@{version}"
    if quant == "int8":
        name = f"{name}+int8"
    return PredictFn(net, name=name, quant=quant)
