"""MultiLayerNetwork: sequential network with fit/output/score/evaluate.

Reference: nn/multilayer/MultiLayerNetwork.java (2486 LoC) — init:386,
fit(DataSetIterator):978, backprop:1049, computeGradientAndScore:1807, feedForward:657,
rnnTimeStep:2196, doTruncatedBPTT:1140.

TPU-native redesign: the whole optimizer step — forward, loss (+l1/l2), autodiff
backward, gradient normalization, updater math, parameter update — is ONE jit-compiled
pure function over the parameter pytree, donated so XLA updates in place. The reference's
Solver/StochasticGradientDescent loop (optimize/solvers/StochasticGradientDescent.java:51)
collapses into that fused step; listeners observe from the host side.

Mutable-object API (net.fit(...), net.output(...)) is preserved as a thin stateful shell
over the pure functions so reference users feel at home; the pure train_step itself is
exposed for ParallelWrapper/pjit composition (see deeplearning4j_tpu.parallel).
"""
from __future__ import annotations

import functools
import math
import time
from typing import Iterable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu import common
from deeplearning4j_tpu.observability.compile_tracker import (
    global_tracker as _compile_tracker,
)
from deeplearning4j_tpu.observability.flight_recorder import (
    dump_on_unhandled as _dump_on_unhandled,
    global_recorder as _flight_recorder,
)
from deeplearning4j_tpu.observability.names import FIT_PHASE_SECONDS
from deeplearning4j_tpu.observability.metrics import (
    global_registry as _obs_registry,
)
from deeplearning4j_tpu.observability.profiler import (
    note_dispatch as _profile_note_dispatch,
)
from deeplearning4j_tpu.observability.watchdog import beat as _wd_beat
from deeplearning4j_tpu.nn.conf.multilayer import MultiLayerConfiguration
from deeplearning4j_tpu.nn.conf.layers.base import PretrainLayer
from deeplearning4j_tpu.nn.conf.layers.recurrent import LSTM
from deeplearning4j_tpu.nn.updaters import (
    UpdaterSpec, effective_lr, grads_to_param_dtype, normalize_gradients,
    updater_init, updater_step, updater_step_with_param,
)
from deeplearning4j_tpu.utils.pytree import flatten_params, num_params, unflatten_params

Array = jax.Array

# step-time attribution series (resolved once — per-step cost is two
# perf_counter reads and one locked float add per phase; budget pinned by
# tests/test_bench_contract.py::test_telemetry_overhead_budget)
_phase_hist = _obs_registry().histogram(
    FIT_PHASE_SECONDS,
    "host wall seconds per fit-loop phase (staging: host cast+transfer "
    "submit, or with device prefetch the visible wait for the staged batch; "
    "dispatch: jitted-call submit; listeners: callback overhead)")
_t_staging = _phase_hist.labels(phase="staging")
_t_dispatch = _phase_hist.labels(phase="dispatch")
_t_listeners = _phase_hist.labels(phase="listeners")


def _updater_spec(layer) -> UpdaterSpec:
    return UpdaterSpec(
        name=layer.updater or "sgd",
        momentum=layer.momentum if layer.momentum is not None else 0.9,
        momentum_schedule=getattr(layer, "momentum_schedule", None),
        rho=layer.rho if layer.rho is not None else 0.95,
        rms_decay=layer.rms_decay if layer.rms_decay is not None else 0.95,
        adam_mean_decay=layer.adam_mean_decay if layer.adam_mean_decay is not None else 0.9,
        adam_var_decay=layer.adam_var_decay if layer.adam_var_decay is not None else 0.999,
        epsilon=layer.epsilon if layer.epsilon is not None else 1e-8,
    )


def _regularization(conf: MultiLayerConfiguration, params_list) -> Array:
    """l1 * |W|_1 + 0.5 * l2 * ||W||^2 over regularizable params (reference
    BaseLayer.calcL1/calcL2; gated on use_regularization like the builder's
    .regularization(true))."""
    if not conf.global_conf.use_regularization:
        return jnp.float32(0.0)
    total = jnp.float32(0.0)
    for layer, params in zip(conf.layers, params_list):
        for name in layer.regularizable_params():
            if name not in params:
                continue
            w = params[name]
            if layer.l1:
                total = total + layer.l1 * jnp.sum(jnp.abs(w))
            if layer.l2:
                total = total + 0.5 * layer.l2 * jnp.sum(w * w)
    return total


def forward_fn(conf: MultiLayerConfiguration, params_list, state_list, x, *,
               train: bool, rng: Optional[jax.Array], mask: Optional[Array] = None,
               collect: bool = False):
    """Pure feed-forward through all layers (reference feedForwardToLayer:680).
    Returns (output, new_state_list, activations_list_or_None)."""
    h = x
    new_states = []
    acts = [] if collect else None
    rngs = (jax.random.split(rng, len(conf.layers))
            if rng is not None else [None] * len(conf.layers))
    for i, layer in enumerate(conf.layers):
        pp = conf.preprocessor(i)
        if pp is not None:
            h = pp.pre_process(h, mask)
        h, ns = layer.apply(params_list[i], state_list[i], h,
                            train=train, rng=rngs[i], mask=mask)
        new_states.append(ns)
        if collect:
            acts.append(h)
    return h, new_states, acts


def loss_fn(conf: MultiLayerConfiguration, params_list, state_list, x, y, rng,
            fmask=None, lmask=None):
    """Training loss: forward to the last (loss) layer + regularization.
    Returns (loss, new_state_list).

    With ``gradient_checkpointing`` set, each layer application is wrapped in
    ``jax.checkpoint``: backward recomputes the layer's forward instead of
    holding its activations in HBM — peak activation memory drops from
    O(depth) to O(1) layers at ~1.3x FLOPs."""
    layers = conf.layers
    last = layers[-1]
    if not last.has_loss():
        raise ValueError("Last layer has no loss function; cannot compute supervised loss")
    remat = conf.global_conf.gradient_checkpointing
    h = x
    new_states = []
    rngs = (jax.random.split(rng, len(layers))
            if rng is not None else [None] * len(layers))
    for i, layer in enumerate(layers[:-1]):
        pp = conf.preprocessor(i)
        if pp is not None:
            h = pp.pre_process(h, fmask)
        if remat:
            def f(p, hh, _layer=layer, _s=state_list[i], _r=rngs[i]):
                return _layer.apply(p, _s, hh, train=True, rng=_r, mask=fmask)
            h, ns = jax.checkpoint(f)(params_list[i], h)
        else:
            h, ns = layer.apply(params_list[i], state_list[i], h,
                                train=True, rng=rngs[i], mask=fmask)
        new_states.append(ns)
    pp = conf.preprocessor(len(layers) - 1)
    if pp is not None:
        h = pp.pre_process(h, fmask)
    h = last.apply_dropout(h, rngs[-1], True)
    loss = last.compute_loss(params_list[-1], h, y, lmask)
    new_states.append(state_list[-1])
    loss = loss + _aux_losses(layers, new_states)
    return loss + _regularization(conf, params_list), new_states


def _aux_losses(layers, new_states):
    """Sum layer-declared auxiliary objectives (a layer publishes one by
    returning an "aux_loss" scalar in its state — e.g. MoELayer's Switch
    load-balance term, weighted by its ``aux_loss_weight``)."""
    total = jnp.float32(0.0)
    for layer, ns in zip(layers, new_states):
        if isinstance(ns, dict) and "aux_loss" in ns:
            total = total + getattr(layer, "aux_loss_weight", 1.0) * ns["aux_loss"]
    return total


def make_train_step(conf: MultiLayerConfiguration, loss=None, *,
                    health: bool = False):
    """Build the fused train step: grads via autodiff, per-layer normalization + updater.
    Pure: (params, states, upd_states, x, y, rng, iteration, fmask, lmask) ->
    (params', states', upd_states', loss).

    ``loss`` optionally replaces the standard ``loss_fn`` with a callable of
    the same signature (params_list, state_list, x, y, rng, fmask, lmask) ->
    (loss, new_state_list) — e.g. PipelineTrainer's pipelined forward — while
    keeping the updater/clipping/schedule semantics identical.

    ``health=True`` fuses the health monitor's summary (grad/update norms,
    non-finite count, loss — see ``observability.health.health_terms``) into
    the step and appends its packed vector to the return tuple. Computed
    where grads, old params, and new params all coexist as program values,
    so it stays donation-safe; off-cadence fit dispatches use the plain
    variant and are byte-identical to unmonitored training."""
    g = conf.global_conf
    if loss is None:
        loss = functools.partial(loss_fn, conf)

    def train_step(params_list, state_list, upd_state, x, y, rng, iteration,
                   fmask=None, lmask=None):
        (loss_val, new_states), grads = jax.value_and_grad(
            lambda p: loss(p, state_list, x, y, rng, fmask, lmask),
            has_aux=True)(params_list)
        grads = grads_to_param_dtype(grads, params_list)

        new_params = []
        new_upd = []
        for i, layer in enumerate(conf.layers):
            g_i = grads[i]
            if not g_i:
                new_params.append(params_list[i])
                new_upd.append(upd_state[i])
                continue
            g_i = normalize_gradients(g_i, layer.gradient_normalization,
                                      layer.gradient_normalization_threshold or 1.0)
            spec = _updater_spec(layer)
            lr = effective_lr(layer.learning_rate, g.lr_policy, iteration,
                              g.lr_policy_decay_rate, g.lr_policy_power,
                              g.lr_policy_steps, g.lr_schedule, g.max_num_iterations)
            lr_bias = (jnp.float32(layer.bias_learning_rate)
                       if layer.bias_learning_rate is not None else lr)
            p_new = {}
            u_new = {}
            for name, grad in g_i.items():
                this_lr = lr_bias if name in ("b", "vb", "beta") else lr
                step, ustate = updater_step_with_param(
                    spec, grad, params_list[i][name], upd_state[i][name],
                    this_lr, iteration)
                p_new[name] = params_list[i][name] - step
                u_new[name] = ustate
            new_params.append(p_new)
            new_upd.append(u_new)
        if health:
            from deeplearning4j_tpu.observability.health import health_terms

            haux = health_terms(grads, params_list, new_params, loss_val)
            return new_params, new_states, new_upd, loss_val, haux
        return new_params, new_states, new_upd, loss_val

    # a config-declared dtype policy is baked in at trace time (GlobalConf.dtype)
    return common.wrap_with_policy(train_step, g.dtype)


def make_multistep_train_step(conf: MultiLayerConfiguration, *,
                              health: bool = False):
    """K fused train steps per host dispatch via `lax.scan`.

    Takes a device-resident stack of K minibatches ``xs, ys`` of shape
    ``(K, B, ...)`` and applies the full train step K times inside one XLA
    program. On TPU this amortizes host->device dispatch latency (the
    dominant cost through a remote relay, cf. the reference's per-minibatch
    `MultiLayerNetwork.fit` loop at MultiLayerNetwork.java:1540 which pays a
    host round-trip every step) across K steps; inputs stay in HBM the whole
    time. Returns the per-step losses as a (K,) array — listeners that only
    fire every N iterations can then read just the scores they need without
    forcing a host sync per step.

    ``health=True`` threads the per-step health vector through the scan and
    returns it stacked as ``(K, 4)`` after the losses; the dispatcher picks
    the row for the cadence-due iteration (a lazy device gather, no sync).
    """
    step = make_train_step(conf, health=health)

    def multi_step(params_list, state_list, upd_state, xs, ys, rng, iteration0):
        def body(carry, batch):
            p, s, u, it = carry
            x, y = batch
            key = jax.random.fold_in(rng, it)
            if health:
                p, s, u, loss, haux = step(p, s, u, x, y, key, it)
                return (p, s, u, it + 1), (loss, haux)
            p, s, u, loss = step(p, s, u, x, y, key, it)
            return (p, s, u, it + 1), loss

        (p, s, u, _), out = jax.lax.scan(
            body, (params_list, state_list, upd_state, iteration0), (xs, ys))
        if health:
            losses, hauxs = out
            return p, s, u, losses, hauxs
        return p, s, u, out

    return multi_step


def _stage_host(x, dtype):
    """Cast features to the staging dtype ON THE HOST, before the device
    transfer, so ``stage_dtype`` halves host->device wire bytes on every fit
    path (its documented contract). Device-resident jax Arrays are cast on
    device instead — pulling them back to host would defeat the point."""
    if dtype is None:
        return x
    if isinstance(x, jax.Array):
        return x.astype(dtype)
    return np.asarray(x).astype(dtype, copy=False)


class LazyScore:
    """`score_value` that syncs device->host only when actually read.

    The reference's fit loop computes `score` eagerly every iteration
    (MultiLayerNetwork.java:1807 computeGradientAndScore) because its
    listeners observe synchronously. On TPU — especially through a remote
    relay — `float(loss)` is a full host round-trip, so the training loops
    here store the device-resident loss (or a thunk indexing into a K-step
    loss stack) and materialize it lazily: a ScoreIterationListener printing
    every N iterations costs N times fewer syncs, and a listener-free fit
    costs none at all. Reads are cached, so repeated access is one sync.
    """

    _score_raw = float("nan")

    #: batch size of the most recently fitted minibatch — set by every fit
    #: path on both network types; PerformanceListener reads it to compute
    #: samples/sec (the reference tracks it on the DataSet instead)
    last_batch_size: int = 0

    #: attached ``observability.health.HealthMonitor`` (or None). When set,
    #: the fit loops dispatch the health variant of the train step whenever
    #: the monitor's cadence is due; off-cadence dispatches are untouched.
    health_monitor = None

    @property
    def score_value(self) -> float:
        raw = self._score_raw
        if callable(raw):
            raw = float(raw())
            self._score_raw = raw
        elif not isinstance(raw, float):
            raw = float(raw)
            self._score_raw = raw
        return raw

    @score_value.setter
    def score_value(self, value) -> None:
        self._score_raw = value

    #: one copy of the user-facing message (raised from several entry points
    #: on both network types)
    NOT_INITIALIZED_MSG = (
        "Network not initialized — call net.init() before fit/output "
        "(reference MultiLayerNetwork.init:386 / ComputationGraph.init:266)")

    def _require_init(self) -> None:
        """Raise the reference's actionable not-initialized error instead of a
        NoneType crash (both network types share this via LazyScore)."""
        if getattr(self, "params_list", None) is None:
            raise RuntimeError(self.NOT_INITIALIZED_MSG)

    def _jit(self, name, fn, donate=None, fingerprint=None, extra=()):
        """Per-network compiled-program cache, keyed on the program name AND
        the active dtype policy: the policy is read at trace time, so a
        name-only key would silently pin the policy active at first call.
        A config-declared ``dtype`` (GlobalConf.dtype) overrides the global
        policy for this network's programs.

        ``fingerprint`` overrides the identity used by the persistent
        executable cache when ``name`` carries per-instance decoration
        (serving versions ``@v2``, replica ranks ``~r1``) that must still
        share warm entries. ``extra`` is a flat tuple of additional
        program-geometry axes (e.g. the decode plane's page_size / pool
        size) folded into both this cache's key and the persistent
        executable fingerprint — same name, different geometry must never
        resolve to the same traced program."""
        if not hasattr(self, "_jit_cache"):
            self._jit_cache = {}
        conf_dtype = getattr(getattr(getattr(self, "conf", None),
                                     "global_conf", None), "dtype", None)
        fn = common.wrap_with_policy(fn, conf_dtype)
        pol = common.effective_policy_key(conf_dtype)
        key = (name, tuple(extra)) + pol
        if key not in self._jit_cache:
            # evict programs traced under a different policy — repeatedly
            # switching the global dtype policy must not grow the cache
            # without bound (each entry pins a compiled XLA program)
            for stale in [k for k in self._jit_cache if k[2:] != pol]:
                del self._jit_cache[stale]
            jitted = (jax.jit(fn, donate_argnums=donate)
                      if donate else jax.jit(fn))
            # every cache miss is a (future) compile: build_program wraps
            # the fresh jit so its first call per abstract signature is
            # timed and recorded (and, cache enabled, resolved through the
            # persistent executable store). A dtype-policy flip re-keys
            # this cache, lands here again, and thus counts as a new
            # compile of the same name — which is what the recompile-storm
            # detector watches.
            from deeplearning4j_tpu.nn import compile_cache as _cc

            cls = type(self).__name__
            self._jit_cache[key] = _cc.build_program(
                f"{cls}.{name}", jitted, cache_key=key,
                fingerprint=f"{cls}.{fingerprint or name}",
                conf=getattr(self, "conf", None),
                extra=("donate", donate) + tuple(extra) + tuple(pol))
        return self._jit_cache[key]

    #: hook: the module-level K-step builder for this network type
    #: (make_multistep_train_step / make_graph_multistep_train_step) so the
    #: shared dispatch helper below can build plain and health variants
    _multistep_builder = None

    def _run_multistep(self, xs, ys, n: int):
        """Dispatch one K-step fused group (shared by both network types):
        picks the health variant when the attached monitor's cadence falls
        inside the group, times the dispatch, records the flight-recorder
        step event, and advances the step clock with MFU attribution.
        Returns the (K,) per-step loss stack; params/states/updater are
        updated in place (donated)."""
        hm = self.health_monitor
        due_i = hm.due_index(self.iteration, n) if hm is not None else None
        name = "multistep" if due_i is None else "multistep_health"
        multi = self._jit(
            name, type(self)._multistep_builder(self.conf,
                                                health=due_i is not None),
            donate=(0, 1, 2))
        t0 = time.perf_counter()
        out = multi(self.params_list, self.state_list, self.updater_state,
                    xs, ys, self._next_rng(), jnp.int32(self.iteration))
        dt = time.perf_counter() - t0
        _t_dispatch.observe(dt)
        _profile_note_dispatch(dt)
        if due_i is None:
            (self.params_list, self.state_list, self.updater_state,
             losses) = out
        else:
            (self.params_list, self.state_list, self.updater_state,
             losses, hauxs) = out
            # lazy device gather of the due step's packed health vector — the
            # monitor parks it; the host sync happens at poll() time
            hm.offer(hauxs[due_i], self.iteration + due_i)
        wrap_name = f"{type(self).__name__}.{name}"
        _compile_tracker().note_step(n, fn=wrap_name)
        _flight_recorder().record(
            "step", path=wrap_name, it=self.iteration, k=n,
            batch=self.last_batch_size, dispatch_s=dt)
        return losses


class MultiLayerNetwork(LazyScore):
    """Stateful convenience shell over the pure functions above."""

    _multistep_builder = staticmethod(make_multistep_train_step)

    def __init__(self, conf: MultiLayerConfiguration):
        self.conf = conf
        self.params_list: Optional[list] = None
        self.state_list: Optional[list] = None
        self.updater_state: Optional[list] = None
        self.iteration = 0
        self.epoch = 0
        self.listeners: list = []
        self.score_value = float("nan")
        self._rng = None
        self._jit_cache: dict = {}
        self._rnn_state: Optional[list] = None  # streaming rnnTimeStep state

    # ------------------------------------------------------------------ lifecycle
    def init(self, seed: Optional[int] = None) -> "MultiLayerNetwork":
        g = self.conf.global_conf
        key = jax.random.PRNGKey(g.seed if seed is None else seed)
        self._rng = jax.random.fold_in(key, 0xD14)
        n = len(self.conf.layers)
        keys = jax.random.split(key, n)
        itype = self.conf.input_type
        self.params_list = []
        self.state_list = []
        cur = itype
        for i, layer in enumerate(self.conf.layers):
            if cur is not None:
                pp = self.conf.preprocessor(i)
                if pp is not None:
                    cur = pp.output_type(cur)
            self.params_list.append(layer.init_params(keys[i], cur))
            self.state_list.append(layer.init_state(cur))
            if cur is not None:
                cur = layer.output_type(cur)
        self.updater_state = [
            {name: updater_init(_updater_spec(layer), p)
             for name, p in params.items()}
            for layer, params in zip(self.conf.layers, self.params_list)
        ]
        return self

    def set_listeners(self, *listeners) -> None:
        self.listeners = list(listeners)

    def add_listener(self, listener) -> None:
        self.listeners.append(listener)

    # ------------------------------------------------------------------ params API
    def params(self) -> Array:
        """Flat 1-D parameter view (reference MultiLayerNetwork.params())."""
        return flatten_params(self.params_list)

    def set_params(self, flat: Array) -> None:
        self.params_list = unflatten_params(self.params_list, flat)

    def num_params(self) -> int:
        return num_params(self.params_list)

    # ------------------------------------------------------------------ inference
    def output(self, x, train: bool = False) -> Array:
        """Forward pass returning final activations (reference output:2061).
        ``train=True`` runs training-mode layer behavior (batch statistics);
        dropout needs an rng and is not applied on this inference path."""
        self._require_init()
        x = jnp.asarray(x)

        fn = self._jit(f"output_train{train}",
                       functools.partial(self._output_pure, train=train))
        out, _ = fn(self.params_list, self.state_list, x)
        return out

    def _output_pure(self, params_list, state_list, x, *, train):
        out, ns, _ = forward_fn(self.conf, params_list, state_list, x,
                                train=train, rng=None)
        return out, ns

    def feed_forward(self, x, train: bool = False) -> list:
        """Per-layer activations (reference feedForward:657)."""
        self._require_init()
        out, _, acts = forward_fn(self.conf, self.params_list, self.state_list,
                                  jnp.asarray(x), train=train, rng=None, collect=True)
        return acts

    def predict(self, x) -> np.ndarray:
        return np.asarray(jnp.argmax(self.output(x), axis=-1))

    def score(self, x=None, y=None, dataset=None) -> float:
        """Loss (incl. regularization) on a dataset, no dropout; a DataSet's
        feature/label masks are honored like fit()'s (reference score:1704
        via setLayerMaskArrays)."""
        self._require_init()
        fmask = lmask = None
        if dataset is not None:
            x, y = dataset.features, dataset.labels
            fmask = (jnp.asarray(dataset.features_mask)
                     if dataset.features_mask is not None else None)
            lmask = (jnp.asarray(dataset.labels_mask)
                     if dataset.labels_mask is not None else None)
        x, y = jnp.asarray(x), jnp.asarray(y)
        fn = self._jit("score", self._score_pure)
        return float(fn(self.params_list, self.state_list, x, y, fmask,
                        lmask))

    def _eval_trunk(self, params_list, state_list, x, fmask=None):
        """Eval-mode forward to the last layer's input with feature-mask
        threading — the ONE trunk behind score() and score_examples() (same
        walk as loss_fn's, without training state)."""
        layers = self.conf.layers
        h = x
        for i, layer in enumerate(layers[:-1]):
            pp = self.conf.preprocessor(i)
            if pp is not None:
                h = pp.pre_process(h, fmask)
            h, _ = layer.apply(params_list[i], state_list[i], h, train=False,
                               rng=None, mask=fmask)
        pp = self.conf.preprocessor(len(layers) - 1)
        if pp is not None:
            h = pp.pre_process(h, fmask)
        return h

    def _score_pure(self, params_list, state_list, x, y, fmask=None,
                    lmask=None):
        h = self._eval_trunk(params_list, state_list, x, fmask)
        loss = self.conf.layers[-1].compute_loss(params_list[-1], h, y, lmask)
        return loss + _regularization(self.conf, params_list)

    def score_examples(self, x, y=None, add_regularization: bool = False):
        """Per-example loss scores, un-reduced (reference scoreExamples:1742/1759)
        — the anomaly-detection / example-weighting API. ``x`` may be a
        DataSet, whose labels mask weights each example's own loss (padded
        timesteps don't count, as in fit()). With ``add_regularization`` the
        network's l1/l2 term is added to every example's score."""
        from deeplearning4j_tpu.datasets.dataset import DataSet

        self._require_init()
        fmask = lmask = None
        if y is None and isinstance(x, DataSet):
            fmask = (jnp.asarray(x.features_mask)
                     if x.features_mask is not None else None)
            lmask = (jnp.asarray(x.labels_mask)
                     if x.labels_mask is not None else None)
            x, y = x.features, x.labels
        fn = self._jit("score_examples", self._score_examples_pure)
        per = fn(self.params_list, self.state_list, jnp.asarray(x),
                 jnp.asarray(y), fmask, lmask)
        if add_regularization:
            per = per + _regularization(self.conf, self.params_list)
        return np.asarray(per)

    def _score_examples_pure(self, params_list, state_list, x, y, fmask,
                             lmask):
        h = self._eval_trunk(params_list, state_list, x, fmask)
        last = self.conf.layers[-1]

        # per-example: the scalar loss of a single-example batch IS that
        # example's score (keeps every loss function's own reduction rules)
        def one(hi, yi, mi=None):
            return last.compute_loss(params_list[-1], hi[None], yi[None],
                                     mi[None] if mi is not None else None)

        if lmask is not None:
            return jax.vmap(one)(h, y, lmask)
        return jax.vmap(one)(h, y)

    def f1_score(self, x, y=None) -> float:
        """F1 on a dataset or (x, y) arrays (reference f1Score:931/1683)."""
        from deeplearning4j_tpu.datasets.dataset import DataSet

        if y is None and isinstance(x, DataSet):
            x, y = x.features, x.labels
        return self.evaluate(x, y).f1()

    # ------------------------------------------------------------------ training
    def _next_rng(self):
        self._require_init()
        if self._rng is None:
            raise RuntimeError(self.NOT_INITIALIZED_MSG)
        self._rng, sub = jax.random.split(self._rng)
        return sub

    @_dump_on_unhandled("MultiLayerNetwork.fit")
    def fit(self, x, y=None, *, epochs: int = 1, fmask=None, lmask=None) -> None:
        """Fit on arrays, a DataSet, or a DataSetIterator (reference fit:978).

        Array/DataSet fits with ``epochs > 1`` take the K-step fused path
        when eligible: the batch is staged on device ONCE and broadcast
        across the scan axis, so repeated epochs cost one host transfer and
        ``ceil(epochs/K)`` dispatches instead of ``epochs`` round-trips."""
        from deeplearning4j_tpu.datasets.dataset import DataSet

        if y is None and isinstance(x, DataSet):
            self.fit(x.features, x.labels, epochs=epochs,
                     fmask=x.features_mask, lmask=x.labels_mask)
            return
        if y is None and hasattr(x, "__iter__") and not isinstance(x, (jnp.ndarray, np.ndarray)):
            self.fit_iterator(x, epochs=epochs)
            return
        if (epochs > 1 and fmask is None and lmask is None
                and self._repeat_multistep_ok()):
            self._fit_repeated(x, y, epochs)
            return
        for _ in range(epochs):
            self._fit_batch(x, y, fmask, lmask)

    def _repeat_multistep_ok(self) -> bool:
        return (self.dispatch_ksteps > 1
                and self._uses_sgd()
                and self.conf.global_conf.iterations <= 1
                and not (self.conf.backprop_type == "TruncatedBPTT"
                         and any(isinstance(l, LSTM)
                                 for l in self.conf.layers)))

    def _fit_repeated(self, x, y, epochs: int) -> None:
        """``epochs`` repeated steps on one device-resident batch, K per
        dispatch via the scanned train step (broadcast along the scan axis —
        XLA reads the same HBM buffer each step, no K-fold staging)."""
        with _t_staging.time():
            xd = jnp.asarray(_stage_host(x, self.stage_dtype))
            yd = jnp.asarray(y)
        self.last_batch_size = int(np.shape(x)[0]) if np.ndim(x) else 0
        remaining = epochs
        while remaining > 0:
            k = min(self.dispatch_ksteps, remaining)
            xs = jnp.broadcast_to(xd[None], (k,) + xd.shape)
            ys = jnp.broadcast_to(yd[None], (k,) + yd.shape)
            losses = self._run_multistep(xs, ys, k)
            with _t_listeners.time():
                for i in range(k):
                    self.iteration += 1
                    self.score_value = (lambda ls=losses, j=i: ls[j])
                    for listener in self.listeners:
                        listener.iteration_done(self, self.iteration)
            _wd_beat(self.iteration)
            remaining -= k

    #: train steps fused per host dispatch in fit_iterator (lax.scan); 1
    #: disables the K-step path. Benched sweet spot for relay-attached TPUs.
    dispatch_ksteps: int = 8

    #: optional dtype (e.g. jnp.bfloat16) features are cast to on the host
    #: BEFORE the device transfer in the fused fit path. Halves host->device
    #: bytes — the binding constraint when the TPU is behind a network relay
    #: (BASELINE.md round-3 fit-API analysis). Labels stay untouched. None
    #: keeps exact f32 staging.
    stage_dtype = None

    #: K-step groups staged + transferred ahead of the dispatch loop on a
    #: background thread (datasets.prefetch.DevicePrefetcher): 2 = double
    #: buffering (batch n+1 in flight to HBM while step n executes), 0 =
    #: synchronous staging (the pre-prefetch behavior; bit-identical params
    #: either way — tests/test_prefetch.py).
    prefetch_depth: int = 2

    @_dump_on_unhandled("MultiLayerNetwork.fit_iterator")
    def fit_iterator(self, iterator: Iterable, epochs: int = 1,
                     ksteps: Optional[int] = None) -> None:
        """Fit from a DataSetIterator (reference fit(DataSetIterator):978).

        TPU fast path: accumulates up to ``ksteps`` host-staged minibatches,
        stacks them into one (K, B, ...) device transfer, and runs all K
        train steps inside ONE XLA dispatch (make_multistep_train_step) —
        the per-minibatch host round-trip of the reference's fit loop is paid
        once per K steps. Listeners still observe every iteration; reading
        `score_value` lazily indexes the on-device loss stack (LazyScore), so
        a listener firing every N iterations costs ~K*N fewer syncs.
        Falls back to per-batch dispatch for TBPTT, masked batches,
        iterations>1 configs, or ragged batch shapes.
        """
        k = self.dispatch_ksteps if ksteps is None else max(1, ksteps)
        multistep_ok = (
            k > 1
            and self._uses_sgd()
            and self.conf.global_conf.iterations <= 1
            and not (self.conf.backprop_type == "TruncatedBPTT"
                     and any(isinstance(l, LSTM) for l in self.conf.layers)))
        for _ in range(epochs):
            for listener in self.listeners:
                if hasattr(listener, "on_epoch_start"):
                    listener.on_epoch_start(self)
            if hasattr(iterator, "reset"):
                iterator.reset()
            if self.conf.pretrain:
                self.pretrain(iterator)
                if hasattr(iterator, "reset"):
                    iterator.reset()
            if multistep_ok:
                self._fit_epoch_multistep(iterator, k)
            else:
                for ds in iterator:
                    self._fit_batch(ds.features, ds.labels, ds.features_mask,
                                    ds.labels_mask)
            for listener in self.listeners:
                if hasattr(listener, "on_epoch_end"):
                    listener.on_epoch_end(self)
            self.epoch += 1

    def _fit_epoch_multistep(self, iterator, k: int) -> None:
        from deeplearning4j_tpu.datasets.prefetch import DevicePrefetcher
        from deeplearning4j_tpu.utils.batching import k_step_groups

        def to_batch(ds):
            if ds.features_mask is not None or ds.labels_mask is not None:
                return None  # masked -> per-batch fallback
            # lint: host-sync-in-hot-loop-ok (producer-thread host staging of iterator output, not a device sync)
            return np.asarray(ds.features), np.asarray(ds.labels)

        def stage(kind_item):
            # producer thread: stack + cast + NON-BLOCKING device_put — the
            # (K, B, ...) group is in flight to HBM while the previous
            # dispatch executes. Singles and len<2 groups pass through to
            # the host fallback path unchanged.
            kind, item = kind_item
            if kind != "group" or len(item) < 2:
                return kind_item
            xs = jax.device_put(_stage_host(np.stack([b[0] for b in item]),
                                            self.stage_dtype))
            ys = jax.device_put(np.stack([b[1] for b in item]))
            return "staged", (xs, ys, len(item))

        pf = DevicePrefetcher(k_step_groups(iterator, k, to_batch), stage,
                              depth=self.prefetch_depth, path="multilayer",
                              wait_series=_t_staging)
        for kind, item in pf:
            if kind == "single":
                self._fit_batch(item.features, item.labels,
                                item.features_mask, item.labels_mask)
            elif kind == "group":
                if item:
                    self._fit_batch(item[0][0], item[0][1])
            else:
                self._dispatch_staged(*item)

    def _dispatch_multistep(self, batches: list) -> None:
        """Synchronous-staging compatibility path (prefetch_depth=0 semantics
        for a pre-built group)."""
        if not batches:
            return
        if len(batches) == 1:
            self._fit_batch(batches[0][0], batches[0][1])
            return
        with _t_staging.time():
            xs = jnp.asarray(_stage_host(np.stack([b[0] for b in batches]),
                                         self.stage_dtype))
            ys = jnp.asarray(np.stack([b[1] for b in batches]))
        self._dispatch_staged(xs, ys, len(batches))

    def _dispatch_staged(self, xs, ys, n: int) -> None:
        """Run a K-step group whose (K, B, ...) stacks are already device-
        resident (or in flight — dispatch never blocks on the transfer).

        Donation hand-off: params/states/updater buffers are DONATED — XLA
        updates them in place (no 2x param HBM during the step) and the
        previous arrays are consumed; anyone holding stale references gets a
        loud "deleted buffer" error, never silent corruption (clone() deep-
        copies for this reason; donation is a no-op on CPU). The staged
        xs/ys are NOT in the donated argnums and were freshly created by
        device_put on the prefetch thread, so a prefetched group can never
        alias a buffer the in-flight step is consuming."""
        self.last_batch_size = int(xs.shape[1])
        losses = self._run_multistep(xs, ys, n)
        with _t_listeners.time():
            for i in range(n):
                self.iteration += 1
                self.score_value = (lambda ls=losses, j=i: ls[j])
                for listener in self.listeners:
                    listener.iteration_done(self, self.iteration)
        _wd_beat(self.iteration)

    #: Solver facade instance when optimization_algo != SGD (built lazily)
    _solver = None

    def _uses_sgd(self) -> bool:
        algo = self.conf.global_conf.optimization_algo
        return algo in (None, "stochastic_gradient_descent")

    def _fit_batch(self, x, y, fmask=None, lmask=None) -> None:
        if not self._uses_sgd():
            # honor optimization_algo: LBFGS/CG/line-GD configs route through
            # the Solver facade (reference Solver.java:55 getOptimizer
            # dispatch) instead of silently training with SGD
            from deeplearning4j_tpu.optimize.solvers import Solver

            if self._solver is None:
                self._solver = Solver(self)
            self._solver.optimize(x, y)
            return
        if (self.conf.backprop_type == "TruncatedBPTT"
                and any(isinstance(l, LSTM) for l in self.conf.layers)):
            self._fit_tbptt(x, y, fmask, lmask)
            return
        with _t_staging.time():
            x, y = jnp.asarray(x), jnp.asarray(y)
            fmask = jnp.asarray(fmask) if fmask is not None else None
            lmask = jnp.asarray(lmask) if lmask is not None else None
        self.last_batch_size = int(x.shape[0]) if x.ndim else 0
        for _ in range(max(1, self.conf.global_conf.iterations)):
            hm = self.health_monitor
            use_health = hm is not None and hm.due(self.iteration)
            name = "train_step_health" if use_health else "train_step"
            step = self._jit(name, make_train_step(self.conf,
                                                   health=use_health))
            t0 = time.perf_counter()
            out = step(self.params_list, self.state_list,
                       self.updater_state, x, y, self._next_rng(),
                       jnp.int32(self.iteration), fmask, lmask)
            dt = time.perf_counter() - t0
            _t_dispatch.observe(dt)
            _profile_note_dispatch(dt)
            if use_health:
                (self.params_list, self.state_list, self.updater_state,
                 loss, haux) = out
                hm.offer(haux, self.iteration)
            else:
                (self.params_list, self.state_list, self.updater_state,
                 loss) = out
            wrap_name = f"{type(self).__name__}.{name}"
            _compile_tracker().note_step(fn=wrap_name)
            _flight_recorder().record(
                "step", path=wrap_name, it=self.iteration,
                batch=self.last_batch_size, dispatch_s=dt)
            self.score_value = loss  # device scalar; synced lazily (LazyScore)
            self.iteration += 1
            with _t_listeners.time():
                for listener in self.listeners:
                    listener.iteration_done(self, self.iteration)
            _wd_beat(self.iteration)

    # ------------------------------------------------------------------ TBPTT
    def _fit_tbptt(self, x, y, fmask=None, lmask=None) -> None:
        """Truncated BPTT (reference doTruncatedBPTT:1140): slice the time axis into
        tbptt_fwd_length chunks; RNN state carries across chunks via lax.stop_gradient
        (the truncation). Time axis = 1 ([B,T,F] layout)."""
        x, y = jnp.asarray(x), jnp.asarray(y)
        self.last_batch_size = int(x.shape[0]) if x.ndim else 0
        T = x.shape[1]
        L = self.conf.tbptt_fwd_length
        n_chunks = max(1, math.ceil(T / L))
        step = self._jit("tbptt_step", make_tbptt_step(self.conf))
        rnn_state = _init_rnn_states(self.conf, x.shape[0], x.dtype)
        for c in range(n_chunks):
            sl = slice(c * L, min((c + 1) * L, T))
            xc, yc = x[:, sl], y[:, sl]
            fm = fmask[:, sl] if fmask is not None else None
            lm = lmask[:, sl] if lmask is not None else None
            (self.params_list, self.state_list, self.updater_state, rnn_state,
             loss) = step(self.params_list, self.state_list, self.updater_state,
                          rnn_state, xc, yc, self._next_rng(),
                          jnp.int32(self.iteration), fm, lm)
            _compile_tracker().note_step(fn=f"{type(self).__name__}.tbptt_step")
            _flight_recorder().record(
                "step", path=f"{type(self).__name__}.tbptt_step",
                it=self.iteration, batch=self.last_batch_size)
            self.score_value = loss  # device scalar; synced lazily (LazyScore)
            self.iteration += 1
            for listener in self.listeners:
                listener.iteration_done(self, self.iteration)
            _wd_beat(self.iteration)

    # ------------------------------------------------------------------ pretrain
    def pretrain(self, iterator) -> None:
        """Greedy layerwise unsupervised pretraining (reference pretrain:152):
        for each pretrain layer, feed inputs forward to it and minimize its
        unsupervised objective."""
        for idx, layer in enumerate(self.conf.layers):
            if isinstance(layer, PretrainLayer):
                self.pretrain_layer(idx, iterator)

    def pretrain_layer(self, layer_idx: int, iterator) -> None:
        """Pretrain ONE layer unsupervised (reference pretrainLayer:183);
        earlier layers run in eval mode to produce its input."""
        self._require_init()
        if not 0 <= layer_idx < len(self.conf.layers):
            raise ValueError(
                f"layer_idx {layer_idx} out of range for "
                f"{len(self.conf.layers)} layers")
        if not isinstance(self.conf.layers[layer_idx], PretrainLayer):
            raise ValueError(
                f"Layer {layer_idx} "
                f"({type(self.conf.layers[layer_idx]).__name__}) is not "
                "pretrainable — layerwise pretraining needs an unsupervised "
                "layer (VAE, RBM, AutoEncoder)")
        step = self._jit(f"pretrain:{layer_idx}",
                         make_pretrain_step(self.conf, layer_idx))
        if hasattr(iterator, "reset"):
            iterator.reset()
        for ds in iterator:
            x = jnp.asarray(ds.features)
            (self.params_list[layer_idx], self.updater_state[layer_idx],
             loss) = step(self.params_list, self.state_list,
                          self.updater_state[layer_idx], x,
                          self._next_rng(), jnp.int32(self.iteration))
            self.score_value = loss  # synced lazily (LazyScore)

    # ------------------------------------------------------------------ evaluation
    def evaluate(self, iterator_or_x, y=None, labels_list=None, top_n: int = 1):
        """Evaluate classification accuracy over an iterator or an (x, y) pair.

        ``labels_list`` attaches class-label names to the returned Evaluation's
        stats; ``top_n`` tracks top-N accuracy alongside top-1 (reference
        MultiLayerNetwork.evaluate(DataSetIterator, List<String>, int)).
        """
        from deeplearning4j_tpu.eval.evaluation import Evaluation

        ev = Evaluation(labels=labels_list, top_n=top_n)
        if y is not None:
            ev.eval(np.asarray(y), np.asarray(self.output(iterator_or_x)))
            return ev
        it = iterator_or_x
        if hasattr(it, "reset"):
            it.reset()
        for ds in it:
            out = self.output(ds.features)
            ev.eval(np.asarray(ds.labels), np.asarray(out),
                    mask=np.asarray(ds.labels_mask) if ds.labels_mask is not None else None)
        return ev

    def evaluate_regression(self, iterator):
        from deeplearning4j_tpu.eval.regression import RegressionEvaluation

        ev = RegressionEvaluation()
        if hasattr(iterator, "reset"):
            iterator.reset()
        for ds in iterator:
            ev.eval(np.asarray(ds.labels), np.asarray(self.output(ds.features)))
        return ev

    def _evaluate_roc_impl(self, roc, iterator):
        if hasattr(iterator, "reset"):
            iterator.reset()
        for ds in iterator:
            roc.eval(np.asarray(ds.labels),
                     np.asarray(self.output(ds.features)))
        return roc

    def evaluate_roc(self, iterator, threshold_steps: int = 30):
        from deeplearning4j_tpu.eval.roc import ROC

        return self._evaluate_roc_impl(ROC(threshold_steps), iterator)

    def evaluate_roc_multiclass(self, iterator, threshold_steps: int = 30):
        """One-vs-all ROC per class (reference evaluateROCMultiClass:2401)."""
        from deeplearning4j_tpu.eval.roc import ROCMultiClass

        return self._evaluate_roc_impl(ROCMultiClass(threshold_steps),
                                       iterator)

    # ------------------------------------------------------------------ rnn API
    def rnn_time_step(self, x) -> Array:
        """Streaming inference carrying hidden state across calls (reference
        rnnTimeStep:2196). x: [B,T,F] (T may be 1)."""
        self._require_init()
        x = jnp.asarray(x)
        if self._rnn_state is None:
            self._rnn_state = _init_rnn_states(self.conf, x.shape[0], x.dtype)
        fn = self._jit("rnn_time_step", functools.partial(_rnn_forward, self.conf))
        out, self._rnn_state = fn(self.params_list, self.state_list,
                                  self._rnn_state, x)
        return out

    def rnn_get_previous_state(self):
        """Per-layer streaming LSTM state (reference rnnGetPreviousState:2225);
        None until rnn_time_step has run."""
        return self._rnn_state

    def rnn_set_previous_state(self, state) -> None:
        """Install streaming state captured by rnn_get_previous_state
        (reference rnnSetPreviousState:2235) — serving handoff/restore."""
        self._rnn_state = (jax.tree_util.tree_map(jnp.asarray, state)
                           if state is not None else None)

    def rnn_clear_previous_state(self) -> None:
        self._rnn_state = None

    # ------------------------------------------------------------------ grads (for checks)
    def gradient_and_score(self, x, y, fmask=None, lmask=None):
        """(grads pytree, score) without updating params (reference
        computeGradientAndScore:1807). Deterministic: no dropout rng."""
        self._require_init()
        x, y = jnp.asarray(x), jnp.asarray(y)

        def lf(p):
            loss, _ = loss_fn(self.conf, p, self.state_list, x, y, None, fmask, lmask)
            return loss

        loss, grads = jax.value_and_grad(lf)(self.params_list)
        return grads, float(loss)

    def clone(self) -> "MultiLayerNetwork":
        import copy

        net = MultiLayerNetwork(copy.deepcopy(self.conf))
        # REAL buffer copies, not aliases: the fused fit path donates param
        # buffers to XLA, so a clone sharing arrays with the original would
        # see its arrays deleted when either of them trains
        cp = lambda a: jnp.array(a)
        net.params_list = jax.tree_util.tree_map(cp, self.params_list)
        net.state_list = jax.tree_util.tree_map(cp, self.state_list)
        net.updater_state = jax.tree_util.tree_map(cp, self.updater_state)
        net.iteration = self.iteration
        net.epoch = self.epoch
        net._rng = self._rng
        if self._rnn_state is not None:  # mid-stream serving handoff
            net._rnn_state = jax.tree_util.tree_map(cp, self._rnn_state)
        return net


# ---------------------------------------------------------------------- rnn helpers
def _init_rnn_states(conf, batch, dtype):
    states = []
    for layer in conf.layers:
        if isinstance(layer, LSTM):
            states.append({"h": jnp.zeros((batch, layer.n_out), dtype),
                           "c": jnp.zeros((batch, layer.n_out), dtype)})
        else:
            states.append({})
    return states


def _rnn_forward(conf, params_list, state_list, rnn_states, x):
    """Forward pass threading LSTM streaming state (pure)."""
    h = x
    new_rnn = []
    for i, layer in enumerate(conf.layers):
        pp = conf.preprocessor(i)
        if pp is not None:
            h = pp.pre_process(h)
        if isinstance(layer, LSTM) and not type(layer).__name__.startswith("GravesBidirectional"):
            h, rs = layer.apply_streaming(params_list[i], rnn_states[i], h)
            new_rnn.append(rs)
        else:
            h, _ = layer.apply(params_list[i], state_list[i], h, train=False, rng=None)
            new_rnn.append(rnn_states[i])
    return h, new_rnn


def make_tbptt_step(conf: MultiLayerConfiguration):
    """TBPTT train step: like make_train_step but threads LSTM state across chunks,
    truncating gradients at chunk boundaries with stop_gradient."""
    g = conf.global_conf

    def tbptt_step(params_list, state_list, upd_state, rnn_states, x, y, rng,
                   iteration, fmask=None, lmask=None):
        def lf(p):
            h = x
            new_rnn = []
            chunk_states = []
            rngs = jax.random.split(rng, len(conf.layers)) if rng is not None else None
            for i, layer in enumerate(conf.layers[:-1]):
                pp = conf.preprocessor(i)
                if pp is not None:
                    h = pp.pre_process(h, fmask)
                if isinstance(layer, LSTM) and not type(layer).__name__.startswith("GravesBidirectional"):
                    h, rs = layer.apply_streaming(p[i], rnn_states[i], h, mask=fmask)
                    new_rnn.append(jax.tree_util.tree_map(jax.lax.stop_gradient, rs))
                    chunk_states.append(state_list[i])
                else:
                    h, ns = layer.apply(p[i], state_list[i], h, train=True,
                                        rng=rngs[i], mask=fmask)
                    new_rnn.append(rnn_states[i])
                    chunk_states.append(ns)
            last = conf.layers[-1]
            h = last.apply_dropout(h, rngs[-1], True)
            loss = last.compute_loss(p[-1], h, y, lmask)
            new_rnn.append(rnn_states[-1])
            # layer-declared aux objectives (MoE load balance) apply per
            # TBPTT chunk exactly as in the standard loss_fn
            loss = loss + _aux_losses(conf.layers, chunk_states)
            return loss + _regularization(conf, p), new_rnn

        (loss, new_rnn), grads = jax.value_and_grad(lf, has_aux=True)(params_list)
        grads = grads_to_param_dtype(grads, params_list)
        new_params = []
        new_upd = []
        for i, layer in enumerate(conf.layers):
            g_i = grads[i]
            if not g_i:
                new_params.append(params_list[i])
                new_upd.append(upd_state[i])
                continue
            g_i = normalize_gradients(g_i, layer.gradient_normalization,
                                      layer.gradient_normalization_threshold or 1.0)
            spec = _updater_spec(layer)
            lr = effective_lr(layer.learning_rate, g.lr_policy, iteration,
                              g.lr_policy_decay_rate, g.lr_policy_power,
                              g.lr_policy_steps, g.lr_schedule, g.max_num_iterations)
            p_new, u_new = {}, {}
            for name, grad in g_i.items():
                step, ustate = updater_step_with_param(
                    spec, grad, params_list[i][name], upd_state[i][name], lr,
                    iteration)
                p_new[name] = params_list[i][name] - step
                u_new[name] = ustate
            new_params.append(p_new)
            new_upd.append(u_new)
        return new_params, state_list, new_upd, new_rnn, loss

    return common.wrap_with_policy(tbptt_step, g.dtype)


def make_pretrain_step(conf: MultiLayerConfiguration, layer_idx: int):
    """Unsupervised pretrain step for layer ``layer_idx`` (reference pretrainLayer:183):
    forward (no dropout) through preceding layers, minimize the layer's pretrain loss
    wrt ITS params only."""
    g = conf.global_conf
    layer = conf.layers[layer_idx]

    def pretrain_step(params_list, state_list, layer_upd_state, x, rng, iteration):
        h = x
        for i in range(layer_idx):
            pp = conf.preprocessor(i)
            if pp is not None:
                h = pp.pre_process(h)
            h, _ = conf.layers[i].apply(params_list[i], state_list[i], h,
                                        train=False, rng=None)
        pp = conf.preprocessor(layer_idx)
        if pp is not None:
            h = pp.pre_process(h)
        h = jax.lax.stop_gradient(h)

        def lf(p):
            return layer.pretrain_loss(p, h, rng=rng)

        loss, grads = jax.value_and_grad(lf)(params_list[layer_idx])
        grads = grads_to_param_dtype(grads, params_list[layer_idx])
        grads = normalize_gradients(grads, layer.gradient_normalization,
                                    layer.gradient_normalization_threshold or 1.0)
        spec = _updater_spec(layer)
        lr = effective_lr(layer.learning_rate, g.lr_policy, iteration,
                          g.lr_policy_decay_rate, g.lr_policy_power,
                          g.lr_policy_steps, g.lr_schedule, g.max_num_iterations)
        p_new, u_new = {}, {}
        for name, grad in grads.items():
            step, ustate = updater_step_with_param(
                spec, grad, params_list[layer_idx][name],
                layer_upd_state[name], lr, iteration)
            p_new[name] = params_list[layer_idx][name] - step
            u_new[name] = ustate
        return p_new, u_new, loss

    return common.wrap_with_policy(pretrain_step, g.dtype)
