"""Weight initialization schemes.

Parity with the reference's ``WeightInit`` enum + ``WeightInitUtil``
(reference nn/weights/WeightInit.java, nn/weights/WeightInitUtil.java). Fan-in/fan-out
are computed from the param shape the same way (for conv kernels: fanIn =
inChannels*kh*kw, fanOut = outChannels*kh*kw).

All initializers take an explicit ``jax.random`` key — the functional replacement for
the reference's global ND4J RNG, and the thing that makes init reproducible under
`jit`/`shard_map`.
"""
from __future__ import annotations

import math
from typing import Optional, Sequence

import jax
import jax.numpy as jnp

Array = jax.Array


def fan_in_out(shape: Sequence[int]) -> tuple[float, float]:
    """(fan_in, fan_out) for dense [in, out] or conv [kh, kw, in, out] shapes."""
    if len(shape) == 2:
        return float(shape[0]), float(shape[1])
    if len(shape) == 4:
        receptive = shape[0] * shape[1]
        return float(shape[2] * receptive), float(shape[3] * receptive)
    if len(shape) == 1:
        return float(shape[0]), float(shape[0])
    receptive = 1
    for s in shape[:-2]:
        receptive *= s
    return float(shape[-2] * receptive), float(shape[-1] * receptive)


def init_weights(key: jax.Array, shape: Sequence[int], scheme: str,
                 distribution: Optional[dict] = None,
                 dtype=jnp.float32) -> Array:
    """Initialize a weight tensor per DL4J WeightInit scheme name."""
    scheme = str(scheme).lower()
    fan_in, fan_out = fan_in_out(shape)
    shape = tuple(shape)

    if scheme == "zero":
        return jnp.zeros(shape, dtype)
    if scheme == "one":
        return jnp.ones(shape, dtype)
    if scheme == "normal":
        # DL4J NORMAL: N(0, 1/sqrt(fanIn))
        return jax.random.normal(key, shape, dtype) / math.sqrt(fan_in)
    if scheme == "uniform":
        a = 1.0 / math.sqrt(fan_in)
        return jax.random.uniform(key, shape, dtype, -a, a)
    if scheme == "xavier":
        # DL4J XAVIER: N(0, 2/(fanIn+fanOut))
        return jax.random.normal(key, shape, dtype) * math.sqrt(2.0 / (fan_in + fan_out))
    if scheme == "xavier_uniform":
        a = math.sqrt(6.0 / (fan_in + fan_out))
        return jax.random.uniform(key, shape, dtype, -a, a)
    if scheme == "xavier_fan_in":
        return jax.random.normal(key, shape, dtype) * math.sqrt(1.0 / fan_in)
    if scheme == "xavier_legacy":
        return jax.random.normal(key, shape, dtype) * math.sqrt(1.0 / (fan_in + fan_out))
    if scheme == "relu":
        # He init: N(0, 2/fanIn)
        return jax.random.normal(key, shape, dtype) * math.sqrt(2.0 / fan_in)
    if scheme == "relu_uniform":
        a = math.sqrt(6.0 / fan_in)
        return jax.random.uniform(key, shape, dtype, -a, a)
    if scheme == "sigmoid_uniform":
        a = 4.0 * math.sqrt(6.0 / (fan_in + fan_out))
        return jax.random.uniform(key, shape, dtype, -a, a)
    if scheme == "lecun_normal":
        return jax.random.normal(key, shape, dtype) * math.sqrt(1.0 / fan_in)
    if scheme == "lecun_uniform":
        a = math.sqrt(3.0 / fan_in)
        return jax.random.uniform(key, shape, dtype, -a, a)
    if scheme == "distribution":
        return _from_distribution(key, shape, distribution or {}, dtype)
    raise ValueError(f"Unknown weight init scheme '{scheme}'")


def _from_distribution(key, shape, dist: dict, dtype) -> Array:
    """DL4J Distribution configs: {"type": "normal"|"uniform"|"binomial", ...}
    (reference nn/conf/distribution/*.java)."""
    kind = str(dist.get("type", "normal")).lower()
    if kind in ("normal", "gaussian"):
        mean = float(dist.get("mean", 0.0))
        std = float(dist.get("std", 1.0))
        return mean + std * jax.random.normal(key, shape, dtype)
    if kind == "uniform":
        lower = float(dist.get("lower", -1.0))
        upper = float(dist.get("upper", 1.0))
        return jax.random.uniform(key, shape, dtype, lower, upper)
    if kind == "binomial":
        n = int(dist.get("n", dist.get("numberOfTrials", 1)))
        p = float(dist.get("p", dist.get("probabilityOfSuccess", 0.5)))
        return jax.random.binomial(key, n, p, shape=shape).astype(dtype)
    raise ValueError(f"Unknown distribution type '{kind}'")
