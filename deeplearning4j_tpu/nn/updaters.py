"""Gradient updaters, learning-rate schedules, and gradient normalization/clipping.

Parity target: the reference's updater stack — per-variable ``GradientUpdater`` math
(ND4J org.nd4j.linalg.learning: Sgd/Nesterovs/Adam/AdaGrad/RmsProp/AdaDelta/NoOp,
imported at nn/updater/LayerUpdater.java:18), learning-rate schedules/policies
(LayerUpdater.java:135-154), and gradient normalization/clipping
(LayerUpdater.java:182-221). Implemented optax-style as pure (init, update) pairs over
param pytrees so the whole update fuses into the jitted train step; per-layer
hyperparameter overrides are resolved by the network from layer configs.

Update sign convention: ``update(grad, ...)`` returns the *step to subtract* from params
(params_new = params - step), matching the reference's
StochasticGradientDescent.stepFunction (NegativeGradientStepFunction).
"""
from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp

Array = jax.Array


# --------------------------------------------------------------------------- schedules
def effective_lr(base_lr: float, policy: Optional[str], iteration,
                 decay: float = 0.0, power: float = 0.0, steps: float = 1.0,
                 schedule: Optional[dict] = None, max_iterations: int = 1) -> Array:
    """Learning rate at ``iteration`` per DL4J LearningRatePolicy semantics
    (reference LayerUpdater.applyLrDecayPolicy:135-154)."""
    it = jnp.asarray(iteration, jnp.float32)
    p = (policy or "none").lower()
    if p in ("none", "fixed"):
        return jnp.asarray(base_lr, jnp.float32)
    if p == "exponential":
        return base_lr * jnp.power(decay, it)
    if p == "inverse":
        return base_lr / jnp.power(1.0 + decay * it, power)
    if p == "poly":
        return base_lr * jnp.power(1.0 - it / max(max_iterations, 1), power)
    if p == "sigmoid":
        return base_lr / (1.0 + jnp.exp(-decay * (it - steps)))
    if p == "step":
        return base_lr * jnp.power(decay, jnp.floor(it / steps))
    if p == "schedule":
        # piecewise-constant map {iteration: lr}: lr of the largest key <= iteration
        lr = jnp.asarray(base_lr, jnp.float32)
        for k in sorted((schedule or {}).keys(), key=int):
            lr = jnp.where(it >= int(k), jnp.float32((schedule or {})[k]), lr)
        return lr
    # TPU-era schedules beyond the reference's policy set
    if p == "cosine":
        # half-cosine from base_lr to ~0 over max_iterations
        frac = jnp.clip(it / max(max_iterations, 1), 0.0, 1.0)
        return base_lr * 0.5 * (1.0 + jnp.cos(jnp.pi * frac))
    if p == "warmup_cosine":
        # linear warmup over `steps` iterations, then cosine to max_iterations
        warm = jnp.maximum(jnp.asarray(steps, jnp.float32), 1.0)
        warm_lr = base_lr * it / warm
        frac = jnp.clip((it - warm) / jnp.maximum(max_iterations - warm, 1.0),
                        0.0, 1.0)
        cos_lr = base_lr * 0.5 * (1.0 + jnp.cos(jnp.pi * frac))
        return jnp.where(it < warm, warm_lr, cos_lr)
    raise ValueError(f"Unknown lr policy '{policy}'")


def scheduled_value(base: float, schedule: Optional[dict], iteration) -> Array:
    """Momentum-after style schedules: {iteration: value} (reference momentumSchedule)."""
    val = jnp.asarray(base, jnp.float32)
    if schedule:
        it = jnp.asarray(iteration, jnp.float32)
        for k in sorted(schedule.keys(), key=int):
            val = jnp.where(it >= int(k), jnp.float32(schedule[k]), val)
    return val


# --------------------------------------------------------------------------- updaters
@dataclasses.dataclass(frozen=True)
class UpdaterSpec:
    """Resolved per-layer updater hyperparameters."""

    name: str = "sgd"
    momentum: float = 0.9
    momentum_schedule: Optional[dict] = None
    rho: float = 0.95              # adadelta
    rms_decay: float = 0.95
    adam_mean_decay: float = 0.9
    adam_var_decay: float = 0.999
    epsilon: float = 1e-8


def updater_init(spec: UpdaterSpec, param: Array) -> dict:
    n = spec.name.lower()
    z = lambda: jnp.zeros_like(param)
    if n in ("sgd", "none", "noop"):
        return {}
    if n in ("nesterovs", "nesterov", "momentum"):
        return {"v": z()}
    if n == "adam":
        return {"m": z(), "v": z()}
    if n == "adagrad":
        return {"h": z()}
    if n == "rmsprop":
        return {"g2": z()}
    if n == "adadelta":
        return {"msg": z(), "msdx": z()}
    if n == "adamax":
        return {"m": z(), "u": z()}
    if n == "lars":
        return {"v": z()}
    if n == "lamb":
        return {"m": z(), "v": z()}
    raise ValueError(f"Unknown updater '{spec.name}'")


def updater_step(spec: UpdaterSpec, grad: Array, state: dict, lr: Array,
                 iteration) -> tuple[Array, dict]:
    """One update. Math mirrors ND4J org.nd4j.linalg.learning.* formulas."""
    n = spec.name.lower()
    eps = spec.epsilon
    if n in ("none", "noop"):
        return jnp.zeros_like(grad), state
    if n == "sgd":
        return lr * grad, state
    if n in ("nesterovs", "nesterov", "momentum"):
        # ND4J Nesterovs: v = mu*v_prev - lr*g; applied delta = -mu*v_prev + (1+mu)*v,
        # returned here as the subtractend (step = -delta).
        mu = scheduled_value(spec.momentum, spec.momentum_schedule, iteration)
        v_prev = state["v"]
        v = mu * v_prev - lr * grad
        step = mu * v_prev - (1 + mu) * v
        return step, {"v": v}
    if n == "adam":
        b1, b2 = spec.adam_mean_decay, spec.adam_var_decay
        t = jnp.asarray(iteration, jnp.float32) + 1.0
        m = b1 * state["m"] + (1 - b1) * grad
        v = b2 * state["v"] + (1 - b2) * grad * grad
        alpha = lr * jnp.sqrt(1 - jnp.power(b2, t)) / (1 - jnp.power(b1, t))
        return alpha * m / (jnp.sqrt(v) + eps), {"m": m, "v": v}
    if n == "adamax":
        b1, b2 = spec.adam_mean_decay, spec.adam_var_decay
        t = jnp.asarray(iteration, jnp.float32) + 1.0
        m = b1 * state["m"] + (1 - b1) * grad
        u = jnp.maximum(b2 * state["u"], jnp.abs(grad))
        return lr / (1 - jnp.power(b1, t)) * m / (u + eps), {"m": m, "u": u}
    if n == "adagrad":
        h = state["h"] + grad * grad
        return lr * grad / (jnp.sqrt(h) + eps), {"h": h}
    if n == "rmsprop":
        d = spec.rms_decay
        g2 = d * state["g2"] + (1 - d) * grad * grad
        return lr * grad / jnp.sqrt(g2 + eps), {"g2": g2}
    if n == "adadelta":
        rho = spec.rho
        msg = rho * state["msg"] + (1 - rho) * grad * grad
        dx = grad * jnp.sqrt(state["msdx"] + eps) / jnp.sqrt(msg + eps)
        msdx = rho * state["msdx"] + (1 - rho) * dx * dx
        return dx, {"msg": msg, "msdx": msdx}
    if n in ("lars", "lamb"):
        # trust-ratio updaters need the parameter value; callers route them
        # through updater_step_with_param
        raise ValueError(f"'{n}' needs the param value: call "
                         "updater_step_with_param")
    raise ValueError(f"Unknown updater '{spec.name}'")


def _safe_norm(x: Array) -> Array:
    return jnp.sqrt(jnp.sum(x.astype(jnp.float32) ** 2) + 1e-12)


def _needs_param(name: str) -> bool:
    return name.lower() in ("lars", "lamb")


def updater_step_with_param(spec: UpdaterSpec, grad: Array, param: Array,
                            state: dict, lr: Array,
                            iteration) -> tuple[Array, dict]:
    """Like updater_step, but for updaters whose math needs the parameter
    value itself (LARS/LAMB layerwise trust ratios). Falls through to
    updater_step for everything else."""
    n = spec.name.lower()
    eps = spec.epsilon
    if n == "lars":
        mu = scheduled_value(spec.momentum, spec.momentum_schedule, iteration)
        w_norm = _safe_norm(param)
        g_norm = _safe_norm(grad)
        trust = jnp.where(g_norm > 0, w_norm / g_norm, 1.0)
        trust = jnp.where(w_norm > 0, trust, 1.0)
        v = mu * state["v"] + lr * trust * grad
        return v, {"v": v}
    if n == "lamb":
        b1, b2 = spec.adam_mean_decay, spec.adam_var_decay
        t = jnp.asarray(iteration, jnp.float32) + 1.0
        m = b1 * state["m"] + (1 - b1) * grad
        v = b2 * state["v"] + (1 - b2) * grad * grad
        m_hat = m / (1 - jnp.power(b1, t))
        v_hat = v / (1 - jnp.power(b2, t))
        update = m_hat / (jnp.sqrt(v_hat) + eps)
        w_norm = _safe_norm(param)
        u_norm = _safe_norm(update)
        trust = jnp.where((w_norm > 0) & (u_norm > 0), w_norm / u_norm, 1.0)
        return lr * trust * update, {"m": m, "v": v}
    return updater_step(spec, grad, state, lr, iteration)


# ------------------------------------------------------------- gradient normalization
def grads_to_param_dtype(grads, params):
    """Explicit grad-dtype boundary at the autodiff/updater seam: cotangents
    arrive in whatever dtype the backward contraction accumulated in (f32
    under a ``grad_accum_dtype`` policy even for bf16 params); updater state
    and parameter deltas follow the PARAM dtype, so cast exactly here rather
    than letting promotion decide inside each updater rule."""
    return jax.tree_util.tree_map(lambda g, p: g.astype(p.dtype), grads, params)


def normalize_gradients(grads: dict, kind: Optional[str], threshold: float) -> dict:
    """Per-layer gradient normalization/clipping applied BEFORE the updater, matching
    reference LayerUpdater.preApply ordering (:182-221). ``grads`` is one layer's
    {param_name: grad} dict."""
    if not kind or kind.lower() in ("none",):
        return grads
    k = kind.lower()
    if k == "renormalizel2perlayer":
        norm = jnp.sqrt(sum(jnp.sum(g * g) for g in grads.values()) + 1e-12)
        return {n: g / norm for n, g in grads.items()}
    if k == "renormalizel2perparamtype":
        return {n: g / jnp.sqrt(jnp.sum(g * g) + 1e-12) for n, g in grads.items()}
    if k == "clipelementwiseabsolutevalue":
        t = threshold
        return {n: jnp.clip(g, -t, t) for n, g in grads.items()}
    if k == "clipl2perlayer":
        norm = jnp.sqrt(sum(jnp.sum(g * g) for g in grads.values()) + 1e-12)
        scale = jnp.minimum(1.0, threshold / norm)
        return {n: g * scale for n, g in grads.items()}
    if k == "clipl2perparamtype":
        out = {}
        for n, g in grads.items():
            norm = jnp.sqrt(jnp.sum(g * g) + 1e-12)
            out[n] = g * jnp.minimum(1.0, threshold / norm)
        return out
    raise ValueError(f"Unknown gradient normalization '{kind}'")
