"""Dataset fetchers: CIFAR-10, LFW, Curves.

Reference: deeplearning4j-core datasets/** fetchers + iterator impls
(CifarDataSetIterator, LFWDataSetIterator, CurvesDataFetcher — SURVEY.md
§2.2). This image has no network egress, so real data is picked up from
local directories when present ($CIFAR_DIR / $LFW_DIR etc.); otherwise a
deterministic, learnable synthetic stand-in with identical shapes is
generated so tests and examples run hermetically (same policy as
datasets/mnist.py).

CIFAR binary parsing rides the native C++ loader (nativert) when built.
"""
from __future__ import annotations

import os
from pathlib import Path
from typing import List, Optional

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterators import ArrayDataSetIterator

_CIFAR_DIRS = [os.environ.get("CIFAR_DIR", ""),
               str(Path.home() / ".cache" / "cifar10"),
               "/root/data/cifar10", "/root/data/cifar-10-batches-bin"]
_LFW_DIRS = [os.environ.get("LFW_DIR", ""),
             str(Path.home() / ".cache" / "lfw"), "/root/data/lfw"]


def _find_cifar_files(train: bool) -> Optional[List[Path]]:
    for d in _CIFAR_DIRS:
        if not d:
            continue
        base = Path(d)
        if not base.is_dir():
            continue
        if train:
            files = sorted(base.glob("data_batch_*.bin"))
        else:
            files = sorted(base.glob("test_batch.bin"))
        if files:
            return files
    return None


def _parse_cifar_numpy(files: List[Path]) -> tuple[np.ndarray, np.ndarray]:
    feats, labels = [], []
    for p in files:
        raw = np.frombuffer(p.read_bytes(), np.uint8)
        recs = raw.reshape(-1, 3073)
        labels.append(recs[:, 0])
        feats.append(recs[:, 1:])
    return np.concatenate(feats), np.concatenate(labels)


def _synthetic_cifar(n: int, seed: int) -> tuple[np.ndarray, np.ndarray]:
    """Class-dependent color+texture patches: learnable, deterministic."""
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, 10, n)
    base_hue = np.linspace(0, 1, 10, endpoint=False)
    imgs = np.empty((n, 32, 32, 3), np.float32)
    yy, xx = np.mgrid[0:32, 0:32] / 31.0
    for i, c in enumerate(labels):
        freq = 1 + (c % 5)
        pattern = 0.5 + 0.5 * np.sin(
            2 * np.pi * freq * (xx * np.cos(base_hue[c] * np.pi)
                                + yy * np.sin(base_hue[c] * np.pi)))
        rgb = np.stack([pattern * (0.3 + 0.7 * base_hue[c]),
                        pattern * (1.0 - base_hue[c]),
                        1.0 - pattern], axis=-1)
        imgs[i] = np.clip(rgb + rng.normal(0, 0.08, rgb.shape), 0, 1)
    return (imgs * 255).astype(np.uint8).reshape(n, -1), labels.astype(np.uint8)


class CifarDataSetIterator(ArrayDataSetIterator):
    """Reference CifarDataSetIterator. Yields NHWC [B, 32, 32, 3] float32 in
    [0,1] (or flattened [B, 3072] with flatten=True) + one-hot labels."""

    def __init__(self, batch: int, train: bool = True, shuffle: bool = True,
                 seed: int = 12, num_examples: Optional[int] = None,
                 flatten: bool = False):
        files = _find_cifar_files(train)
        if files is not None:
            feats, labels = _parse_cifar_numpy(files)
            self.synthetic = False
        else:
            n = num_examples or (50000 if train else 10000)
            feats, labels = _synthetic_cifar(n, 7 if train else 8)
            self.synthetic = True
        if num_examples is not None:
            feats, labels = feats[:num_examples], labels[:num_examples]
        x = feats.astype(np.float32) / 255.0
        # Canonicalize to NHWC BEFORE any flattening: CIFAR binaries are
        # channel-major (3,32,32) while synthetic is HWC — flattening the raw
        # layouts would give flatten=True a source-dependent pixel order.
        if not self.synthetic:
            x = x.reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
        else:
            x = x.reshape(-1, 32, 32, 3)
        if flatten:
            x = x.reshape(len(x), -1)
        y = np.zeros((len(labels), 10), np.float32)
        y[np.arange(len(labels)), labels] = 1.0
        super().__init__(x, y, batch, shuffle=shuffle, seed=seed)


def _find_lfw_dir() -> Optional[Path]:
    for d in _LFW_DIRS:
        if d and Path(d).is_dir() and any(Path(d).iterdir()):
            return Path(d)
    return None


def _synthetic_faces(n: int, n_people: int, size: int,
                     seed: int) -> tuple[np.ndarray, np.ndarray]:
    """Per-person parametric 'face': ellipse + eye/mouth offsets drawn from a
    person-specific generator, so identity is learnable."""
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, n_people, n)
    yy, xx = np.mgrid[0:size, 0:size] / (size - 1.0)
    imgs = np.empty((n, size, size), np.float32)
    for i, p in enumerate(labels):
        prng = np.random.default_rng(5000 + int(p))
        cx, cy = prng.uniform(0.4, 0.6, 2)
        rx, ry = prng.uniform(0.25, 0.35, 2)
        face = (((xx - cx) / rx) ** 2 + ((yy - cy) / ry) ** 2 < 1).astype(float)
        ex = prng.uniform(0.10, 0.16)
        ey = prng.uniform(0.10, 0.18)
        for sx in (-1, 1):
            face -= 0.8 * (((xx - (cx + sx * ex)) ** 2
                            + (yy - (cy - ey)) ** 2) < 0.002)
        mw = prng.uniform(0.08, 0.14)
        face -= 0.6 * ((np.abs(xx - cx) < mw)
                       & (np.abs(yy - (cy + 0.15)) < 0.02))
        imgs[i] = np.clip(face + rng.normal(0, 0.05, face.shape), 0, 1)
    return imgs, labels


class LFWDataSetIterator(ArrayDataSetIterator):
    """Reference LFWDataSetIterator: labeled faces. Real data = a directory
    of per-person subdirectories of images (loaded via ImageRecordReader);
    otherwise synthetic parametric faces."""

    def __init__(self, batch: int, num_examples: int = 1000,
                 num_labels: int = 20, image_size: int = 28,
                 shuffle: bool = True, seed: int = 12):
        root = _find_lfw_dir()
        if root is not None:
            from deeplearning4j_tpu.datavec.records import ImageRecordReader
            rr = ImageRecordReader(root, image_size, image_size, channels=1)
            recs = []
            for i, rec in enumerate(rr):
                if i >= num_examples:
                    break
                recs.append(rec)
            arr = np.asarray(recs, np.float32)
            x, labels = arr[:, :-1], arr[:, -1].astype(int)
            num_labels = rr.num_labels()
            x = x.reshape(len(x), image_size, image_size, 1)
            self.synthetic = False
        else:
            imgs, labels = _synthetic_faces(num_examples, num_labels,
                                            image_size, 99)
            x = imgs[..., None]
            self.synthetic = True
        y = np.zeros((len(labels), num_labels), np.float32)
        y[np.arange(len(labels)), labels] = 1.0
        super().__init__(x, y, batch, shuffle=shuffle, seed=seed)


def _synthetic_curves(n: int, size: int, seed: int) -> np.ndarray:
    """Random smooth curves rasterized on a size x size grid (reference
    Curves dataset for autoencoder pretraining)."""
    rng = np.random.default_rng(seed)
    imgs = np.zeros((n, size, size), np.float32)
    t = np.linspace(0, 1, 6 * size)
    for i in range(n):
        # random cubic bezier
        pts = rng.uniform(0.1, 0.9, (4, 2))
        b = ((1 - t)[:, None] ** 3 * pts[0] + 3 * (1 - t)[:, None] ** 2
             * t[:, None] * pts[1] + 3 * (1 - t)[:, None] * t[:, None] ** 2
             * pts[2] + t[:, None] ** 3 * pts[3])
        rr_ = np.clip((b[:, 1] * (size - 1)).astype(int), 0, size - 1)
        cc = np.clip((b[:, 0] * (size - 1)).astype(int), 0, size - 1)
        imgs[i, rr_, cc] = 1.0
    return imgs.reshape(n, -1)


class CurvesDataSetIterator(ArrayDataSetIterator):
    """Reference CurvesDataFetcher: unlabeled curve images for autoencoder
    pretraining — labels are the features themselves."""

    def __init__(self, batch: int, num_examples: int = 2000, size: int = 28,
                 seed: int = 12):
        x = _synthetic_curves(num_examples, size, 17)
        super().__init__(x, x.copy(), batch, shuffle=False, seed=seed)


class IrisDataSetIterator(ArrayDataSetIterator):
    """Alias of datasets.mnist.IrisDataSetIterator for discoverability."""

    def __init__(self, batch: int = 150, num_examples: int = 150,
                 seed: int = 42):
        from deeplearning4j_tpu.datasets.mnist import IrisDataSetIterator as _I
        inner = _I(batch, num_examples, seed)
        super().__init__(inner.features, inner.labels, batch, shuffle=False)
