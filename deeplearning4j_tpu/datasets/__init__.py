from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterators import (
    DataSetIterator, ListDataSetIterator, ArrayDataSetIterator,
    AsyncDataSetIterator, MultipleEpochsIterator, SamplingDataSetIterator,
)
from deeplearning4j_tpu.datasets.prefetch import DevicePrefetcher
