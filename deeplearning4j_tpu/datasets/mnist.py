"""MNIST dataset iterator.

Reference: deeplearning4j-core datasets/mnist/MnistManager.java + MnistDbFile.java (raw
IDX parsing) and datasets/iterator/impl/MnistDataSetIterator.java:30.

Real IDX files are parsed when present (searched in $MNIST_DIR, ~/.cache/mnist,
/root/data/mnist — this image has no network egress, so no downloader). When absent, a
deterministic procedurally-generated digit set with the same shapes/statistics stands in
so tests and benchmarks run hermetically; the generator draws digit-dependent stroke
patterns, giving a learnable (not random-label) classification task.
"""
from __future__ import annotations

import gzip
import os
import struct
from pathlib import Path
from typing import Optional

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterators import ArrayDataSetIterator

_SEARCH_DIRS = [
    os.environ.get("MNIST_DIR", ""),
    str(Path.home() / ".cache" / "mnist"),
    "/root/data/mnist",
]

_FILES = {
    True: ("train-images-idx3-ubyte", "train-labels-idx1-ubyte"),
    False: ("t10k-images-idx3-ubyte", "t10k-labels-idx1-ubyte"),
}


def _read_idx(path: Path) -> np.ndarray:
    """Parse an IDX file (reference MnistDbFile.java header handling). Plain
    (non-gz) files go through the native C++ parser when available."""
    if path.suffix != ".gz":
        from deeplearning4j_tpu import nativert
        arr = nativert.read_idx(str(path))
        if arr is not None:
            return arr
    opener = gzip.open if path.suffix == ".gz" else open
    with opener(path, "rb") as f:
        magic = struct.unpack(">i", f.read(4))[0]
        ndim = magic & 0xFF
        dims = [struct.unpack(">i", f.read(4))[0] for _ in range(ndim)]
        data = np.frombuffer(f.read(), dtype=np.uint8)
    return data.reshape(dims)


def _find_real_mnist(train: bool) -> Optional[tuple[np.ndarray, np.ndarray]]:
    img_name, lbl_name = _FILES[train]
    for d in _SEARCH_DIRS:
        if not d:
            continue
        base = Path(d)
        for suffix in ("", ".gz"):
            img, lbl = base / (img_name + suffix), base / (lbl_name + suffix)
            if img.exists() and lbl.exists():
                return _read_idx(img), _read_idx(lbl)
    return None


def _synthetic_mnist(n: int, seed: int = 123) -> tuple[np.ndarray, np.ndarray]:
    """Deterministic learnable digit-like data: each class is a distinct spatial
    template (strokes on a 28x28 grid) plus pixel noise."""
    rng = np.random.default_rng(seed)
    templates = np.zeros((10, 28, 28), np.float32)
    for d in range(10):
        trng = np.random.default_rng(1000 + d)
        for _ in range(4):  # 4 strokes per digit class
            r0, c0 = trng.integers(4, 24, 2)
            dr, dc = trng.integers(-3, 4, 2)
            for t in range(12):
                r = int(np.clip(r0 + dr * t / 4, 0, 27))
                c = int(np.clip(c0 + dc * t / 4, 0, 27))
                templates[d, r, c] = 1.0
                if r + 1 < 28:
                    templates[d, r + 1, c] = max(templates[d, r + 1, c], 0.6)
                if c + 1 < 28:
                    templates[d, r, c + 1] = max(templates[d, r, c + 1], 0.6)
    labels = rng.integers(0, 10, n)
    imgs = templates[labels]
    # small random shifts + noise
    shifted = np.empty_like(imgs)
    for i in range(n):
        sr, sc = rng.integers(-2, 3, 2)
        shifted[i] = np.roll(np.roll(imgs[i], sr, axis=0), sc, axis=1)
    noisy = np.clip(shifted + rng.normal(0, 0.15, shifted.shape), 0, 1)
    return (noisy * 255).astype(np.uint8), labels.astype(np.uint8)


class MnistDataSetIterator(ArrayDataSetIterator):
    """Reference MnistDataSetIterator.java:30 equivalent. Yields DataSets with
    features [B, 784] float32 in [0,1] and one-hot labels [B, 10]."""

    def __init__(self, batch: int, train: bool = True, shuffle: bool = True,
                 seed: int = 6, num_examples: Optional[int] = None,
                 flatten: bool = True):
        real = _find_real_mnist(train)
        if real is not None:
            images, labels = real
            self.synthetic = False
        else:
            n = num_examples or (60000 if train else 10000)
            images, labels = _synthetic_mnist(n, seed=123 if train else 321)
            self.synthetic = True
        if num_examples is not None:
            images, labels = images[:num_examples], labels[:num_examples]
        feats = images.astype(np.float32) / 255.0
        feats = feats.reshape(len(feats), -1) if flatten else feats[..., None]
        onehot = np.zeros((len(labels), 10), np.float32)
        onehot[np.arange(len(labels)), labels] = 1.0
        super().__init__(feats, onehot, batch, shuffle=shuffle, seed=seed)


class IrisDataSetIterator(ArrayDataSetIterator):
    """Reference datasets/iterator/impl/IrisDataSetIterator. Without bundled data files
    the three classes are generated as deterministic Gaussian clusters with
    iris-like means/spreads in 4-D feature space."""

    _MEANS = np.array([[5.0, 3.4, 1.5, 0.2],
                       [5.9, 2.8, 4.3, 1.3],
                       [6.6, 3.0, 5.6, 2.0]], np.float32)
    _STDS = np.array([[0.35, 0.38, 0.17, 0.10],
                      [0.52, 0.31, 0.47, 0.20],
                      [0.64, 0.32, 0.55, 0.27]], np.float32)

    def __init__(self, batch: int = 150, num_examples: int = 150, seed: int = 42):
        rng = np.random.default_rng(seed)
        per = num_examples // 3
        feats, labels = [], []
        for c in range(3):
            feats.append(rng.normal(self._MEANS[c], self._STDS[c],
                                    (per, 4)).astype(np.float32))
            labels.append(np.full(per, c))
        x = np.concatenate(feats)
        y = np.concatenate(labels)
        idx = rng.permutation(len(x))
        x, y = x[idx], y[idx]
        onehot = np.zeros((len(y), 3), np.float32)
        onehot[np.arange(len(y)), y] = 1.0
        super().__init__(x, onehot, batch, shuffle=False)
