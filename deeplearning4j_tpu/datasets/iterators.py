"""DataSetIterator family.

Reference: nn datasets/iterator/*.java (19 files) — notably AsyncDataSetIterator.java:36
(background prefetch thread + blocking queue). The async iterator here does the same
host-side prefetch with a worker thread; on TPU this overlaps host batch assembly with
device compute (the jitted step is dispatched asynchronously anyway, so one batch of
lookahead suffices to keep the device fed).
"""
from __future__ import annotations

from typing import Iterator, Optional

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet


class DataSetIterator:
    """Iterator protocol: for ds in it: ...; reset() to rewind (reference
    org.nd4j.linalg.dataset.api.iterator.DataSetIterator)."""

    def __iter__(self) -> Iterator[DataSet]:
        raise NotImplementedError

    def reset(self) -> None:
        pass

    def batch_size(self) -> int:
        raise NotImplementedError

    def total_examples(self) -> int:
        raise NotImplementedError


class ListDataSetIterator(DataSetIterator):
    """Iterate over a pre-built list of DataSets (reference ListDataSetIterator)."""

    def __init__(self, datasets: list, batch: Optional[int] = None):
        self._list = datasets
        self._batch = batch or (datasets[0].num_examples() if datasets else 0)

    def __iter__(self):
        return iter(self._list)

    def batch_size(self) -> int:
        return self._batch

    def total_examples(self) -> int:
        return sum(d.num_examples() for d in self._list)


class ArrayDataSetIterator(DataSetIterator):
    """Minibatch iterator over arrays with optional shuffle per epoch."""

    def __init__(self, features: np.ndarray, labels: np.ndarray, batch: int,
                 shuffle: bool = False, seed: int = 0,
                 features_mask: Optional[np.ndarray] = None,
                 labels_mask: Optional[np.ndarray] = None,
                 drop_last: bool = True):
        self.features = np.asarray(features)
        self.labels = np.asarray(labels)
        self.features_mask = features_mask
        self.labels_mask = labels_mask
        self._batch = batch
        self._shuffle = shuffle
        self._seed = seed
        self._epoch = 0
        self._drop_last = drop_last

    def __iter__(self):
        n = self.features.shape[0]
        idx = np.arange(n)
        if self._shuffle:
            rng = np.random.default_rng(self._seed + self._epoch)
            rng.shuffle(idx)
        self._epoch += 1
        end = n - (n % self._batch) if self._drop_last and n % self._batch else n
        for i in range(0, end, self._batch):
            sl = idx[i:i + self._batch]
            yield DataSet(
                self.features[sl], self.labels[sl],
                self.features_mask[sl] if self.features_mask is not None else None,
                self.labels_mask[sl] if self.labels_mask is not None else None)

    def batch_size(self) -> int:
        return self._batch

    def total_examples(self) -> int:
        return int(self.features.shape[0])


class AsyncDataSetIterator(DataSetIterator):
    """Background-thread prefetch wrapper (reference AsyncDataSetIterator.java:36).

    Built on datasets.prefetch.DevicePrefetcher (identity stage: host batches
    only — device staging belongs to the fit loops). The prefetcher's bounded
    put polls a stop flag, so a consumer that exits early (early-stopping
    break, listener exception) shuts the producer down instead of leaving it
    blocked on a full queue forever (pinned by tests/test_prefetch.py)."""

    def __init__(self, base: DataSetIterator, queue_size: int = 4):
        self.base = base
        self.queue_size = queue_size
        self._pf = None  # most recent producer, exposed for shutdown/tests

    def __iter__(self):
        from deeplearning4j_tpu.datasets.prefetch import DevicePrefetcher

        self.close()  # a re-iteration abandons the previous producer
        self._pf = DevicePrefetcher(self.base, depth=max(1, self.queue_size),
                                    path=None)
        return iter(self._pf)

    def close(self) -> None:
        if self._pf is not None:
            self._pf.close()

    def reset(self) -> None:
        self.close()
        if hasattr(self.base, "reset"):  # base may be a plain iterable/list
            self.base.reset()

    def batch_size(self) -> int:
        return self.base.batch_size()

    def total_examples(self) -> int:
        return self.base.total_examples()


class MultipleEpochsIterator(DataSetIterator):
    """Repeat a base iterator N times (reference MultipleEpochsIterator.java)."""

    def __init__(self, epochs: int, base: DataSetIterator):
        self.epochs = epochs
        self.base = base

    def __iter__(self):
        for _ in range(self.epochs):
            self.base.reset()
            yield from self.base

    def reset(self) -> None:
        self.base.reset()

    def batch_size(self) -> int:
        return self.base.batch_size()

    def total_examples(self) -> int:
        return self.epochs * self.base.total_examples()


class SamplingDataSetIterator(DataSetIterator):
    """Sample random minibatches with replacement (reference
    SamplingDataSetIterator.java)."""

    def __init__(self, dataset: DataSet, batch: int, total_batches: int, seed: int = 0):
        self.dataset = dataset
        self._batch = batch
        self.total_batches = total_batches
        self._seed = seed
        self._epoch = 0

    def __iter__(self):
        rng = np.random.default_rng(self._seed + self._epoch)
        self._epoch += 1
        n = self.dataset.num_examples()
        for _ in range(self.total_batches):
            idx = rng.integers(0, n, self._batch)
            yield DataSet(self.dataset.features[idx], self.dataset.labels[idx])

    def batch_size(self) -> int:
        return self._batch

    def total_examples(self) -> int:
        return self._batch * self.total_batches


class ExistingDataSetIterator(ListDataSetIterator):
    """Wrap pre-built DataSets — accepts any iterable, including generators,
    like the reference's Iterator<DataSet> constructor (reference
    datasets/iterator/ExistingDataSetIterator.java)."""

    def __init__(self, datasets, batch=None):
        super().__init__(list(datasets), batch)
