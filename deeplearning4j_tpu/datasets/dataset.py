"""DataSet: features + labels (+ masks) container with normalization support.

Reference: ND4J org.nd4j.linalg.dataset.DataSet (external dep, used 160x across the
reference per SURVEY.md §1). Host-side numpy until it crosses into a jitted step.
"""
from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np


@dataclasses.dataclass
class DataSet:
    features: np.ndarray
    labels: np.ndarray
    features_mask: Optional[np.ndarray] = None
    labels_mask: Optional[np.ndarray] = None

    def num_examples(self) -> int:
        return int(self.features.shape[0])

    def split_test_and_train(self, n_train: int) -> tuple["DataSet", "DataSet"]:
        def cut(a, sl):
            return a[sl] if a is not None else None

        tr = slice(0, n_train)
        te = slice(n_train, None)
        return (DataSet(self.features[tr], self.labels[tr],
                        cut(self.features_mask, tr), cut(self.labels_mask, tr)),
                DataSet(self.features[te], self.labels[te],
                        cut(self.features_mask, te), cut(self.labels_mask, te)))

    def shuffle(self, seed: Optional[int] = None) -> None:
        rng = np.random.default_rng(seed)
        idx = rng.permutation(self.num_examples())
        self.features = self.features[idx]
        self.labels = self.labels[idx]
        if self.features_mask is not None:
            self.features_mask = self.features_mask[idx]
        if self.labels_mask is not None:
            self.labels_mask = self.labels_mask[idx]

    def batch_by(self, batch_size: int) -> list["DataSet"]:
        out = []
        for i in range(0, self.num_examples(), batch_size):
            sl = slice(i, i + batch_size)
            out.append(DataSet(
                self.features[sl], self.labels[sl],
                self.features_mask[sl] if self.features_mask is not None else None,
                self.labels_mask[sl] if self.labels_mask is not None else None))
        return out


class NormalizerStandardize:
    """Feature-wise zero-mean/unit-variance normalizer (reference ND4J
    NormalizerStandardize; serialized into the model zip as normalizer.bin)."""

    def __init__(self):
        self.mean: Optional[np.ndarray] = None
        self.std: Optional[np.ndarray] = None

    def fit(self, ds: DataSet) -> None:
        flat = ds.features.reshape(ds.features.shape[0], -1)
        self.mean = flat.mean(axis=0)
        self.std = flat.std(axis=0) + 1e-8

    def transform(self, ds: DataSet) -> None:
        shape = ds.features.shape
        flat = ds.features.reshape(shape[0], -1)
        ds.features = ((flat - self.mean) / self.std).reshape(shape)

    def revert(self, ds: DataSet) -> None:
        shape = ds.features.shape
        flat = ds.features.reshape(shape[0], -1)
        ds.features = (flat * self.std + self.mean).reshape(shape)

    def to_arrays(self) -> dict:
        return {"mean": self.mean, "std": self.std}

    @staticmethod
    def from_arrays(d: dict) -> "NormalizerStandardize":
        n = NormalizerStandardize()
        n.mean, n.std = d["mean"], d["std"]
        return n


class NormalizerMinMaxScaler:
    """Min-max [0,1] scaling (reference ND4J NormalizerMinMaxScaler)."""

    def __init__(self, min_range: float = 0.0, max_range: float = 1.0):
        self.min_range = min_range
        self.max_range = max_range
        self.data_min: Optional[np.ndarray] = None
        self.data_max: Optional[np.ndarray] = None

    def fit(self, ds: DataSet) -> None:
        flat = ds.features.reshape(ds.features.shape[0], -1)
        self.data_min = flat.min(axis=0)
        self.data_max = flat.max(axis=0)

    def transform(self, ds: DataSet) -> None:
        shape = ds.features.shape
        flat = ds.features.reshape(shape[0], -1)
        rng = np.where(self.data_max > self.data_min, self.data_max - self.data_min, 1.0)
        scaled = (flat - self.data_min) / rng
        ds.features = (scaled * (self.max_range - self.min_range)
                       + self.min_range).reshape(shape)
