"""Device prefetch: overlap host->device transfer with the running dispatch.

Reference AsyncDataSetIterator.java:36 prefetches *host* batches on a worker
thread. On TPU that is only half the win: the staging phase the telemetry
attributes per step (``dl4j_fit_phase_seconds{phase="staging"}``) is the host
stack + ``stage_dtype`` cast + transfer SUBMIT, and in the synchronous fit
loops it ran serially before every donated dispatch. ``DevicePrefetcher``
moves that work to a background thread: while step *n*'s dispatch executes,
the producer pulls the next K-step group from the iterator, stages it, and
issues a **non-blocking** ``jax.device_put`` — so batch *n+1* is in flight to
HBM behind the compute (the tf.data/GPipe input-pipeline overlap pattern).

Donation safety — the ownership hand-off, explicitly:

* The jitted train steps donate ONLY ``(params, states, updater_state)``
  (``donate_argnums=(0, 1, 2)``); batch inputs are never donated, so XLA
  never reuses a staged batch buffer for step outputs.
* Every staged item is produced from host numpy by ``jax.device_put`` /
  ``make_array_from_callback`` — a FRESH device buffer per group, never a
  view of a buffer an in-flight step reads.
* Each queue slot is consumed by exactly one dispatch: the consumer pops an
  item, hands it to the train step, and drops its reference. The producer
  holds no reference after ``put``. Nothing ever aliases the donated
  params/state buffers, so depth-2 prefetch cannot trigger a
  "deleted buffer" error (pinned by tests/test_prefetch.py).

Bounded depth (default 2 = double buffering) caps HBM held by staged batches
at ``depth * group_bytes``; depth <= 0 degrades to synchronous inline staging
(the pre-prefetch behavior, used by the numerical-equivalence tests and the
bench A/B).
"""
from __future__ import annotations

import queue
import threading
import time
from typing import Callable, Iterable, Optional

from deeplearning4j_tpu.observability.names import (
    PREFETCH_BYTES_TOTAL, PREFETCH_DEPTH, PREFETCH_OVERLAP_RATIO,
    PREFETCH_STAGING_SECONDS_TOTAL, PREFETCH_WAIT_SECONDS_TOTAL,
)
from deeplearning4j_tpu.observability.metrics import (
    global_registry as _obs_registry, tree_nbytes as _tree_nbytes,
)

# families resolved once at import; one series per `path` label (which fit
# loop is prefetching). Budget pinned by test_telemetry_overhead_budget.
_depth_gauge = _obs_registry().gauge(
    PREFETCH_DEPTH,
    "staged items currently queued ahead of the dispatch loop, by fit path")
_bytes_total = _obs_registry().counter(
    PREFETCH_BYTES_TOTAL,
    "bytes of staged device arrays handed to the prefetch queue, by fit path")
_staging_total = _obs_registry().counter(
    PREFETCH_STAGING_SECONDS_TOTAL,
    "producer-thread seconds spent pulling + staging items (the work hidden "
    "behind dispatch when overlap works), by fit path")
_wait_total = _obs_registry().counter(
    PREFETCH_WAIT_SECONDS_TOTAL,
    "consumer seconds blocked waiting for a staged item (staging NOT hidden "
    "behind dispatch), by fit path")
_overlap_gauge = _obs_registry().gauge(
    PREFETCH_OVERLAP_RATIO,
    "1 - wait/staging over this prefetcher's lifetime: fraction of staging "
    "time hidden behind dispatch (1.0 = fully overlapped)")

_DONE = object()  # queue sentinel: producer finished (or was stopped)


class DevicePrefetcher:
    """Pull items from ``source`` on a background thread, run ``stage`` on
    each (stack + cast + non-blocking ``jax.device_put`` — staging decides
    the sharding, e.g. a ``NamedSharding`` from ParallelWrapper._batch_spec),
    and yield staged items in order through a bounded queue.

    Single-use iterable. Errors raised by the iterator or by ``stage``
    propagate to the consumer AFTER every item staged before them — the
    consumer observes the same prefix of work as the synchronous loop.
    ``close()`` (also called when iteration ends or the consumer's for-loop
    exits early) shuts the producer down deterministically; the thread never
    stays blocked on a full queue.

    ``wait_series``: optional histogram series (e.g. the fit loops'
    ``dl4j_fit_phase_seconds{phase="staging"}``) observing what the consumer
    actually waited per item — under working overlap it collapses toward 0.
    ``path=None`` disables all metrics (host-only use, AsyncDataSetIterator).
    """

    def __init__(self, source: Iterable, stage: Optional[Callable] = None,
                 *, depth: int = 2, path: Optional[str] = "default",
                 wait_series=None):
        self._source = source
        self._stage = stage
        self._depth = depth
        self._wait_series = wait_series
        if path is not None:
            self._m_depth = _depth_gauge.labels(path=path)
            self._m_bytes = _bytes_total.labels(path=path)
            self._m_staging = _staging_total.labels(path=path)
            self._m_wait = _wait_total.labels(path=path)
            self._m_overlap = _overlap_gauge.labels(path=path)
        else:
            self._m_depth = self._m_bytes = self._m_staging = None
            self._m_wait = self._m_overlap = None
        self._q: queue.Queue = queue.Queue(maxsize=max(1, depth))
        self._stop = threading.Event()
        self._error: Optional[BaseException] = None
        self._staged_s = 0.0  # producer-side total (GIL-atomic float adds)
        self._wait_s = 0.0
        self.thread: Optional[threading.Thread] = None

    # ---------------------------------------------------------------- producer
    def _put(self, item) -> bool:
        """Bounded put that polls the stop flag — a consumer that went away
        can never strand the producer on a full queue (the reference
        AsyncDataSetIterator leak)."""
        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=0.05)
                return True
            except queue.Full:
                continue
        return False

    def _run(self) -> None:
        try:
            it = iter(self._source)
            while not self._stop.is_set():
                t0 = time.perf_counter()
                try:
                    item = next(it)
                except StopIteration:
                    break
                if self._stage is not None:
                    item = self._stage(item)
                dt = time.perf_counter() - t0
                self._staged_s += dt
                if self._m_staging is not None:
                    self._m_staging.inc(dt)
                    nbytes = _tree_nbytes(item)
                    if nbytes:
                        self._m_bytes.inc(nbytes)
                if not self._put(item):
                    return
                if self._m_depth is not None:
                    self._m_depth.set(self._q.qsize())
        except BaseException as e:  # propagate into the consumer, in order
            self._error = e
        finally:
            self._put(_DONE)

    # ---------------------------------------------------------------- consumer
    def __iter__(self):
        if self._depth <= 0:
            yield from self._iter_sync()
            return
        self.thread = threading.Thread(
            target=self._run, daemon=True,
            name="dl4j-prefetch" if self._wait_series is None
            else "dl4j-prefetch-staging")
        self.thread.start()
        try:
            while True:
                t0 = time.perf_counter()
                item = self._q.get()
                wait = time.perf_counter() - t0
                if item is _DONE:
                    if self._error is not None:
                        raise self._error
                    return
                self._wait_s += wait
                if self._m_wait is not None:
                    self._m_wait.inc(wait)
                    self._m_depth.set(self._q.qsize())
                    if self._staged_s > 0.0:
                        self._m_overlap.set(max(0.0, min(1.0,
                            1.0 - self._wait_s / self._staged_s)))
                if self._wait_series is not None:
                    self._wait_series.observe(wait)
                yield item
        finally:
            self.close()

    def _iter_sync(self):
        """depth <= 0: the exact pre-prefetch behavior — stage inline on the
        consumer thread, full staging cost visible in ``wait_series``."""
        for item in self._source:
            t0 = time.perf_counter()
            if self._stage is not None:
                item = self._stage(item)
            dt = time.perf_counter() - t0
            if self._m_staging is not None:
                self._m_staging.inc(dt)
                nbytes = _tree_nbytes(item)
                if nbytes:
                    self._m_bytes.inc(nbytes)
            if self._wait_series is not None:
                self._wait_series.observe(dt)
            yield item

    def close(self) -> None:
        """Deterministic shutdown: stop the producer, unblock it by draining
        the queue, and join. Safe to call more than once."""
        self._stop.set()
        if self.thread is None:
            return
        while True:
            try:
                self._q.get_nowait()
            except queue.Empty:
                break
        self.thread.join(timeout=5.0)

    def __enter__(self) -> "DevicePrefetcher":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
