"""Native runtime bindings (C++ host-side IO/staging pipeline).

The reference reaches all native code through JavaCPP bindings (SURVEY.md
§2.10): libnd4j tensor backends, cuDNN helpers, HDF5. Its data path runs
through AsyncDataSetIterator (background prefetch thread + blocking queue,
reference deeplearning4j-nn datasets/iterator/AsyncDataSetIterator.java:36)
and MagicQueue (parallelism/MagicQueue.java:21). Here the equivalent host
runtime is ``native/src/dl4j_runtime.cpp`` — IDX/CIFAR parsers, an async
producer-thread batch loader, a numeric CSV reader, and the binary stats
codec (SBE-codec equivalent, reference ui-model ui/stats/sbe/*) — consumed
via ctypes. Device compute stays in XLA; this layer only stages host memory.

The shared library is built on demand with g++ (toolchain is baked into the
image); every entry point degrades to ``None``/pure-Python when the build is
unavailable so the framework never hard-requires the native path.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from pathlib import Path
from typing import List, Optional, Sequence

import numpy as np

_REPO_ROOT = Path(__file__).resolve().parent.parent.parent
_NATIVE_DIR = _REPO_ROOT / "native"
_LIB_PATH = _NATIVE_DIR / "libdl4j_runtime.so"
_SRC_PATH = _NATIVE_DIR / "src" / "dl4j_runtime.cpp"

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_load_attempted = False

c_i64 = ctypes.c_int64
c_f32p = ctypes.POINTER(ctypes.c_float)
c_u8p = ctypes.POINTER(ctypes.c_uint8)
c_i32p = ctypes.POINTER(ctypes.c_int32)
c_i64p = ctypes.POINTER(ctypes.c_int64)


def _build() -> bool:
    if not _SRC_PATH.exists():
        return False
    try:
        subprocess.run(
            ["g++", "-O3", "-std=c++17", "-fPIC", "-shared", "-pthread",
             str(_SRC_PATH), "-o", str(_LIB_PATH)],
            check=True, capture_output=True, timeout=120)
        return _LIB_PATH.exists()
    except (OSError, subprocess.SubprocessError) as e:
        # leave a post-mortem breadcrumb (worker_exit-style): a silent False
        # here used to mean "mysteriously slow Python paths" with no trace
        try:
            from deeplearning4j_tpu.observability.flight_recorder import (
                global_recorder)
            stderr = getattr(e, "stderr", b"") or b""
            global_recorder().record(
                "native_build_failed", src=str(_SRC_PATH), error=repr(e),
                stderr=stderr[-500:].decode("utf-8", "replace")
                if isinstance(stderr, bytes) else str(stderr)[-500:])
        except Exception:  # lint: swallowed-exception-ok (telemetry must not turn a degraded build into a crash)
            pass
        return False


def _declare(lib: ctypes.CDLL) -> None:
    lib.dl4j_idx_open.restype = ctypes.c_void_p
    lib.dl4j_idx_open.argtypes = [ctypes.c_char_p]
    lib.dl4j_idx_ndim.restype = ctypes.c_int
    lib.dl4j_idx_ndim.argtypes = [ctypes.c_void_p]
    lib.dl4j_idx_dims.argtypes = [ctypes.c_void_p, c_i64p]
    lib.dl4j_idx_read.argtypes = [ctypes.c_void_p, c_u8p]
    lib.dl4j_idx_close.argtypes = [ctypes.c_void_p]

    lib.dl4j_loader_create_from_arrays.restype = ctypes.c_void_p
    lib.dl4j_loader_create_from_arrays.argtypes = [
        c_u8p, c_u8p, c_i64, c_i64, ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ctypes.c_int, ctypes.c_uint64, ctypes.c_int]
    lib.dl4j_mnist_loader_create.restype = ctypes.c_void_p
    lib.dl4j_mnist_loader_create.argtypes = [
        ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int, ctypes.c_int,
        ctypes.c_int, ctypes.c_uint64, ctypes.c_int]
    lib.dl4j_cifar_loader_create.restype = ctypes.c_void_p
    lib.dl4j_cifar_loader_create.argtypes = [
        ctypes.POINTER(ctypes.c_char_p), ctypes.c_int, ctypes.c_int,
        ctypes.c_int, ctypes.c_int, ctypes.c_uint64]
    for name in ("dl4j_loader_num_examples", "dl4j_loader_feature_size"):
        getattr(lib, name).restype = c_i64
        getattr(lib, name).argtypes = [ctypes.c_void_p]
    for name in ("dl4j_loader_num_classes", "dl4j_loader_batch_size"):
        getattr(lib, name).restype = ctypes.c_int
        getattr(lib, name).argtypes = [ctypes.c_void_p]
    lib.dl4j_loader_next.restype = ctypes.c_int
    lib.dl4j_loader_next.argtypes = [ctypes.c_void_p, c_f32p, c_f32p]
    lib.dl4j_loader_reset.argtypes = [ctypes.c_void_p]
    lib.dl4j_loader_close.argtypes = [ctypes.c_void_p]

    lib.dl4j_csv_open.restype = ctypes.c_void_p
    lib.dl4j_csv_open.argtypes = [ctypes.c_char_p, ctypes.c_char, ctypes.c_int]
    lib.dl4j_csv_open2.restype = ctypes.c_void_p
    lib.dl4j_csv_open2.argtypes = [ctypes.c_char_p, ctypes.c_char,
                                   ctypes.c_int, ctypes.c_int]
    lib.dl4j_csv_rows.restype = c_i64
    lib.dl4j_csv_rows.argtypes = [ctypes.c_void_p]
    lib.dl4j_csv_cols.restype = c_i64
    lib.dl4j_csv_cols.argtypes = [ctypes.c_void_p]
    lib.dl4j_csv_read.argtypes = [ctypes.c_void_p, c_f32p]
    lib.dl4j_csv_close.argtypes = [ctypes.c_void_p]

    lib.dl4j_stats_begin.restype = ctypes.c_void_p
    lib.dl4j_stats_begin.argtypes = [
        ctypes.c_char_p, ctypes.c_char_p, c_i64, ctypes.c_int32,
        ctypes.c_double, ctypes.c_double, ctypes.c_double, c_i64, c_i64]
    lib.dl4j_stats_add.restype = ctypes.c_int
    lib.dl4j_stats_add.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.c_char_p, ctypes.c_double,
        ctypes.c_double, ctypes.c_double, c_i32p, ctypes.c_int]
    lib.dl4j_stats_finish.restype = c_i64
    lib.dl4j_stats_finish.argtypes = [ctypes.c_void_p, c_u8p, c_i64]
    lib.dl4j_stats_abort.argtypes = [ctypes.c_void_p]
    lib.dl4j_runtime_version.restype = ctypes.c_int

    lib.dl4j_vocab_count_file.restype = ctypes.c_void_p
    lib.dl4j_vocab_count_file.argtypes = [ctypes.c_char_p, ctypes.c_int,
                                          ctypes.c_int]
    lib.dl4j_vocab_num_words.restype = c_i64
    lib.dl4j_vocab_num_words.argtypes = [ctypes.c_void_p]
    lib.dl4j_vocab_total_tokens.restype = c_i64
    lib.dl4j_vocab_total_tokens.argtypes = [ctypes.c_void_p]
    lib.dl4j_vocab_entry.restype = c_i64
    lib.dl4j_vocab_entry.argtypes = [ctypes.c_void_p, c_i64, ctypes.c_char_p,
                                     c_i64]
    lib.dl4j_vocab_close.argtypes = [ctypes.c_void_p]

    lib.dl4j_ingest_decode.restype = c_i64
    lib.dl4j_ingest_decode.argtypes = [c_u8p, c_i64, ctypes.c_int, c_f32p,
                                       c_i64]
    lib.dl4j_ingest_create.restype = ctypes.c_void_p
    lib.dl4j_ingest_create.argtypes = [ctypes.c_int]
    lib.dl4j_ingest_submit.restype = ctypes.c_int
    lib.dl4j_ingest_submit.argtypes = [ctypes.c_void_p, c_u8p, c_i64,
                                       ctypes.c_int]
    lib.dl4j_ingest_next.restype = c_i64
    lib.dl4j_ingest_next.argtypes = [ctypes.c_void_p, c_f32p, c_i64]
    lib.dl4j_ingest_close.argtypes = [ctypes.c_void_p]


def get_runtime() -> Optional[ctypes.CDLL]:
    """Load (building if needed) the native runtime; None when unavailable.
    Set DL4J_TPU_DISABLE_NATIVE=1 to force the pure-Python paths."""
    global _lib, _load_attempted
    if os.environ.get("DL4J_TPU_DISABLE_NATIVE") == "1":
        return None
    with _lock:
        if _load_attempted:
            return _lib
        _load_attempted = True
        stale = (_LIB_PATH.exists() and _SRC_PATH.exists()
                 and _SRC_PATH.stat().st_mtime > _LIB_PATH.stat().st_mtime)
        # lint: blocking-under-lock-ok (one-time lazy native build; the module lock exists precisely to serialize first-use compilation)
        if (not _LIB_PATH.exists() or stale) and not _build():
            if not _LIB_PATH.exists():
                return None
        try:
            lib = ctypes.CDLL(str(_LIB_PATH))
            _declare(lib)
            if lib.dl4j_runtime_version() != 4:
                return None
            _lib = lib
        except (OSError, AttributeError):
            # AttributeError: a stale older-version .so whose rebuild failed
            # is missing current-version symbols — fall back to pure Python
            # rather than raising out of native_available()
            _lib = None
        return _lib


def native_available() -> bool:
    return get_runtime() is not None


# ---------------------------------------------------------------------------
# IDX
# ---------------------------------------------------------------------------

def read_idx(path: str) -> Optional[np.ndarray]:
    """Parse an IDX (MNIST-format) file with the native parser; None on any
    failure (missing lib, bad file)."""
    lib = get_runtime()
    if lib is None:
        return None
    h = lib.dl4j_idx_open(str(path).encode())
    if not h:
        return None
    try:
        ndim = lib.dl4j_idx_ndim(h)
        dims = np.zeros(ndim, np.int64)
        lib.dl4j_idx_dims(h, dims.ctypes.data_as(c_i64p))
        out = np.empty(int(dims.prod()), np.uint8)
        lib.dl4j_idx_read(h, out.ctypes.data_as(c_u8p))
        return out.reshape(dims.tolist())
    finally:
        lib.dl4j_idx_close(h)


# ---------------------------------------------------------------------------
# CSV
# ---------------------------------------------------------------------------

def read_csv_numeric(path: str, delimiter: str = ",", skip_lines: int = 0,
                     strict: bool = False) -> Optional[np.ndarray]:
    """Fast numeric CSV → float32 [rows, cols].

    ``strict=False``: non-numeric fields become 0 (lenient legacy behavior).
    ``strict=True``: one native pass validates WHILE parsing — returns None
    on any empty/non-numeric field or ragged row so the caller can fall back
    to its general string-preserving reader. Also None when the native
    runtime is unavailable or the file can't be read."""
    lib = get_runtime()
    if lib is None:
        return None
    h = lib.dl4j_csv_open2(str(path).encode(), delimiter.encode()[:1],
                           int(skip_lines), 1 if strict else 0)
    if not h:
        return None
    try:
        rows, cols = lib.dl4j_csv_rows(h), lib.dl4j_csv_cols(h)
        out = np.empty((int(rows), int(cols)), np.float32)
        if rows and cols:
            lib.dl4j_csv_read(h, out.ctypes.data_as(c_f32p))
        return out
    finally:
        lib.dl4j_csv_close(h)


# ---------------------------------------------------------------------------
# Async prefetch loader
# ---------------------------------------------------------------------------

class AsyncNativeLoader:
    """Native async batch loader: a C++ producer thread assembles normalized
    float32 batches (one-hot labels) into a bounded queue; iteration here
    blocks on the queue (reference AsyncDataSetIterator semantics: prefetch
    depth = ``capacity``, reset() reshuffles and restarts the epoch)."""

    def __init__(self, handle, lib):
        if not handle:
            raise ValueError("native loader creation failed")
        self._h = handle
        self._lib = lib
        self.batch = lib.dl4j_loader_batch_size(handle)
        self.feature_size = int(lib.dl4j_loader_feature_size(handle))
        self.num_classes = lib.dl4j_loader_num_classes(handle)
        self.num_examples = int(lib.dl4j_loader_num_examples(handle))

    @classmethod
    def from_arrays(cls, features: np.ndarray, labels: np.ndarray,
                    num_classes: int, batch: int, capacity: int = 4,
                    shuffle: bool = True, seed: int = 0,
                    normalize: bool = True) -> "AsyncNativeLoader":
        lib = get_runtime()
        if lib is None:
            raise RuntimeError("native runtime unavailable")
        f = np.ascontiguousarray(features, np.uint8).reshape(len(features), -1)
        l = np.ascontiguousarray(labels, np.uint8).ravel()
        h = lib.dl4j_loader_create_from_arrays(
            f.ctypes.data_as(c_u8p), l.ctypes.data_as(c_u8p),
            f.shape[0], f.shape[1], num_classes, batch, capacity,
            int(shuffle), seed, int(normalize))
        return cls(h, lib)

    @classmethod
    def mnist(cls, images_path: str, labels_path: str, batch: int,
              capacity: int = 4, shuffle: bool = True, seed: int = 0,
              normalize: bool = True) -> "AsyncNativeLoader":
        lib = get_runtime()
        if lib is None:
            raise RuntimeError("native runtime unavailable")
        h = lib.dl4j_mnist_loader_create(
            str(images_path).encode(), str(labels_path).encode(), batch,
            capacity, int(shuffle), seed, int(normalize))
        return cls(h, lib)

    @classmethod
    def cifar(cls, paths: Sequence[str], batch: int, capacity: int = 4,
              shuffle: bool = True, seed: int = 0) -> "AsyncNativeLoader":
        lib = get_runtime()
        if lib is None:
            raise RuntimeError("native runtime unavailable")
        arr = (ctypes.c_char_p * len(paths))(
            *[str(p).encode() for p in paths])
        h = lib.dl4j_cifar_loader_create(arr, len(paths), batch, capacity,
                                         int(shuffle), seed)
        return cls(h, lib)

    def next(self) -> Optional[tuple]:
        """Next (features [B, F] f32, one-hot labels [B, C] f32), or None at
        end of epoch."""
        if not self._h:
            raise ValueError("loader is closed")
        x = np.empty((self.batch, self.feature_size), np.float32)
        y = np.empty((self.batch, self.num_classes), np.float32)
        ok = self._lib.dl4j_loader_next(
            self._h, x.ctypes.data_as(c_f32p), y.ctypes.data_as(c_f32p))
        return (x, y) if ok else None

    def reset(self) -> None:
        if not self._h:
            raise ValueError("loader is closed")
        self._lib.dl4j_loader_reset(self._h)

    def __iter__(self):
        while True:
            b = self.next()
            if b is None:
                return
            yield b

    def close(self) -> None:
        if self._h:
            self._lib.dl4j_loader_close(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        # lint: swallowed-exception-ok (destructor must not raise during interpreter teardown)
        except Exception:
            pass


# ---------------------------------------------------------------------------
# Stats codec
# ---------------------------------------------------------------------------

def encode_stats_native(session_id: str, worker_id: str, timestamp: int,
                        iteration: int, score: float, iter_time_ms: float,
                        samples_per_sec: float, mem_rss: int, device_mem: int,
                        sections: List[dict]) -> Optional[bytes]:
    """Encode a StatsReport with the native codec (same DLTS wire format as
    the Python encoder in ui/stats.py). ``sections`` is
    [params, gradients, updates], each name -> (mean_mag, hist, (lo, hi))."""
    lib = get_runtime()
    if lib is None:
        return None
    h = lib.dl4j_stats_begin(session_id.encode(), worker_id.encode(),
                             timestamp, iteration, score, iter_time_ms,
                             samples_per_sec, mem_rss, device_mem)
    if not h:
        return None
    try:
        for si, section in enumerate(sections[:3]):
            for name, (mm, hist, (lo, hi)) in section.items():
                ha = np.asarray(hist, np.int32)
                lib.dl4j_stats_add(h, si, name.encode(), float(mm), float(lo),
                                   float(hi), ha.ctypes.data_as(c_i32p),
                                   len(ha))
        n = lib.dl4j_stats_finish(h, None, 0)
        out = np.empty(int(n), np.uint8)
        written = lib.dl4j_stats_finish(h, out.ctypes.data_as(c_u8p), n)
        h = None  # finish with a large-enough buffer frees the builder
        if written != n:
            return None
        return out.tobytes()
    finally:
        if h:
            lib.dl4j_stats_abort(h)


# ---------------------------------------------------------------------------
# Batched ingest decode (zero-copy host data plane): raw record bytes -> f32.
# ctypes releases the GIL for the whole native call, and IngestDecoder adds a
# C++ producer thread so decode overlaps the training step (the
# AsyncDataSetIterator role, on the consume side of the broker).
# ---------------------------------------------------------------------------

#: codec ids shared with dl4j_runtime.cpp (kIngestF32/Bf16/U8)
INGEST_CODECS = {"f32": 0, "none": 0, "bf16": 1, "u8": 2}

#: floats produced per input byte, by codec id
_INGEST_WIDTH = {0: 4, 1: 2, 2: 1}  # bytes per element


def _ingest_counter():
    from deeplearning4j_tpu.observability.metrics import global_registry
    from deeplearning4j_tpu.observability.names import (
        INGEST_DECODE_BYTES_TOTAL)
    return global_registry().counter(
        INGEST_DECODE_BYTES_TOTAL,
        "raw record bytes decoded to f32 batches, by path (native/python)")


def decode_records_py(buf, codec: str = "f32") -> np.ndarray:
    """Pure-Python fallback decoder (also the bench baseline): one record's
    bytes -> f32 vector."""
    cid = INGEST_CODECS[codec]
    _ingest_counter().labels(path="python").inc(len(buf))
    if cid == 0:
        return np.frombuffer(buf, np.float32).copy()  # lint: hot-path-copy-ok (fallback path by definition; native is the hot path)
    if cid == 1:
        import ml_dtypes
        return np.frombuffer(buf, ml_dtypes.bfloat16).astype(np.float32)
    # multiply by the f32 reciprocal, exactly like the native decoder (and
    # the native Loader's normalize path) — bitwise parity across paths
    return (np.frombuffer(buf, np.uint8).astype(np.float32)
            * np.float32(1.0 / 255.0))


def decode_records(buf, codec: str = "f32") -> Optional[np.ndarray]:
    """One-shot native decode of a record's bytes; None when the native
    runtime is unavailable or the length is ragged for the codec (callers
    fall back to ``decode_records_py``)."""
    lib = get_runtime()
    if lib is None:
        return None
    cid = INGEST_CODECS[codec]
    raw = np.frombuffer(buf, np.uint8)  # lint: hot-path-copy-ok (view, no .copy(): zero-copy reinterpret of the input bytes)
    n = len(raw) // _INGEST_WIDTH[cid]
    out = np.empty(n, np.float32)
    wrote = lib.dl4j_ingest_decode(
        raw.ctypes.data_as(c_u8p), len(raw), cid,
        out.ctypes.data_as(c_f32p), n)
    if wrote != n:
        return None
    _ingest_counter().labels(path="native").inc(len(raw))
    return out


class IngestDecoder:
    """Pipelined native decoder: ``submit()`` stages raw record bytes into a
    bounded native queue, a C++ worker thread decodes them to f32, ``next()``
    collects finished records in submission order.

    The staging queue is BOUNDED: ``submit()`` blocks once ``capacity``
    records are in flight, so interleave submits with ``next()`` when
    streaming more than ``capacity`` records (the producer/consumer shape
    DevicePrefetcher already has). Raises RuntimeError at construction when
    the native runtime is unavailable — callers that want graceful
    degradation use ``decode_records``/``decode_records_py``."""

    def __init__(self, capacity: int = 8):
        lib = get_runtime()
        if lib is None:
            raise RuntimeError("native runtime unavailable")
        self._lib = lib
        self._h = lib.dl4j_ingest_create(int(capacity))
        if not self._h:
            raise RuntimeError("native ingest creation failed")
        self._sizes: List[int] = []  # FIFO of expected output lengths

    def submit(self, buf, codec: str = "f32") -> None:
        if not self._h:
            raise ValueError("decoder is closed")
        cid = INGEST_CODECS[codec]
        raw = np.frombuffer(buf, np.uint8)  # lint: hot-path-copy-ok (view, no .copy(): the native side stages its own copy off-GIL)
        if len(raw) % _INGEST_WIDTH[cid]:
            raise ValueError(f"ragged record: {len(raw)} bytes is not a "
                             f"whole number of {codec} elements")
        rc = self._lib.dl4j_ingest_submit(
            self._h, raw.ctypes.data_as(c_u8p), len(raw), cid)
        if rc != 0:
            raise RuntimeError("ingest pipeline poisoned by a bad record")
        self._sizes.append(len(raw) // _INGEST_WIDTH[cid])
        _ingest_counter().labels(path="native").inc(len(raw))

    def next(self) -> Optional[np.ndarray]:
        """Next decoded f32 record (submission order), or None when every
        submitted record has been collected."""
        if not self._h:
            raise ValueError("decoder is closed")
        if not self._sizes:
            return None
        n = self._sizes.pop(0)
        out = np.empty(n, np.float32)
        wrote = self._lib.dl4j_ingest_next(
            self._h, out.ctypes.data_as(c_f32p), n)
        if wrote != n:
            raise RuntimeError(f"ingest decode returned {wrote}, "
                               f"expected {n}")
        return out

    def close(self) -> None:
        if self._h:
            self._lib.dl4j_ingest_close(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        # lint: swallowed-exception-ok (destructor must not raise during interpreter teardown)
        except Exception:
            pass


# ---------------------------------------------------------------------------
# Vocabulary counting (parallel token counts, reference VocabConstructor.java)
# ---------------------------------------------------------------------------

def count_tokens_file(path: str, common_preprocess: bool = False,
                      nthreads: int = 0) -> Optional[List[tuple]]:
    """Count whitespace tokens in an ASCII text file with worker threads.

    Returns [(word, count), ...] ordered by count desc then word asc, or
    None when the native runtime is unavailable, the file can't be read, or
    it contains non-ASCII bytes (the Python tokenizer pipeline has unicode
    semantics this fast path intentionally does not replicate).
    ``common_preprocess`` applies the CommonPreprocessor rules (strip
    punctuation/digits, lowercase) inline during the scan.
    """
    lib = get_runtime()
    if lib is None:
        return None
    h = lib.dl4j_vocab_count_file(path.encode(), 1 if common_preprocess else 0,
                                  int(nthreads))
    if not h:
        return None
    try:
        n = lib.dl4j_vocab_num_words(h)
        cap = 65536
        buf = ctypes.create_string_buffer(cap)
        out = []
        for i in range(int(n)):
            cnt = lib.dl4j_vocab_entry(h, i, buf, cap)
            if cnt < 0:
                return None
            word = buf.value.decode("ascii")
            if len(word) >= cap - 1:
                # possible truncation (undetectable through the C ABI):
                # decline and let the Python pipeline keep the full token
                return None
            out.append((word, int(cnt)))
        return out
    finally:
        lib.dl4j_vocab_close(h)
