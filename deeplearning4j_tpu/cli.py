"""Command-line entry points.

Reference mains (SURVEY.md §2.4/§2.9): ParallelWrapperMain (deeplearning4j-
scaleout cli main/ParallelWrapperMain.java — jcommander-parsed flags driving
ParallelWrapper training of a serialized model) and PlayUIServer's main
(ui/play/PlayUIServer.java --uiPort). Run as:

    python -m deeplearning4j_tpu.cli ui --port 9000
    python -m deeplearning4j_tpu.cli parallel-train --model m.zip \
        --workers 4 --averaging-frequency 1 --epochs 1 [--dataset mnist]
    python -m deeplearning4j_tpu.cli elastic-train --model m.zip \
        --workers 4 --lease-timeout 15 --checkpoint-dir ckpt/
    python -m deeplearning4j_tpu.cli keras-server --port 25333
    python -m deeplearning4j_tpu.cli serve --model m.zip \
        --replicas 4 --sharding dp_tp --port 8080
"""
from __future__ import annotations

import argparse
import sys
import time


def _cmd_ui(args) -> int:
    from deeplearning4j_tpu.ui.server import UIServer

    server = UIServer.get_instance(args.port)
    if args.enable_remote:
        server.enable_remote_listener()
    print(f"UI server listening on http://127.0.0.1:{server.port}")
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        return 0


def _cmd_parallel_train(args) -> int:
    from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper
    from deeplearning4j_tpu.utils.model_serializer import (
        guess_model, write_model,
    )

    net = guess_model(args.model)
    if args.flight_recorder_dir:
        from deeplearning4j_tpu.observability import (
            global_recorder, install_signal_handlers,
        )
        global_recorder().set_dump_dir(args.flight_recorder_dir)
        install_signal_handlers()
        print(f"flight recorder armed: bundles -> {args.flight_recorder_dir} "
              "(SIGTERM/SIGUSR1 dump)")
    if args.dataset == "mnist":
        from deeplearning4j_tpu.datasets.mnist import MnistDataSetIterator
        it = MnistDataSetIterator(args.batch, train=True,
                                  num_examples=args.num_examples)
    elif args.dataset == "cifar":
        from deeplearning4j_tpu.datasets.fetchers import CifarDataSetIterator
        it = CifarDataSetIterator(args.batch, train=True, flatten=False,
                                  num_examples=args.num_examples)
    else:
        from deeplearning4j_tpu.datavec import (
            CSVRecordReader, RecordReaderDataSetIterator,
        )
        if args.num_classes is None and not args.regression:
            print("error: CSV training needs --num-classes (classification) "
                  "or --regression", file=sys.stderr)
            return 2
        it = RecordReaderDataSetIterator(
            CSVRecordReader(args.dataset), args.batch,
            label_index=args.label_index, num_classes=args.num_classes,
            regression=args.regression)
    if args.pipeline:
        from deeplearning4j_tpu.parallel.mesh import build_mesh
        from deeplearning4j_tpu.parallel.pipeline_trainer import (
            PipelineTrainer)
        import jax

        stages = args.workers or len(jax.devices())
        PipelineTrainer(net, mesh=build_mesh({"stage": stages}),
                        n_microbatches=args.microbatches) \
            .fit(it, epochs=args.epochs)
    else:
        builder = (ParallelWrapper.builder(net)
                   .workers(args.workers)
                   .averaging_frequency(args.averaging_frequency)
                   .prefetch_buffer(args.prefetch))
        if args.sequence_parallel:
            from deeplearning4j_tpu.parallel.mesh import build_mesh
            import jax

            n = len(jax.devices())
            sp = args.sequence_parallel
            builder = (builder.mesh(build_mesh({"data": n // sp, "sp": sp}))
                       .sequence_parallel("sp", mode=args.sp_mode))
        if args.expert_parallel:
            builder = builder.expert_parallel("data")
        wrapper = builder.build()
        wrapper.fit(it, epochs=args.epochs)
    if args.output:
        write_model(net, args.output)
        print(f"trained model written to {args.output}")
    if args.telemetry_out:
        from deeplearning4j_tpu.observability import (global_registry,
                                                      global_tracker)
        global_registry().write_jsonl(
            args.telemetry_out, source="cli.parallel-train",
            compile_events=global_tracker().snapshot_events())
        print(f"telemetry snapshot appended to {args.telemetry_out}")
    print(f"final score: {net.score_value}")
    return 0


def _cmd_elastic_train(args) -> int:
    from deeplearning4j_tpu.parallel.elastic import ElasticTrainer
    from deeplearning4j_tpu.utils.model_serializer import (
        guess_model, write_model,
    )

    net = guess_model(args.model)
    if args.dataset == "mnist":
        from deeplearning4j_tpu.datasets.mnist import MnistDataSetIterator
        it = MnistDataSetIterator(args.batch, train=True,
                                  num_examples=args.num_examples)
    else:
        from deeplearning4j_tpu.datasets.fetchers import CifarDataSetIterator
        it = CifarDataSetIterator(args.batch, train=True, flatten=False,
                                  num_examples=args.num_examples)
    builder = (ElasticTrainer.builder(net)
               .workers(args.workers)
               .push_frequency(args.push_frequency)
               .staleness(args.staleness)
               .compression(args.compression)
               .lease_timeout(args.lease_timeout)
               .respawn(not args.no_respawn))
    if args.checkpoint_dir:
        builder = builder.checkpoint(args.checkpoint_dir,
                                     interval_s=args.checkpoint_interval)
    trainer = builder.build()
    trainer.fit(it, epochs=args.epochs)
    stats = trainer.stats
    print(f"elastic fit done: {stats['steps']} steps over "
          f"{stats['joins']} worker joins, {stats['handoffs']} handoffs, "
          f"{stats['fenced']} fenced pushes"
          + (" (warm-started from checkpoint)" if stats["restored"] else ""))
    if args.output:
        write_model(net, args.output)
        print(f"trained model written to {args.output}")
    print(f"final score: {net.score_value}")
    return 0


def _cmd_keras_server(args) -> int:
    from deeplearning4j_tpu.keras_server import Server

    srv = Server(port=args.port).start()
    print(f"keras gateway listening on 127.0.0.1:{srv.port}")
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        srv.stop()
        return 0


def _cmd_serve(args) -> int:
    from deeplearning4j_tpu.keras_server import InferenceServer
    from deeplearning4j_tpu.observability import tracing

    tracing.configure(enabled=not args.no_tracing,
                      sample=args.trace_sample,
                      base_dir=args.trace_dir)
    srv = InferenceServer(
        host=args.host, port=args.port, replicas=args.replicas,
        sharding=args.sharding, max_batch=args.max_batch,
        max_latency_s=args.max_latency_ms / 1e3, max_queue=args.max_queue,
        warmup=args.warmup, autoscale=args.autoscale,
        min_replicas=args.min_replicas, max_replicas=args.max_replicas,
        autoscale_cooldown_s=args.autoscale_cooldown_s)
    if srv.replica_set is not None:
        srv.replica_set.load(args.name, args.model, quant=args.quant)
    else:
        srv.registry.load(args.name, args.model, quant=args.quant)
    srv.start()
    mode = (f"{args.replicas} replica(s)"
            + (f", {args.sharding}-sharded" if args.sharding else "")
            + (f", autoscaled [{args.min_replicas or 1}.."
               f"{args.max_replicas or max(args.replicas, 8)}]"
               if args.autoscale else ""))
    trace = ("off" if args.no_tracing
             else f"on, sample={args.trace_sample:g}")
    print(f"inference server listening on http://{args.host}:{srv.port} "
          f"({mode}; POST /v1/predict, GET /serve/status; "
          f"tracing {trace} — GET /serve/traces, /serve/slo)")
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        srv.stop()
        return 0


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="deeplearning4j_tpu")
    sub = p.add_subparsers(dest="cmd", required=True)

    ui = sub.add_parser("ui", help="start the training UI server")
    ui.add_argument("--port", type=int, default=9000)
    ui.add_argument("--enable-remote", action="store_true",
                    help="accept POSTed remote stats")
    ui.set_defaults(fn=_cmd_ui)

    tr = sub.add_parser("parallel-train",
                        help="data-parallel training of a serialized model")
    tr.add_argument("--model", required=True, help="model zip path")
    tr.add_argument("--dataset", default="mnist",
                    help="mnist | cifar | path to CSV")
    tr.add_argument("--workers", type=int, default=None)
    tr.add_argument("--averaging-frequency", type=int, default=1)
    tr.add_argument("--prefetch", type=int, default=2)
    tr.add_argument("--batch", type=int, default=128)
    tr.add_argument("--epochs", type=int, default=1)
    tr.add_argument("--num-examples", type=int, default=None)
    tr.add_argument("--label-index", type=int, default=-1)
    tr.add_argument("--num-classes", type=int, default=None)
    tr.add_argument("--regression", action="store_true")
    tr.add_argument("--output", help="write trained model zip here")
    tr.add_argument("--sequence-parallel", type=int, default=0, metavar="N",
                    help="shard the sequence axis over N devices "
                         "(Ulysses/ring attention; transformer configs)")
    tr.add_argument("--sp-mode", choices=("ulysses", "ring"),
                    default="ulysses")
    tr.add_argument("--expert-parallel", action="store_true",
                    help="GShard all_to_all MoE dispatch over the data axis")
    tr.add_argument("--pipeline", action="store_true",
                    help="GPipe pipeline over the model's homogeneous "
                         "block stack (stages = --workers or all devices)")
    tr.add_argument("--microbatches", type=int, default=4)
    tr.add_argument("--flight-recorder-dir", default=None, metavar="DIR",
                    help="arm the flight recorder: crash/signal/health-alarm "
                         "bundles are written under DIR; SIGTERM and SIGUSR1 "
                         "dump handlers are installed")
    tr.add_argument("--telemetry-out", default=None, metavar="PATH",
                    help="append a metrics-registry snapshot (JSONL, incl. "
                         "compile events) to PATH after training")
    tr.set_defaults(fn=_cmd_parallel_train)

    el = sub.add_parser(
        "elastic-train",
        help="preemption-tolerant async-PS training: leased worker "
             "membership, broker shard handoff, checkpoint warm start")
    el.add_argument("--model", required=True, help="model zip path")
    el.add_argument("--dataset", default="mnist", help="mnist | cifar")
    el.add_argument("--workers", type=int, default=4)
    el.add_argument("--push-frequency", type=int, default=4)
    el.add_argument("--staleness", type=int, default=8)
    el.add_argument("--compression", default="none",
                    choices=("none", "bf16"))
    el.add_argument("--batch", type=int, default=128)
    el.add_argument("--epochs", type=int, default=1)
    el.add_argument("--num-examples", type=int, default=None)
    el.add_argument("--lease-timeout", type=float, default=15.0,
                    help="seconds of heartbeat silence before a worker is "
                         "declared dead and its shard handed off")
    el.add_argument("--no-respawn", action="store_true",
                    help="fail instead of replacing a dead worker")
    el.add_argument("--checkpoint-dir", default=None,
                    help="async sharded checkpoints; a committed one warm-"
                         "starts the PS on restart")
    el.add_argument("--checkpoint-interval", type=float, default=30.0)
    el.add_argument("--output", help="write trained model zip here")
    el.set_defaults(fn=_cmd_elastic_train)

    ks = sub.add_parser("keras-server", help="start the Keras gateway")
    ks.add_argument("--port", type=int, default=25333)
    ks.set_defaults(fn=_cmd_keras_server)

    sv = sub.add_parser(
        "serve", help="serve a model over HTTP (micro-batched /v1/predict; "
                      "optionally N replicas and/or sharded pins)")
    sv.add_argument("--model", required=True,
                    help="model file: model_serializer zip or Keras HDF5")
    sv.add_argument("--name", default="default",
                    help="model name requests address (default: 'default')")
    sv.add_argument("--host", default="127.0.0.1")
    sv.add_argument("--port", type=int, default=8080)
    sv.add_argument("--replicas", type=int, default=1,
                    help="independent pinned programs behind the least-"
                         "queue-depth router (one device each)")
    sv.add_argument("--sharding", default=None,
                    choices=("dp", "dp_tp", "zero3"),
                    help="partition-rule set for each replica's pinned "
                         "params (its own mesh slice; gather-at-use, "
                         "bitwise-equal to single-device)")
    sv.add_argument("--quant", default=None, choices=("int8",),
                    help="int8 serving DtypePolicy for the pinned weights")
    sv.add_argument("--max-batch", type=int, default=32)
    sv.add_argument("--max-latency-ms", type=float, default=2.0,
                    help="micro-batcher fill-or-deadline coalescing wait")
    sv.add_argument("--max-queue", type=int, default=256,
                    help="admission limit per replica (429 past it)")
    sv.add_argument("--warmup", action="store_true",
                    help="pre-build every micro-batch bucket program up to "
                         "--max-batch (parallel, executable-cache-backed) "
                         "before the model goes active, so the first real "
                         "request never pays an XLA compile")
    sv.add_argument("--autoscale", action="store_true",
                    help="SLO-driven fleet sizing: a control loop grows/"
                         "shrinks --replicas between --min-replicas and "
                         "--max-replicas from error-budget burn and queue "
                         "pressure (warm scale-out, drain-without-loss "
                         "scale-in, lease-fenced membership)")
    sv.add_argument("--min-replicas", type=int, default=None,
                    help="autoscaler floor (default: 1)")
    sv.add_argument("--max-replicas", type=int, default=None,
                    help="autoscaler ceiling (default: max(--replicas, 8))")
    sv.add_argument("--autoscale-cooldown-s", type=float, default=30.0,
                    help="minimum seconds between scale events (hysteresis)")
    sv.add_argument("--no-tracing", action="store_true",
                    help="disable request tracing (spans become process-"
                         "wide no-ops; /serve/traces serves empty)")
    sv.add_argument("--trace-sample", type=float, default=1.0,
                    help="tail-sampling keep probability for ORDINARY "
                         "traces; errors/429s/p99-exceeders always keep")
    sv.add_argument("--trace-dir", default=None,
                    help="persist kept traces (traces.jsonl + "
                         "trace_index.db) under this directory")
    sv.set_defaults(fn=_cmd_serve)
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
