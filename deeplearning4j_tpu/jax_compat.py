"""Version bridge for the handful of JAX APIs that moved between releases.

The package targets the current JAX surface (``jax.shard_map`` with
``check_vma``, ``jax.typeof(...).vma``, ``jax.enable_x64``); deployment
images sometimes pin an older jaxlib where those live under
``jax.experimental`` with earlier names (``check_rep``).  Everything in the
repo imports the moved names from here so the skew stays in one file.

Beyond renames, this module is the ONE place that knows whether a trace is
inside a shard_map body and whether that body is varying-mesh-axes checked:
new JAX exposes it as ``jax.typeof(x).vma``; old JAX has no aval-level
signal, so our ``shard_map`` wrapper brackets the body with a contextvar.
``ops/pallas_kernels.py`` dispatch predicates consume the merged answer via
:func:`in_checked_shard_map` — pallas_call is rejected by the vma/rep
checker, so kernels must yield to XLA math exactly when that returns True.
"""
from __future__ import annotations

import contextvars
import functools

import jax

# innermost shard_map body's guard state: None = not in a shard_map body,
# True/False = the body's check_vma (new) / check_rep (old) setting
_SHARD_MAP_GUARD: contextvars.ContextVar = contextvars.ContextVar(
    "dl4j_shard_map_guard", default=None)

_NEW_SHARD_MAP = hasattr(jax, "shard_map")
if not _NEW_SHARD_MAP:  # pragma: no cover - exercised on old-jax images
    from jax.experimental.shard_map import shard_map as _old_shard_map


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
    """``jax.shard_map`` with the body bracketed by the guard contextvar.

    ``check_vma`` follows the current JAX name; on older releases it is
    forwarded as ``check_rep`` (same semantics for our purposes: both
    reject pallas_call inside a guarded body).
    """
    @functools.wraps(f)
    def bracketed(*args, **kwargs):
        token = _SHARD_MAP_GUARD.set(check_vma)
        try:
            return f(*args, **kwargs)
        finally:
            _SHARD_MAP_GUARD.reset(token)

    if _NEW_SHARD_MAP:
        return jax.shard_map(bracketed, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)
    return _old_shard_map(bracketed, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_rep=check_vma)


def in_checked_shard_map(x) -> bool:
    """True when ``x`` is being traced inside a vma/rep-CHECKED shard_map
    body — the contexts where the checker rejects ``pallas_call`` and
    kernels must fall back to XLA math.  Bodies built with
    ``check_vma=False`` (ulysses/ring attention) return False: the kernel
    may engage there.

    New JAX answers from the aval (``jax.typeof(x).vma`` is non-empty only
    under a checked shard_map); old JAX answers from the contextvar set by
    this module's :func:`shard_map` wrapper.
    """
    typeof = getattr(jax, "typeof", None)
    if typeof is not None:
        try:
            if bool(getattr(typeof(x), "vma", None)):
                return True
        # lint: swallowed-exception-ok (typeof/vma probe across JAX versions; absence means not varying)
        except Exception:
            pass
    return _SHARD_MAP_GUARD.get() is True


def pcast(x, axes, to: str = "varying"):
    """``jax.lax.pcast`` (new JAX: adjusts an aval's varying-mesh-axes set,
    e.g. marking loop-carry accumulators device-varying so carry types line
    up under a checked shard_map).  Older releases have no vma aval axis at
    all — their ``check_rep`` tracker infers replication from data flow — so
    the cast is an identity there."""
    fn = getattr(jax.lax, "pcast", None)
    if fn is not None:
        return fn(x, axes, to=to)
    return x


def enable_x64(enabled: bool = True):
    """``jax.enable_x64`` (new) / ``jax.experimental.enable_x64`` (old)."""
    ctx = getattr(jax, "enable_x64", None)
    if ctx is not None:
        return ctx(enabled)
    from jax.experimental import enable_x64 as _x64
    return _x64(enabled)
