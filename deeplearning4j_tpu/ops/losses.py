"""Loss functions.

Parity with the reference's ``ILossFunction`` family (ND4J org.nd4j.linalg.lossfunctions,
used by output-layer configs, reference nn/conf/layers/OutputLayer.java). Each loss is a
pure function ``loss(labels, preout, activation, mask) -> scalar mean score`` where
``preout`` is the pre-activation output of the final layer; applying the activation inside
the loss lets us use numerically-stable fused forms (softmax+CE, sigmoid+BCE) — the
TPU-native equivalent of the reference's computeGradient analytic pairings.

Per-example scores (for masking and per-output weighting) are computed then mean-reduced
over batch; mask arrays broadcast over the output dim (reference BaseEvaluation masking).
"""
from __future__ import annotations

import os
from typing import Callable, Optional

import jax
import jax.numpy as jnp

Array = jax.Array


def _reduce(per_example: Array, mask: Optional[Array]) -> Array:
    """Mean over examples (per_example has trailing dim 1), honoring an optional
    {0,1} mask over the leading (batch[, time]) dims."""
    if mask is None:
        return jnp.mean(per_example)
    mask = mask.astype(per_example.dtype)
    while mask.ndim < per_example.ndim:
        mask = mask[..., None]
    return jnp.sum(per_example * mask) / jnp.maximum(jnp.sum(mask), 1.0)


def mse(labels: Array, preout: Array, activation, mask=None) -> Array:
    out = activation(preout)
    per = jnp.sum((labels - out) ** 2, axis=-1, keepdims=True) / labels.shape[-1]
    return _reduce(per, mask)


def l2(labels: Array, preout: Array, activation, mask=None) -> Array:
    out = activation(preout)
    per = jnp.sum((labels - out) ** 2, axis=-1, keepdims=True)
    return _reduce(per, mask)


def mae(labels: Array, preout: Array, activation, mask=None) -> Array:
    out = activation(preout)
    per = jnp.sum(jnp.abs(labels - out), axis=-1, keepdims=True) / labels.shape[-1]
    return _reduce(per, mask)


def l1(labels: Array, preout: Array, activation, mask=None) -> Array:
    out = activation(preout)
    per = jnp.sum(jnp.abs(labels - out), axis=-1, keepdims=True)
    return _reduce(per, mask)


def mape(labels: Array, preout: Array, activation, mask=None) -> Array:
    out = activation(preout)
    per = 100.0 * jnp.sum(jnp.abs((labels - out) / jnp.where(labels == 0, 1e-8, labels)),
                          axis=-1, keepdims=True) / labels.shape[-1]
    return _reduce(per, mask)


def msle(labels: Array, preout: Array, activation, mask=None) -> Array:
    out = activation(preout)
    per = jnp.sum((jnp.log1p(jnp.maximum(labels, 0)) - jnp.log1p(jnp.maximum(out, -0.999999))) ** 2,
                  axis=-1, keepdims=True) / labels.shape[-1]
    return _reduce(per, mask)


def _is_softmax(activation) -> bool:
    return getattr(activation, "__name__", "") in ("softmax", "logsoftmax")


def _is_sigmoid(activation) -> bool:
    return getattr(activation, "__name__", "") == "sigmoid"


@jax.custom_vjp
def _fused_sm_xent_per(labels: Array, preout: Array) -> Array:
    """Per-row softmax cross entropy through the Pallas fused kernel: the
    forward computes loss AND dlogits in ONE pass over the logits
    (ops/pallas_kernels.softmax_cross_entropy), and the backward replays the
    saved gradient instead of re-deriving softmax from a stored log-softmax
    — the cuDNN softmax-loss pairing, TPU form. labels/preout: (N, C);
    returns (N, 1) f32."""
    from deeplearning4j_tpu.ops.pallas_kernels import softmax_cross_entropy

    loss, _ = softmax_cross_entropy(preout, labels,
                                    interpret=_xent_interpret())
    return loss[:, None]


def _xent_interpret() -> bool:
    # pallas interpret mode off-TPU (tests exercise the kernel body on CPU)
    return jax.default_backend() not in ("tpu",)


def _fused_sm_xent_fwd(labels, preout):
    from deeplearning4j_tpu.ops.pallas_kernels import softmax_cross_entropy

    loss, grad = softmax_cross_entropy(preout, labels,
                                       interpret=_xent_interpret())
    return loss[:, None], grad


def _fused_sm_xent_bwd(grad, ct):
    # labels are data in LossMCXENT (reference semantics) — zero cotangent;
    # dpreout = ct * (softmax - labels), saved from the forward pass
    d = ct.astype(jnp.float32) * grad.astype(jnp.float32)
    return jnp.zeros_like(grad), d.astype(grad.dtype)


_fused_sm_xent_per.defvjp(_fused_sm_xent_fwd, _fused_sm_xent_bwd)


def _fused_xent_engaged(preout: Array) -> bool:
    """DL4J_FUSED_XENT=0 disables, =1 forces (interpret mode off-TPU); unset
    -> engaged exactly when the other pallas kernels are (use_pallas()).
    Read at call time like every other kill switch in the tree."""
    env = os.environ.get("DL4J_FUSED_XENT")
    if env == "0":
        return False
    if preout.dtype not in (jnp.float32, jnp.bfloat16):
        return False  # f64 gradient checks stay on the exact autodiff path
    if env == "1":
        return True
    from deeplearning4j_tpu.ops.pallas_kernels import use_pallas

    return use_pallas()


def mcxent(labels: Array, preout: Array, activation, mask=None) -> Array:
    """Multi-class cross entropy (reference LossMCXENT). Fused log-softmax when the
    output activation is softmax (the common OutputLayer pairing); on TPU the
    per-row loss+gradient ride the fused Pallas kernel via custom_vjp."""
    if _is_softmax(activation):
        if _fused_xent_engaged(preout):
            C = preout.shape[-1]
            # labels cast to the logits dtype BEFORE the custom_vjp call:
            # bwd's zero labels-cotangent must match the primal aval (int
            # one-hot labels would otherwise crash jax.grad)
            per = _fused_sm_xent_per(
                labels.reshape(-1, C).astype(preout.dtype),
                preout.reshape(-1, C))
            per = per.reshape(preout.shape[:-1] + (1,)).astype(preout.dtype)
            return _reduce(per, mask)
        logp = jax.nn.log_softmax(preout, axis=-1)
    else:
        out = activation(preout)
        logp = jnp.log(jnp.clip(out, 1e-10, 1.0))
    per = -jnp.sum(labels * logp, axis=-1, keepdims=True)
    return _reduce(per, mask)


def negativeloglikelihood(labels, preout, activation, mask=None) -> Array:
    return mcxent(labels, preout, activation, mask)


def xent(labels: Array, preout: Array, activation, mask=None) -> Array:
    """Binary cross entropy (reference LossBinaryXENT). Fused stable form for sigmoid."""
    if _is_sigmoid(activation):
        # log(sigmoid(x)) = -softplus(-x); log(1-sigmoid(x)) = -softplus(x)
        per = jnp.sum(labels * jax.nn.softplus(-preout) + (1 - labels) * jax.nn.softplus(preout),
                      axis=-1, keepdims=True)
    else:
        out = jnp.clip(activation(preout), 1e-10, 1 - 1e-10)
        per = -jnp.sum(labels * jnp.log(out) + (1 - labels) * jnp.log(1 - out),
                       axis=-1, keepdims=True)
    return _reduce(per, mask)


def hinge(labels: Array, preout: Array, activation, mask=None) -> Array:
    out = activation(preout)
    # labels in {-1, +1} or {0, 1} (mapped)
    y = jnp.where(labels <= 0, -1.0, 1.0)
    per = jnp.sum(jnp.maximum(0.0, 1.0 - y * out), axis=-1, keepdims=True)
    return _reduce(per, mask)


def squared_hinge(labels: Array, preout: Array, activation, mask=None) -> Array:
    out = activation(preout)
    y = jnp.where(labels <= 0, -1.0, 1.0)
    per = jnp.sum(jnp.maximum(0.0, 1.0 - y * out) ** 2, axis=-1, keepdims=True)
    return _reduce(per, mask)


def kl_divergence(labels: Array, preout: Array, activation, mask=None) -> Array:
    out = jnp.clip(activation(preout), 1e-10, 1.0)
    lbl = jnp.clip(labels, 1e-10, 1.0)
    per = jnp.sum(lbl * (jnp.log(lbl) - jnp.log(out)), axis=-1, keepdims=True)
    return _reduce(per, mask)


def poisson(labels: Array, preout: Array, activation, mask=None) -> Array:
    out = jnp.maximum(activation(preout), 1e-10)
    per = jnp.sum(out - labels * jnp.log(out), axis=-1, keepdims=True)
    return _reduce(per, mask)


def cosine_proximity(labels: Array, preout: Array, activation, mask=None) -> Array:
    out = activation(preout)
    ln = jnp.linalg.norm(labels, axis=-1, keepdims=True)
    on = jnp.linalg.norm(out, axis=-1, keepdims=True)
    per = -jnp.sum(labels * out, axis=-1, keepdims=True) / jnp.maximum(ln * on, 1e-10)
    return _reduce(per, mask)


LOSSES: dict[str, Callable] = {
    "mse": mse,
    "l2": l2,
    "mae": mae,
    "l1": l1,
    "mape": mape,
    "msle": msle,
    "mcxent": mcxent,
    "negativeloglikelihood": negativeloglikelihood,
    "xent": xent,
    "hinge": hinge,
    "squared_hinge": squared_hinge,
    "squaredhinge": squared_hinge,
    "kl_divergence": kl_divergence,
    "kld": kl_divergence,
    "reconstruction_crossentropy": xent,
    "poisson": poisson,
    "cosine_proximity": cosine_proximity,
}


def _f32_entry(fn: Callable) -> Callable:
    """Losses compute in at least float32. Under the full-bf16 activation
    policy the network hands the output layer bfloat16 pre-activations;
    log/exp/div in the loss are where reduced precision actually hurts (and
    the upcast is one elementwise op on (B, C) logits — free next to the
    savings upstream). Never downcasts: the float64 gradient-check path
    (nn/gradientcheck.py) flows through unchanged."""
    from deeplearning4j_tpu.common import at_least_f32

    def _upcast(a: Array) -> Array:
        if jnp.issubdtype(a.dtype, jnp.floating):
            return a.astype(at_least_f32(a.dtype))
        return a

    def wrapped(labels, preout, activation, mask=None):
        return fn(_upcast(jnp.asarray(labels)),
                  _upcast(jnp.asarray(preout)), activation, mask)
    return wrapped


def get_loss(name) -> Callable:
    if callable(name):
        return _f32_entry(name)
    key = str(name).lower()
    if key not in LOSSES:
        raise ValueError(f"Unknown loss '{name}'. Known: {sorted(LOSSES)}")
    return _f32_entry(LOSSES[key])
