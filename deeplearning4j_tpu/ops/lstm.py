"""Three-variant recurrent engine: fused scan, Pallas persistent cell, scan.

The reference accelerates recurrence through a reflection-loaded helper seam
(LSTMHelpers.java activateHelper/backpropGradientHelper; CudnnLSTMHelper takes
over fwd/bwd when present). The TPU-native equivalent lives here, one module,
three implementations of the same cell math, selected by a calibrated dispatch
gate at trace time (the round-5 ``DL4J_FLASH_MIN_SEQ`` pattern):

* **fused** (variant A, the default): one ``[B, F+H] x [F+H, 4H]`` MXU
  contraction per step — input and recurrent weights concatenated so the scan
  body issues a single matmul instead of two — routed through the
  ``DtypePolicy`` reduction-precision seam (``preferred_element_type``), with
  all four gate activations applied as one vectorized slice-free
  select-on-``[B, 4, H]`` block.
* **pallas** (variant B): a persistent-cell kernel that keeps the whole
  ``[F+H, 4H]`` weight resident in VMEM across a multi-timestep block while
  the Mosaic pipeline double-buffers ``x`` slabs in from HBM, h/c carried in
  revisited VMEM output blocks across the sequential grid. A custom VJP runs
  BPTT as reverse time blocks through the matching backward kernel
  (gates recomputed from the saved h/c histories — flash-attention practice:
  trade FLOPs for HBM). Block size is autotuned over {8, 16, 32} against a
  VMEM-residency budget; see :func:`_vmem_bytes` for the arithmetic.
* **scan** (variant C): the original one-precomputed-input-matmul
  ``lax.scan``, kept as the reference oracle the fast paths are tested
  against (and selectable for on-chip A/B).

Dispatch: ``DL4J_LSTM_IMPL=auto|fused|pallas|scan`` (read at trace time, so
bench A/Bs flip it between traces). ``auto`` engages pallas only past
``(hidden, seq)`` thresholds and under the VMEM budget — the ``batch`` axis
enters through the budget — and falls back to fused everywhere else,
including on CPU and whenever the cell uses non-tanh/sigmoid activations
(the hand-derived kernel backward is specific to the standard cell). Every
selection increments ``dl4j_lstm_dispatch_total`` and the shared
``dl4j_pallas_dispatch_total`` engagement counter.
"""
from __future__ import annotations

import functools
import os
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

from deeplearning4j_tpu.common import accum_dtype, get_policy
from deeplearning4j_tpu.observability.metrics import global_registry
from deeplearning4j_tpu.observability.names import (LSTM_DISPATCH_TOTAL,
                                                    LSTM_PALLAS_BLOCK_STEPS)
from deeplearning4j_tpu.ops.pallas_kernels import _note_dispatch, use_pallas

Array = jax.Array

#: env knob: force one implementation (auto = calibrated gate)
IMPL_ENV = "DL4J_LSTM_IMPL"
#: pallas block-size candidates (timesteps per grid step)
BLOCK_CHOICES = (32, 16, 8)


def _requested_impl() -> str:
    return os.environ.get(IMPL_ENV, "auto").lower()


def _interpret_default() -> bool:
    """DL4J_LSTM_INTERPRET=1 runs the pallas variant in interpret mode — the
    CPU test hook (layer code has no kwarg path down to the kernel)."""
    return os.environ.get("DL4J_LSTM_INTERPRET") == "1"


def _min_hidden() -> int:
    # uncalibrated default, armed for on-chip capture: below MXU-filling
    # widths the fused scan's single small matmul wins (the kernel's custom
    # call is a fusion barrier, same lesson as DL4J_FLASH_MIN_SEQ)
    return int(os.environ.get("DL4J_LSTM_PALLAS_MIN_HIDDEN", "512"))


def _min_seq() -> int:
    # at least one full minimum-size block of real timesteps, or the
    # kernel's fixed launch cost cannot amortize
    return int(os.environ.get("DL4J_LSTM_PALLAS_MIN_SEQ", "8"))


def _vmem_budget() -> int:
    # ~16 MB VMEM/core minus headroom for Mosaic's own pipeline buffers
    return int(os.environ.get("DL4J_LSTM_VMEM_BUDGET", str(12 * 1024 * 1024)))


def _vmem_bytes(bt: int, batch: int, n_in: int, hidden: int,
                itemsize: int) -> int:
    """Worst-case (backward-kernel) VMEM residency for one block config.

    The backward is the binding constraint: it holds W AND the dW accumulator
    (2x the ``(F+H) x 4H`` weight), streams four double-buffered slabs
    (x, h_prev, c_prev, dy) plus the dx output slab, and carries dh/dc in
    f32. The forward fits whenever the backward does.
    """
    fh4 = (n_in + hidden) * 4 * hidden
    w_and_dw = 2 * fh4 * max(itemsize, 4)  # dW accumulates at least f32
    streams = 2 * bt * batch * (n_in + 3 * hidden) * itemsize
    dx_out = 2 * bt * batch * n_in * itemsize
    carries = 8 * batch * hidden * 4
    work = batch * (n_in + 9 * hidden) * 4  # xh + z + dz tiles in f32
    return w_and_dw + streams + dx_out + carries + work


def _pick_block(seq: int, batch: int, n_in: int, hidden: int,
                dtype) -> Optional[int]:
    """Autotuned timestep-block choice: least padding first, then the larger
    block (better weight-reload amortization per DMA), subject to the VMEM
    budget. Sequences are padded up to a block multiple with zero mask (the
    kernel freezes state on masked steps), so any T is serviceable — the
    budget is the only way this returns None."""
    itemsize = jnp.dtype(dtype).itemsize
    env = os.environ.get("DL4J_LSTM_BLOCK")
    if env:
        bt = int(env)
        ok = bt > 0 and _vmem_bytes(bt, batch, n_in, hidden,
                                    itemsize) <= _vmem_budget()
        return bt if ok else None
    for bt in sorted(BLOCK_CHOICES, key=lambda b: ((-seq) % b, -b)):
        if _vmem_bytes(bt, batch, n_in, hidden, itemsize) <= _vmem_budget():
            return bt
    return None


def resolve_impl(hidden: int, seq: int, batch: int, n_in: int, *,
                 dtype=None, act_name: str = "tanh",
                 gate_name: str = "sigmoid", impl: Optional[str] = None,
                 interpret: bool = False) -> Tuple[str, Optional[int]]:
    """THE dispatch gate: -> (implementation, pallas block size or None).

    One predicate for every caller (layers, bench, tests) so a forward under
    ``jax.grad`` can never take a different path than the plain forward.
    Hard constraints on pallas — TPU-or-interpret availability, the standard
    tanh/sigmoid cell (the kernel backward is hand-derived for it), a
    lane-aligned hidden width on real hardware, and the VMEM budget — hold
    even when ``DL4J_LSTM_IMPL=pallas`` forces the variant; a forced-but-
    impossible pallas request degrades to fused, never to a crash."""
    choice = (impl or _requested_impl()).lower()
    if choice not in ("auto", "fused", "pallas", "scan"):
        raise ValueError(f"unknown LSTM impl '{choice}' "
                         "(expected auto|fused|pallas|scan)")
    if choice == "scan":
        return "scan", None
    if choice == "fused":
        return "fused", None
    dtype = dtype if dtype is not None else get_policy().compute_dtype
    pallas_hard_ok = ((use_pallas() or interpret)
                     and act_name in (None, "tanh")
                     and gate_name in (None, "sigmoid")
                     and (interpret or hidden % 128 == 0))
    bt = (_pick_block(seq, batch, n_in, hidden, dtype)
          if pallas_hard_ok else None)
    if choice == "pallas":
        return ("pallas", bt) if bt is not None else ("fused", None)
    # auto: calibrated thresholds (hidden, seq); batch enters via the VMEM
    # budget inside _pick_block
    if bt is not None and hidden >= _min_hidden() and seq >= _min_seq():
        return "pallas", bt
    return "fused", None


# ------------------------------------------------------------- dispatch notes
#: counted per TRACE (like dl4j_pallas_dispatch_total): the branch is baked
#: into the compiled program, so each increment is one program embedding the
#: variant choice, and retraces surface as extra counts
_lstm_dispatch = global_registry().counter(
    LSTM_DISPATCH_TOTAL,
    "recurrent-engine variant selections at trace time, by selected "
    "implementation and requested mode")

_pallas_block = global_registry().gauge(
    LSTM_PALLAS_BLOCK_STEPS,
    "timesteps per pallas LSTM kernel block (VMEM-autotuned) at the most "
    "recent pallas trace")


def _note_impl(selected: str, requested: str, bt: Optional[int]) -> None:
    _lstm_dispatch.labels(impl=selected, requested=requested).inc()
    _note_dispatch("lstm_cell", selected == "pallas")
    if bt is not None:
        _pallas_block.set(bt)


# ------------------------------------------------------ variant C: scan oracle
def lstm_scan(params: dict, x: Array, act, gate_act, h0: Array, c0: Array,
              peephole: bool, mask: Optional[Array]):
    """Reference oracle: precomputed input contraction + per-step recurrent
    matmul under lax.scan. x: [B,T,F] -> (outputs [B,T,H], (h, c)).

    Both contractions route ``preferred_element_type`` through the policy's
    grad-accum seam — the per-step ``h @ RW`` included (it used to
    silently accumulate in compute dtype, bypassing the reduction-precision
    policy the big input matmul honored)."""
    pol = get_policy()
    w = params["W"].astype(pol.compute_dtype)
    rw = params["RW"].astype(pol.compute_dtype)
    b = params["b"].astype(pol.compute_dtype)
    adt = accum_dtype(pol.compute_dtype)

    # Input contributions for all timesteps in one big MXU matmul: [B,T,4H];
    # cast straight back so the scan carry dtype below never changes.
    xw = jnp.einsum("btf,fg->btg", x.astype(pol.compute_dtype), w,
                    preferred_element_type=adt
                    ).astype(pol.compute_dtype) + b

    def step(carry, inputs):
        h, c = carry
        xw_t, m_t = inputs
        z = xw_t + jnp.matmul(h.astype(pol.compute_dtype), rw,
                              preferred_element_type=adt
                              ).astype(pol.compute_dtype)
        zi, zf, zg, zo = jnp.split(z.astype(pol.output_dtype), 4, axis=-1)
        if peephole:
            # cast peephole params to the gate dtype: a silent bf16*f32
            # promotion here would flip the scan carry dtype mid-trace
            zi = zi + c * params["pI"].astype(zi.dtype)
            zf = zf + c * params["pF"].astype(zf.dtype)
        i = gate_act(zi)
        f = gate_act(zf)
        g = act(zg)
        c_new = f * c + i * g
        if peephole:
            zo = zo + c_new * params["pO"].astype(zo.dtype)
        o = gate_act(zo)
        h_new = o * act(c_new)
        if m_t is not None:
            m = m_t[:, None]
            h_new = jnp.where(m > 0, h_new, h)
            c_new = jnp.where(m > 0, c_new, c)
        return (h_new, c_new), h_new

    xw_t = jnp.moveaxis(xw, 1, 0)  # [T,B,4H]
    mask_t = jnp.moveaxis(mask, 1, 0) if mask is not None else None
    if mask_t is None:
        (h, c), ys = lax.scan(lambda cr, xi: step(cr, (xi, None)),
                              (h0, c0), xw_t)
    else:
        (h, c), ys = lax.scan(step, (h0, c0), (xw_t, mask_t))
    return jnp.moveaxis(ys, 0, 1), (h, c)


# ------------------------------------------------------ variant A: fused scan
def lstm_fused(params: dict, x: Array, act, gate_act, h0: Array, c0: Array,
               peephole: bool, mask: Optional[Array]):
    """Fused scan: ONE ``[B, F+H] x [F+H, 4H]`` contraction per step (input
    and recurrent weights concatenated once, outside the scan), gate
    activations applied as a single vectorized slice-free block — a
    select over the ``[B, 4, H]`` view instead of four split-then-activate
    chains. Same signature and numerics contract as :func:`lstm_scan`."""
    pol = get_policy()
    cd = pol.compute_dtype
    od = pol.output_dtype
    adt = accum_dtype(cd)
    wcat = jnp.concatenate([params["W"], params["RW"]], axis=0).astype(cd)
    b = params["b"].astype(od)
    B = x.shape[0]
    hidden = params["RW"].shape[0]
    if peephole:
        zeros_h = jnp.zeros_like(params["pI"])
        # rows (pI, pF, 0, 0): the o-gate peephole taps c_new, added after
        # the cell update below
        p_if = jnp.stack([params["pI"], params["pF"], zeros_h, zeros_h]
                         ).astype(od)
        p_o = params["pO"].astype(od)
    # gate 2 (cell candidate) takes `act`; gates 0/1/3 take `gate_act`
    cell_gate = (jnp.arange(4) == 2).reshape(1, 4, 1)

    def step(carry, inputs):
        h, c = carry
        x_t, m_t = inputs
        xh = jnp.concatenate([x_t.astype(cd), h.astype(cd)], axis=-1)
        z = jnp.matmul(xh, wcat, preferred_element_type=adt).astype(od) + b
        z4 = z.reshape(B, 4, hidden)
        if peephole:
            z4 = z4 + c[:, None, :] * p_if
        g4 = jnp.where(cell_gate, act(z4), gate_act(z4))
        i, f, g, o = g4[:, 0], g4[:, 1], g4[:, 2], g4[:, 3]
        c_new = f * c + i * g
        if peephole:
            o = gate_act(z4[:, 3] + c_new * p_o)
        h_new = o * act(c_new)
        if m_t is not None:
            m = m_t[:, None]
            h_new = jnp.where(m > 0, h_new, h)
            c_new = jnp.where(m > 0, c_new, c)
        return (h_new, c_new), h_new

    x_t = jnp.moveaxis(x, 1, 0)  # [T,B,F]
    mask_t = jnp.moveaxis(mask, 1, 0) if mask is not None else None
    if mask_t is None:
        (h, c), ys = lax.scan(lambda cr, xi: step(cr, (xi, None)),
                              (h0, c0), x_t)
    else:
        (h, c), ys = lax.scan(step, (h0, c0), (x_t, mask_t))
    return jnp.moveaxis(ys, 0, 1), (h, c)


# ------------------------------------------- variant B: pallas persistent cell
def _lstm_fwd_kernel(x_ref, w_ref, b_ref, h0_ref, c0_ref, m_ref, *rest,
                     bt: int, hidden: int, peephole: bool):
    """One grid step = ``bt`` timesteps with the full [F+H, 4H] weight
    resident in VMEM (constant index map -> loaded once for the whole
    sequence) while the pipeline double-buffers the next x slab in.

    h/c live in the revisited (B, H) output blocks: initialized from h0/c0
    at program 0, carried across the sequential grid, final state for free.
    """
    if peephole:
        p_ref, ys_ref, cs_ref, h_ref, c_ref = rest
    else:
        ys_ref, cs_ref, h_ref, c_ref = rest
    wd = jnp.promote_types(x_ref.dtype, jnp.float32)

    @pl.when(pl.program_id(0) == 0)
    def _init():
        h_ref[...] = h0_ref[...].astype(h_ref.dtype)
        c_ref[...] = c0_ref[...].astype(c_ref.dtype)

    w = w_ref[...].astype(wd)
    b = b_ref[0].astype(wd)
    if peephole:
        p_i = p_ref[0].astype(wd)
        p_f = p_ref[1].astype(wd)
        p_o = p_ref[2].astype(wd)

    def body(t, carry):
        h, c = carry
        x_t = x_ref[pl.ds(t, 1)][0].astype(wd)            # [B, F]
        m_t = m_ref[pl.ds(t, 1)][0].astype(wd)[:, None]   # [B, 1]
        xh = jnp.concatenate([x_t, h], axis=-1)           # [B, F+H]
        z = jnp.dot(xh, w, preferred_element_type=wd) + b  # [B, 4H]
        zi = z[:, :hidden]
        zf = z[:, hidden:2 * hidden]
        zg = z[:, 2 * hidden:3 * hidden]
        zo = z[:, 3 * hidden:]
        if peephole:
            zi = zi + c * p_i
            zf = zf + c * p_f
        i = jax.nn.sigmoid(zi)
        f = jax.nn.sigmoid(zf)
        g = jnp.tanh(zg)
        c_new = f * c + i * g
        if peephole:
            zo = zo + c_new * p_o
        o = jax.nn.sigmoid(zo)
        h_new = o * jnp.tanh(c_new)
        h_new = jnp.where(m_t > 0, h_new, h)
        c_new = jnp.where(m_t > 0, c_new, c)
        ys_ref[pl.ds(t, 1)] = h_new[None].astype(ys_ref.dtype)
        cs_ref[pl.ds(t, 1)] = c_new[None].astype(cs_ref.dtype)
        return h_new, c_new

    h, c = lax.fori_loop(0, bt, body,
                         (h_ref[...].astype(wd), c_ref[...].astype(wd)))
    h_ref[...] = h.astype(h_ref.dtype)
    c_ref[...] = c.astype(c_ref.dtype)


def _lstm_bwd_kernel(x_ref, hp_ref, cp_ref, dy_ref, w_ref, b_ref,
                     dht_ref, dct_ref, m_ref, *rest,
                     bt: int, hidden: int, peephole: bool):
    """Reverse time block: recompute the forward gates from the saved h/c
    histories (no [T, B, 4H] activation stash), then the hand-derived cell
    backward. dW/db/dpeep accumulate in constant-index output blocks; dh/dc
    ride the revisited (B, H) blocks that finish as dh0/dc0, seeded from the
    final-state cotangents at program 0 (TBPTT chunk boundaries hand real
    state cotangents in; plain fit passes zeros)."""
    if peephole:
        p_ref, dx_ref, dw_ref, db_ref, dp_ref, dh_ref, dc_ref = rest
    else:
        dx_ref, dw_ref, db_ref, dp_ref, dh_ref, dc_ref = rest
    wd = jnp.promote_types(x_ref.dtype, jnp.float32)
    n_in = x_ref.shape[-1]

    @pl.when(pl.program_id(0) == 0)
    def _init():
        dw_ref[...] = jnp.zeros(dw_ref.shape, dw_ref.dtype)
        db_ref[...] = jnp.zeros(db_ref.shape, db_ref.dtype)
        dp_ref[...] = jnp.zeros(dp_ref.shape, dp_ref.dtype)
        dh_ref[...] = dht_ref[...].astype(dh_ref.dtype)
        dc_ref[...] = dct_ref[...].astype(dc_ref.dtype)

    w = w_ref[...].astype(wd)
    b = b_ref[0].astype(wd)
    if peephole:
        p_i = p_ref[0].astype(wd)
        p_f = p_ref[1].astype(wd)
        p_o = p_ref[2].astype(wd)

    def body(j, carry):
        dh, dc, dw, db, dp = carry
        t = bt - 1 - j
        x_t = x_ref[pl.ds(t, 1)][0].astype(wd)
        hp = hp_ref[pl.ds(t, 1)][0].astype(wd)
        cp = cp_ref[pl.ds(t, 1)][0].astype(wd)
        dy = dy_ref[pl.ds(t, 1)][0].astype(wd)
        m_t = m_ref[pl.ds(t, 1)][0].astype(wd)[:, None]
        # forward recompute (one extra matmul per step; W is already here)
        xh = jnp.concatenate([x_t, hp], axis=-1)
        z = jnp.dot(xh, w, preferred_element_type=wd) + b
        zi = z[:, :hidden]
        zf = z[:, hidden:2 * hidden]
        zg = z[:, 2 * hidden:3 * hidden]
        zo = z[:, 3 * hidden:]
        if peephole:
            zi = zi + cp * p_i
            zf = zf + cp * p_f
        i = jax.nn.sigmoid(zi)
        f = jax.nn.sigmoid(zf)
        g = jnp.tanh(zg)
        c_new = f * cp + i * g
        if peephole:
            zo = zo + c_new * p_o
        o = jax.nn.sigmoid(zo)
        tc = jnp.tanh(c_new)
        # masked steps froze state in the forward: their gradient passes
        # straight through to t-1 and the gates see zero
        dh_t = dh + dy
        dh_act = jnp.where(m_t > 0, dh_t, 0.0)
        dh_skip = jnp.where(m_t > 0, 0.0, dh_t)
        dc_act = jnp.where(m_t > 0, dc, 0.0)
        dc_skip = jnp.where(m_t > 0, 0.0, dc)
        do = dh_act * tc
        dzo = do * o * (1.0 - o)
        dc_t = dc_act + dh_act * o * (1.0 - tc * tc)
        if peephole:
            dc_t = dc_t + dzo * p_o
        di = dc_t * g
        df = dc_t * cp
        dg = dc_t * i
        dzi = di * i * (1.0 - i)
        dzf = df * f * (1.0 - f)
        dzg = dg * (1.0 - g * g)
        dz = jnp.concatenate([dzi, dzf, dzg, dzo], axis=-1)  # [B, 4H]
        dxh = jnp.dot(dz, w.T, preferred_element_type=wd)    # [B, F+H]
        dw = dw + jnp.dot(xh.T, dz, preferred_element_type=wd)
        db = db + jnp.sum(dz, axis=0)
        if peephole:
            dp = dp + jnp.stack([jnp.sum(dzi * cp, axis=0),
                                 jnp.sum(dzf * cp, axis=0),
                                 jnp.sum(dzo * c_new, axis=0)])
        dx_ref[pl.ds(t, 1)] = dxh[None, :, :n_in].astype(dx_ref.dtype)
        dh_next = dxh[:, n_in:] + dh_skip
        dc_next = dc_t * f + dc_skip
        if peephole:
            dc_next = dc_next + dzi * p_i + dzf * p_f
        return dh_next, dc_next, dw, db, dp

    zero_w = jnp.zeros(dw_ref.shape, wd)
    zero_b = jnp.zeros((4 * hidden,), wd)
    zero_p = jnp.zeros((3, hidden), wd)
    dh, dc, dw, db, dp = lax.fori_loop(
        0, bt, body, (dh_ref[...].astype(wd), dc_ref[...].astype(wd),
                      zero_w, zero_b, zero_p))
    dh_ref[...] = dh.astype(dh_ref.dtype)
    dc_ref[...] = dc.astype(dc_ref.dtype)
    dw_ref[...] = (dw_ref[...].astype(wd) + dw).astype(dw_ref.dtype)
    db_ref[...] = (db_ref[...].astype(wd) + db[None]).astype(db_ref.dtype)
    if peephole:
        dp_ref[...] = (dp_ref[...].astype(wd) + dp).astype(dp_ref.dtype)


def _pallas_forward(x_t, wcat, b2, peep, h0, c0, m_t, bt, peephole,
                    interpret):
    """x_t [T,B,F] time-major, T % bt == 0 -> (ys [T,B,H], cs [T,B,H], h, c).
    cs (per-step cell states) feed the backward's recompute."""
    T, B, F = x_t.shape
    H = h0.shape[-1]
    nb = T // bt
    kernel = functools.partial(_lstm_fwd_kernel, bt=bt, hidden=H,
                               peephole=peephole)
    in_specs = [
        pl.BlockSpec((bt, B, F), lambda i: (i, 0, 0)),
        pl.BlockSpec((F + H, 4 * H), lambda i: (0, 0)),  # resident weights
        pl.BlockSpec((1, 4 * H), lambda i: (0, 0)),
        pl.BlockSpec((B, H), lambda i: (0, 0)),
        pl.BlockSpec((B, H), lambda i: (0, 0)),
        pl.BlockSpec((bt, B), lambda i: (i, 0)),
    ]
    operands = [x_t, wcat, b2, h0, c0, m_t]
    if peephole:
        in_specs.append(pl.BlockSpec((3, H), lambda i: (0, 0)))
        operands.append(peep)
    return pl.pallas_call(
        kernel,
        grid=(nb,),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((bt, B, H), lambda i: (i, 0, 0)),
            pl.BlockSpec((bt, B, H), lambda i: (i, 0, 0)),
            pl.BlockSpec((B, H), lambda i: (0, 0)),  # revisited h carry
            pl.BlockSpec((B, H), lambda i: (0, 0)),  # revisited c carry
        ],
        out_shape=[
            jax.ShapeDtypeStruct((T, B, H), x_t.dtype),
            jax.ShapeDtypeStruct((T, B, H), x_t.dtype),
            jax.ShapeDtypeStruct((B, H), h0.dtype),
            jax.ShapeDtypeStruct((B, H), c0.dtype),
        ],
        interpret=interpret,
    )(*operands)


def _pallas_backward(x_t, hprev, cprev, wcat, b2, peep, dys, dht, dct, m_t,
                     bt, peephole, interpret):
    T, B, F = x_t.shape
    H = hprev.shape[-1]
    nb = T // bt
    wd = jnp.promote_types(x_t.dtype, jnp.float32)
    kernel = functools.partial(_lstm_bwd_kernel, bt=bt, hidden=H,
                               peephole=peephole)

    def rev3(i):
        return (nb - 1 - i, 0, 0)

    def rev2(i):
        return (nb - 1 - i, 0)

    def const2(i):
        return (0, 0)

    in_specs = [
        pl.BlockSpec((bt, B, F), rev3),
        pl.BlockSpec((bt, B, H), rev3),
        pl.BlockSpec((bt, B, H), rev3),
        pl.BlockSpec((bt, B, H), rev3),
        pl.BlockSpec((F + H, 4 * H), const2),
        pl.BlockSpec((1, 4 * H), const2),
        pl.BlockSpec((B, H), const2),
        pl.BlockSpec((B, H), const2),
        pl.BlockSpec((bt, B), rev2),
    ]
    operands = [x_t, hprev, cprev, dys, wcat, b2, dht, dct, m_t]
    if peephole:
        in_specs.append(pl.BlockSpec((3, H), const2))
        operands.append(peep)
    return pl.pallas_call(
        kernel,
        grid=(nb,),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((bt, B, F), rev3),
            pl.BlockSpec((F + H, 4 * H), const2),
            pl.BlockSpec((1, 4 * H), const2),
            pl.BlockSpec((3, H), const2),
            pl.BlockSpec((B, H), const2),
            pl.BlockSpec((B, H), const2),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((T, B, F), x_t.dtype),
            jax.ShapeDtypeStruct((F + H, 4 * H), wd),
            jax.ShapeDtypeStruct((1, 4 * H), wd),
            jax.ShapeDtypeStruct((3, H), wd),
            jax.ShapeDtypeStruct((B, H), wd),
            jax.ShapeDtypeStruct((B, H), wd),
        ],
        interpret=interpret,
    )(*operands)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1, 2))
def _pallas_lstm(bt, peephole, interpret, x_t, wcat, b2, peep, h0, c0, m_t):
    ys, _, h, c = _pallas_forward(x_t, wcat, b2, peep, h0, c0, m_t, bt,
                                  peephole, interpret)
    return ys, h, c


def _pallas_lstm_fwd(bt, peephole, interpret, x_t, wcat, b2, peep, h0, c0,
                     m_t):
    ys, cs, h, c = _pallas_forward(x_t, wcat, b2, peep, h0, c0, m_t, bt,
                                   peephole, interpret)
    return (ys, h, c), (x_t, wcat, b2, peep, h0, c0, m_t, ys, cs)


def _pallas_lstm_bwd(bt, peephole, interpret, res, cts):
    x_t, wcat, b2, peep, h0, c0, m_t, ys, cs = res
    dys, dht, dct = cts
    # per-step h_{t-1}/c_{t-1} histories: the saved outputs shifted right by
    # one with the initial state in front
    hprev = jnp.concatenate([h0[None].astype(ys.dtype), ys[:-1]], axis=0)
    cprev = jnp.concatenate([c0[None].astype(cs.dtype), cs[:-1]], axis=0)
    dx, dw, db2, dp, dh0, dc0 = _pallas_backward(
        x_t, hprev, cprev, wcat, b2, peep, dys.astype(x_t.dtype),
        dht.astype(h0.dtype), dct.astype(c0.dtype), m_t, bt, peephole,
        interpret)
    dpeep = dp.astype(peep.dtype) if peephole else jnp.zeros_like(peep)
    return (dx.astype(x_t.dtype), dw.astype(wcat.dtype),
            db2.astype(b2.dtype), dpeep, dh0.astype(h0.dtype),
            dc0.astype(c0.dtype), jnp.zeros_like(m_t))


_pallas_lstm.defvjp(_pallas_lstm_fwd, _pallas_lstm_bwd)


def _lstm_pallas_seq(params: dict, x: Array, h0: Array, c0: Array,
                     peephole: bool, mask: Optional[Array], bt: int,
                     interpret: bool):
    """Engine adapter around the kernel: time-major layout, block padding
    (padded steps carry zero mask, so state freezes and their dx is exactly
    zero), synthesized all-ones mask when the caller has none (``where(1>0)``
    is the identity, so unmasked numerics are untouched)."""
    pol = get_policy()
    cd = pol.compute_dtype
    od = pol.output_dtype
    hidden = params["RW"].shape[0]
    wcat = jnp.concatenate([params["W"], params["RW"]], axis=0).astype(cd)
    b2 = params["b"].astype(cd)[None]
    if peephole:
        peep = jnp.stack([params["pI"], params["pF"], params["pO"]]
                         ).astype(cd)
    else:
        peep = jnp.zeros((3, hidden), cd)
    B, T = x.shape[0], x.shape[1]
    x_t = jnp.moveaxis(x, 1, 0).astype(cd)
    m_t = (jnp.moveaxis(mask, 1, 0).astype(cd) if mask is not None
           else jnp.ones((T, B), cd))
    pad = (-T) % bt
    if pad:
        x_t = jnp.concatenate(
            [x_t, jnp.zeros((pad,) + x_t.shape[1:], x_t.dtype)], axis=0)
        m_t = jnp.concatenate([m_t, jnp.zeros((pad, B), m_t.dtype)], axis=0)
    ys, h, c = _pallas_lstm(bt, peephole, interpret, x_t, wcat, b2, peep,
                            h0.astype(cd), c0.astype(cd), m_t)
    return (jnp.moveaxis(ys[:T], 0, 1).astype(od),
            (h.astype(od), c.astype(od)))


# ------------------------------------------------------------------ the seam
def lstm_sequence(params: dict, x: Array, act, gate_act, h0: Array,
                  c0: Array, peephole: bool, mask: Optional[Array], *,
                  act_name: Optional[str] = "tanh",
                  gate_name: Optional[str] = "sigmoid",
                  impl: Optional[str] = None,
                  interpret: Optional[bool] = None):
    """THE recurrent entry point layers call (full sequences, TBPTT chunks,
    and single-step rnnTimeStep alike). Resolves the implementation at trace
    time via :func:`resolve_impl`, notes the dispatch, runs the variant.
    Returns ``(outputs [B,T,H], (h, c))`` like the original scan."""
    if interpret is None:
        interpret = _interpret_default()
    B, T = x.shape[0], x.shape[1]
    hidden = params["RW"].shape[0]
    selected, bt = resolve_impl(hidden, T, B, x.shape[-1],
                                dtype=get_policy().compute_dtype,
                                act_name=act_name, gate_name=gate_name,
                                impl=impl, interpret=interpret)
    _note_impl(selected, impl or _requested_impl(), bt)
    if selected == "scan":
        return lstm_scan(params, x, act, gate_act, h0, c0, peephole, mask)
    if selected == "pallas":
        return _lstm_pallas_seq(params, x, h0, c0, peephole, mask, bt,
                                interpret)
    return lstm_fused(params, x, act, gate_act, h0, c0, peephole, mask)
