"""Activation functions.

Capability parity with the reference's ``IActivation`` set (ND4J
org.nd4j.linalg.activations, referenced from nn/conf/NeuralNetConfiguration.java:478
``activationFn``). All are pure jnp functions — XLA fuses them into adjacent
matmuls/convs on TPU, which replaces the reference's separate elementwise op dispatch.

Names are matched case-insensitively to the DL4J enum names so imported / serialized
configs round-trip.
"""
from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp

Array = jax.Array


def identity(x: Array) -> Array:
    return x


def relu(x: Array) -> Array:
    return jnp.maximum(x, 0)


def relu6(x: Array) -> Array:
    return jnp.clip(x, 0, 6)


def leakyrelu(x: Array, alpha: float = 0.01) -> Array:
    return jnp.where(x >= 0, x, alpha * x)


def elu(x: Array, alpha: float = 1.0) -> Array:
    safe = jnp.where(x > 0, 0.0, x)
    return jnp.where(x > 0, x, alpha * (jnp.exp(safe) - 1.0))


def selu(x: Array) -> Array:
    return jax.nn.selu(x)


def sigmoid(x: Array) -> Array:
    return jax.nn.sigmoid(x)


def hardsigmoid(x: Array) -> Array:
    return jnp.clip(0.2 * x + 0.5, 0.0, 1.0)


def tanh(x: Array) -> Array:
    return jnp.tanh(x)


def hardtanh(x: Array) -> Array:
    return jnp.clip(x, -1.0, 1.0)


def rationaltanh(x: Array) -> Array:
    # 1.7159 * tanh(2x/3) approximation via rational function (DL4J ActivationRationalTanh)
    return 1.7159 * jnp.tanh(2.0 * x / 3.0)


def rectifiedtanh(x: Array) -> Array:
    return jnp.maximum(0.0, jnp.tanh(x))


def softmax(x: Array) -> Array:
    return jax.nn.softmax(x, axis=-1)


def logsoftmax(x: Array) -> Array:
    return jax.nn.log_softmax(x, axis=-1)


def softplus(x: Array) -> Array:
    return jax.nn.softplus(x)


def softsign(x: Array) -> Array:
    return jax.nn.soft_sign(x)


def cube(x: Array) -> Array:
    return x ** 3


def swish(x: Array) -> Array:
    return x * jax.nn.sigmoid(x)


def gelu(x: Array) -> Array:
    return jax.nn.gelu(x)


ACTIVATIONS: dict[str, Callable[[Array], Array]] = {
    "identity": identity,
    "linear": identity,
    "relu": relu,
    "relu6": relu6,
    "leakyrelu": leakyrelu,
    "elu": elu,
    "selu": selu,
    "sigmoid": sigmoid,
    "hardsigmoid": hardsigmoid,
    "tanh": tanh,
    "hardtanh": hardtanh,
    "rationaltanh": rationaltanh,
    "rectifiedtanh": rectifiedtanh,
    "softmax": softmax,
    "logsoftmax": logsoftmax,
    "softplus": softplus,
    "softsign": softsign,
    "cube": cube,
    "swish": swish,
    "gelu": gelu,
}


def get_activation(name) -> Callable[[Array], Array]:
    """Resolve an activation by DL4J-style name (case-insensitive) or pass a callable through."""
    if callable(name):
        return name
    key = str(name).lower()
    if key not in ACTIVATIONS:
        raise ValueError(f"Unknown activation '{name}'. Known: {sorted(ACTIVATIONS)}")
    return ACTIVATIONS[key]
