"""Pallas TPU kernels for hot ops, with XLA fallbacks.

Reference seam: deeplearning4j-cuda helpers (SURVEY.md §2.3) are reflection-
loaded per layer (ConvolutionLayer.java:69-76) so an accelerator backend can
take over fwd/bwd transparently. Here the seam is ``use_pallas()``: on TPU the
pallas kernels run; elsewhere (or when disabled) the mathematically identical
XLA path runs. Tests exercise the kernels in interpret mode on CPU.

Kernels:
* flash_attention — tiled online-softmax attention (forward), custom VJP with
  a recompute backward (standard flash-attention practice: trade FLOPs for HBM).
* softmax_cross_entropy — fused row-softmax + NLL loss per row.

Sharding interactions (validated on the virtual CPU mesh):
* inside a vma-checked shard_map trace the flash/masked kernels yield to the
  XLA math (_in_checked_shard_map) — the checker rejects pallas_call there.
  shard_map callers that want the kernel set check_vma=False
  (parallel/ring_attention.py ulysses/ring) and the kernel ENGAGES in those
  bodies; the fused xent kernel stays XLA in every shard_map body
  (_in_shard_map — its interpret lowering also trips on the body trace).
* under plain GSPMD sharded jit (ParallelWrapper sync DP) the pallas custom
  call is not batch-partitioned: XLA gathers operands and replicates the
  output. Multi-chip attention should ride ring/ulysses_attention (sequence
  parallelism) instead; if DP-sharded attention throughput looks off on
  hardware, A/B with DL4J_TPU_DISABLE_PALLAS=1 — the XLA einsum path
  partitions cleanly along the batch axis.
"""
from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from deeplearning4j_tpu import jax_compat

Array = jax.Array
_NEG = -1e30

# Flash-attention tile sizes: MXU/VMEM-friendly defaults, overridable for
# on-chip sweeps (DL4J_FLASH_BLK_Q / DL4J_FLASH_BLK_K).
_BLK_Q = int(os.environ.get("DL4J_FLASH_BLK_Q", "128"))
_BLK_K = int(os.environ.get("DL4J_FLASH_BLK_K", "512"))


def _causal_mask(s, q0, k0):
    """Mask score tile ``s`` [blk_q, blk_k] to q_pos >= k_pos, where the tile
    starts at absolute positions (q0, k0). ONE shared convention for the
    forward and both backward kernels — they must never disagree."""
    blk_q, blk_k = s.shape
    q_pos = q0 + jax.lax.broadcasted_iota(jnp.int32, (blk_q, blk_k), 0)
    k_pos = k0 + jax.lax.broadcasted_iota(jnp.int32, (blk_q, blk_k), 1)
    return jnp.where(q_pos >= k_pos, s, _NEG)


def _flatten_heads(a):
    """(B, T, H, D) -> (B*H, T, D) kernel layout."""
    B, T, H, D = a.shape
    return a.transpose(0, 2, 1, 3).reshape(B * H, T, D)


def _unflatten_heads(a, B, H):
    BH, T, D = a.shape
    return a.reshape(B, H, T, D).transpose(0, 2, 1, 3)


def use_pallas() -> bool:
    """Backend seam (reference helper loading seam).

    True when the default device is a TPU. The platform *name* is not enough:
    through the axon relay ``jax.default_backend()`` reports ``"axon"`` even
    though the device is a real TPU chip, so we inspect the device itself —
    ``device_kind`` (e.g. "TPU v5 lite") and the platform string both count.
    """
    if os.environ.get("DL4J_TPU_DISABLE_PALLAS") == "1":
        return False
    try:
        if jax.default_backend() == "cpu":
            return False
        dev = jax.devices()[0]
        kind = (getattr(dev, "device_kind", "") or "").lower()
        plat = (getattr(dev, "platform", "") or "").lower()
        return "tpu" in kind or plat in ("tpu", "axon")
    except Exception:
        return False


# ---------------------------------------------------------------- flash attention
def _flash_fwd_kernel(q_ref, k_ref, v_ref, *rest, blk_k: int, causal: bool,
                      blk_q: int, seq_k: int, scale: float, has_mask: bool):
    """One (batch*head, q-block) program: stream K/V blocks, online softmax.

    q_ref: (blk_q, D); k_ref/v_ref: (seq_k, D); o_ref: (blk_q, D);
    lse_ref: (blk_q,) log-sum-exp of the scaled scores per query row —
    saved so the backward can recompute P = exp(S - lse) without a second
    online-softmax pass. With has_mask, a (seq_k,) {0,1} key-padding mask
    precedes the outputs: masked keys get -inf logits.
    """
    if has_mask:
        km_ref, o_ref, lse_ref = rest
    else:
        o_ref, lse_ref = rest
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * scale      # block is (1, blk_q, D)
    d = q.shape[-1]
    m = jnp.full((blk_q,), _NEG, jnp.float32)
    l = jnp.zeros((blk_q,), jnp.float32)
    acc = jnp.zeros((blk_q, d), jnp.float32)
    n_k = seq_k // blk_k

    def body(j, carry):
        m, l, acc = carry
        k_blk = k_ref[0, pl.ds(j * blk_k, blk_k), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(j * blk_k, blk_k), :].astype(jnp.float32)
        s = q @ k_blk.T                                   # (blk_q, blk_k)
        if has_mask:
            km_blk = km_ref[0, pl.ds(j * blk_k, blk_k), 0].astype(jnp.float32)
            s = jnp.where(km_blk[None, :] > 0, s, _NEG)
        if causal:
            s = _causal_mask(s, qi * blk_q, j * blk_k)
        m_blk = jnp.max(s, axis=1)
        m_new = jnp.maximum(m, m_blk)
        p = jnp.exp(s - m_new[:, None])
        p = jnp.where(s <= _NEG, 0.0, p)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=1)
        acc_new = acc * alpha[:, None] + p @ v_blk
        return m_new, l_new, acc_new

    m, l, acc = jax.lax.fori_loop(0, n_k, body, (m, l, acc))
    l_safe = jnp.maximum(l, 1e-20)
    o_ref[0] = (acc / l_safe[:, None]).astype(o_ref.dtype)
    lse_ref[0, :, 0] = m + jnp.log(l_safe)


def _bh_mask(key_mask: Array, H: int) -> Array:
    """[B, Tk] {0,1} key mask -> (B*H, Tk, 1) f32 kernel operand.

    The trailing singleton is Mosaic block-layout armor shared by every
    per-row vector the flash kernels touch (mask, lse, delta): a (1, blk)
    block on a (B*H, X) array has sublane size 1, which the TPU lowering
    rejects unless it equals the array dim; as (B*H, X, 1) the block
    (1, blk, 1) is legal — blk is 8-divisible and the lane dim matches."""
    B, Tk = key_mask.shape
    return jnp.broadcast_to(key_mask.astype(jnp.float32)[:, None, :],
                            (B, H, Tk)).reshape(B * H, Tk, 1)


def _flash_forward(q: Array, k: Array, v: Array, causal: bool,
                   blk_q: int = None, blk_k: int = None,
                   interpret: bool = False, key_mask: Array = None):
    """q,k,v: (B, T, H, D) -> (out (B, T, H, D), lse (B*H, Tq) f32). None
    block sizes -> env-tunable module defaults (_BLK_Q/_BLK_K). key_mask:
    optional [B, Tk] {0,1} key-padding mask."""
    B, Tq, H, D = q.shape
    Tk = k.shape[1]
    blk_q = min(blk_q, Tq) if blk_q else _pick_blk(Tq, _BLK_Q)
    blk_k = min(blk_k, Tk) if blk_k else _pick_blk(Tk, _BLK_K)
    if not blk_q or not blk_k or Tq % blk_q or Tk % blk_k:
        raise ValueError(f"sequence lengths ({Tq},{Tk}) must be divisible by "
                         f"block sizes ({blk_q},{blk_k})")
    scale = 1.0 / (D ** 0.5)
    qr, kr, vr = _flatten_heads(q), _flatten_heads(k), _flatten_heads(v)
    has_mask = key_mask is not None

    kernel = functools.partial(_flash_fwd_kernel, blk_k=blk_k, causal=causal,
                               blk_q=blk_q, seq_k=Tk, scale=scale,
                               has_mask=has_mask)
    in_specs = [
        pl.BlockSpec((1, blk_q, D), lambda bh, i: (bh, i, 0)),
        pl.BlockSpec((1, Tk, D), lambda bh, i: (bh, 0, 0)),
        pl.BlockSpec((1, Tk, D), lambda bh, i: (bh, 0, 0)),
    ]
    operands = [qr, kr, vr]
    if has_mask:
        in_specs.append(pl.BlockSpec((1, Tk, 1), lambda bh, i: (bh, 0, 0)))
        operands.append(_bh_mask(key_mask, H))
    out, lse = pl.pallas_call(
        kernel,
        grid=(B * H, Tq // blk_q),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, blk_q, D), lambda bh, i: (bh, i, 0)),
            # trailing singleton: see _bh_mask on Mosaic block-layout rules
            pl.BlockSpec((1, blk_q, 1), lambda bh, i: (bh, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, Tq, D), q.dtype),
            jax.ShapeDtypeStruct((B * H, Tq, 1), jnp.float32),
        ],
        interpret=interpret,
    )(*operands)
    return _unflatten_heads(out, B, H), lse[:, :, 0]


def _attention_xla(q, k, v, causal):
    # Single source of truth for the reference math (also the ring-attention
    # correctness oracle) — keep one copy so masking/scaling can't diverge.
    from deeplearning4j_tpu.parallel.ring_attention import attention_reference
    return attention_reference(q, k, v, causal).astype(q.dtype)


def _in_shard_map(x) -> bool:
    """True when ``x`` is being traced inside ANY shard_map body, guarded or
    not. The fused softmax-xent kernel yields to XLA math in every shard_map
    body (its interpret lowering's while_loop carry trips the checker even
    with the guard off); the flash kernels only need the narrower
    :func:`_in_checked_shard_map` test."""
    return (jax_compat._SHARD_MAP_GUARD.get() is not None
            or jax_compat.in_checked_shard_map(x))


def _in_checked_shard_map(x) -> bool:
    """True when ``x`` is device-varying under a vma/rep-CHECKED shard_map
    trace — the contexts whose checker rejects pallas_call, so flash/masked
    dispatch must yield to XLA math. Bodies opened with ``check_vma=False``
    (parallel/ring_attention.py ulysses/ring) return False: the kernel
    engages there, which is the whole point of the sequence-parallel path."""
    return jax_compat.in_checked_shard_map(x)


#: shortest sequence the flash kernel engages at. Short sequences lose to
#: plain XLA attention INSIDE a model: the custom call is a fusion barrier,
#: so surrounding projections lose their elementwise epilogues — measured
#: on-chip (v5e, r5): transformer T=256 runs 24% faster on the XLA path,
#: while standalone attention at T>=1024 runs 1.2-2.7x faster on pallas
#: (scripts/bench_log.jsonl seq sweep). Long context is what the kernel is
#: for; XLA also O(T^2)-materializes scores, so >= this length pallas is
#: both faster and the only memory-safe path.
_MIN_SEQ = int(os.environ.get("DL4J_FLASH_MIN_SEQ", "1024"))


#: dispatch accounting: these call sites execute at TRACE time (the branch
#: is baked into the compiled program), so each increment is one compiled
#: program embedding the pallas-vs-XLA choice — retraces show up as extra
#: counts, which is exactly what an engagement dashboard wants to see
from deeplearning4j_tpu.observability.names import (  # noqa: E402
    PALLAS_DISPATCH_TOTAL,
)
from deeplearning4j_tpu.observability.metrics import (  # noqa: E402
    global_registry as _obs_registry,
)

_pallas_dispatch = _obs_registry().counter(
    PALLAS_DISPATCH_TOTAL,
    "pallas-vs-XLA dispatch decisions at kernel call sites, counted per "
    "trace, by kernel and whether the pallas path engaged")


def _note_dispatch(kernel: str, engaged: bool) -> None:
    _pallas_dispatch.labels(kernel=kernel,
                            engaged="true" if engaged else "false").inc()


def _pallas_ok(q, k, interpret: bool, force: bool = False) -> bool:
    """ONE dispatch predicate for every flash/masked entry point AND its
    custom_vjp fwd rule — they must agree, or a forward under jax.grad would
    silently take a different code path than the plain forward.

    ``force`` is the per-call ``force_pallas`` opt-in: it bypasses the
    _MIN_SEQ length heuristic but never the hard constraints — hardware
    support (``use_pallas()``/interpret), tileable sequence lengths, and the
    vma-checked shard_map guard (the checker rejects pallas_call outright;
    engaging there would crash, not run slowly)."""
    if not ((use_pallas() or interpret)
            and _tileable(q.shape[1], k.shape[1])):
        return False
    if _in_checked_shard_map(q):
        return False
    return force or interpret or max(q.shape[1], k.shape[1]) >= _MIN_SEQ


def _pick_blk(t: int, pref: int):
    """Largest supported block size dividing ``t`` (pref first, then the
    smaller standard tiles). Without the fallback, raising the default
    K-block to 512 would silently drop 128-divisible-but-not-512-divisible
    lengths (1280, 3200, ...) to the O(T^2) XLA path."""
    if t <= 128:
        return t
    for b in sorted({pref, 256, 128}, reverse=True):
        if b <= t and t % b == 0:
            return b
    return None


def _tileable(tq: int, tk: int, blk_q: int = None, blk_k: int = None) -> bool:
    return (_pick_blk(tq, blk_q or _BLK_Q) is not None
            and _pick_blk(tk, blk_k or _BLK_K) is not None)


def _masked_attention_xla(q: Array, k: Array, v: Array, key_mask: Array,
                          causal: bool) -> Array:
    d = q.shape[-1]
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) / jnp.sqrt(jnp.float32(d))
    s = jnp.where(key_mask[:, None, None, :] > 0, s, _NEG)
    if causal:
        tq, tk = s.shape[-2], s.shape[-1]
        cm = jnp.arange(tq)[:, None] >= jnp.arange(tk)[None, :]
        s = jnp.where(cm, s, _NEG)
    p = jax.nn.softmax(s, axis=-1)
    # rows whose keys are ALL masked (padded queries) -> zero output
    p = jnp.where(jnp.max(s, axis=-1, keepdims=True) <= _NEG, 0.0, p)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v).astype(q.dtype)


def masked_attention(q: Array, k: Array, v: Array, key_mask: Array,
                     causal: bool = False, interpret: bool = False,
                     force_pallas: bool = False) -> Array:
    """Attention with a {0,1} key/padding mask [B, Tk]: masked keys get -inf
    logits (NOT zeroed k/v — zeroing still leaves them e^0 softmax mass).
    Shapes as flash_attention: (B, T, H, D). On TPU this rides the same
    tiled Pallas kernels as flash_attention (O(blk·T) memory); elsewhere or
    on non-tileable shapes it runs the identical XLA math.

    Dispatch thresholds and ``force_pallas`` are exactly as documented on
    :func:`flash_attention` — both entry points share one predicate
    (``_pallas_ok``)."""
    return _masked_attention_vjp(q, k, v, key_mask.astype(jnp.float32),
                                 causal, interpret, force_pallas)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def _masked_attention_vjp(q, k, v, key_mask, causal, interpret, force):
    ok = _pallas_ok(q, k, interpret, force)
    _note_dispatch("masked_attention", ok)
    if ok:
        return _flash_forward(q, k, v, causal, interpret=interpret,
                              key_mask=key_mask)[0]
    return _masked_attention_xla(q, k, v, key_mask, causal)


def _masked_fwd_rule(q, k, v, key_mask, causal, interpret, force):
    if _pallas_ok(q, k, interpret, force) \
            and _pallas_bwd_enabled(k.shape[1], force):
        out, lse = _flash_forward(q, k, v, causal, interpret=interpret,
                                  key_mask=key_mask)
        return out, (q, k, v, key_mask, out, lse)
    return (_masked_attention_vjp(q, k, v, key_mask, causal, interpret,
                                  force),
            (q, k, v, key_mask, None, None))


def _masked_bwd_rule(causal, interpret, force, res, g):
    q, k, v, km, out, lse = res
    if lse is not None:
        dq, dk, dv = _flash_backward(q, k, v, out, lse, g, causal,
                                     interpret=interpret, key_mask=km)
    else:
        _, vjp = jax.vjp(
            lambda a, b, c: _masked_attention_xla(a, b, c, km, causal),
            q, k, v)
        dq, dk, dv = vjp(g)
    return dq, dk, dv, jnp.zeros_like(km)


_masked_attention_vjp.defvjp(_masked_fwd_rule, _masked_bwd_rule)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def flash_attention(q: Array, k: Array, v: Array, causal: bool = False,
                    interpret: bool = False,
                    force_pallas: bool = False) -> Array:
    """Tiled attention: pallas forward on TPU (shapes that don't tile fall
    back to the identical XLA math rather than erroring), XLA elsewhere.
    Backward is tiled pallas too (dQ + dK/dV kernels recomputing P from the
    saved logsumexp — flash-attention practice: trade FLOPs for HBM; peak
    extra memory O(blk·T), never O(Tq·Tk)); set DL4J_FLASH_PALLAS_BWD=0 to
    use the XLA chunked-scan backward instead.

    Dispatch thresholds (measured on-chip, v5e round 5):

    * The forward kernel engages only at ``max(Tq, Tk) >=`` **_MIN_SEQ**
      (default 1024, env ``DL4J_FLASH_MIN_SEQ``). Shorter sequences run
      faster on the XLA path inside a model — the custom call is a fusion
      barrier, so neighbouring projections lose their epilogues.
    * The tiled pallas backward engages only at ``Tk >=`` **_PBWD_MIN_SEQ**
      (default 4096, env ``DL4J_FLASH_PBWD_MIN_SEQ``); below that the
      chunked lax.scan backward wins. ``DL4J_FLASH_PALLAS_BWD=0/1``
      overrides unconditionally.

    ``force_pallas=True`` is the per-call opt-in that bypasses both length
    heuristics (for workloads whose measured crossover differs — e.g. a
    sequence-parallel body whose per-shard lengths sit under the gate). It
    never overrides the hard constraints: TPU/interpret availability,
    tileable lengths, and the vma-checked shard_map guard, where
    pallas_call would be rejected outright."""
    ok = _pallas_ok(q, k, interpret, force_pallas)
    _note_dispatch("flash_attention", ok)
    if ok:
        return _flash_forward(q, k, v, causal, interpret=interpret)[0]
    return _attention_xla(q, k, v, causal)


# -------------------------------------------------- pallas backward kernels
def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                         *rest, blk_k: int, causal: bool, blk_q: int,
                         seq_k: int, scale: float, has_mask: bool = False):
    """dQ program per (batch*head, q-block): stream K/V blocks.

    dS = P ∘ (dP − delta) with P = exp(S − lse), dP = dO·Vᵀ,
    delta = rowsum(dO ∘ O); dQ = dS·K·scale. Masked entries clamp to P = 0
    rather than exp(S − lse): for a fully key-masked row lse is ~_NEG and
    the exponent would overflow.
    """
    if has_mask:
        km_ref, dq_ref = rest
    else:
        (dq_ref,) = rest
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32)              # (blk_q, D)
    do = do_ref[0].astype(jnp.float32)
    lse = lse_ref[0, :, 0].astype(jnp.float32)    # (blk_q,)
    delta = delta_ref[0, :, 0].astype(jnp.float32)  # (blk_q,)
    dq = jnp.zeros_like(q)
    n_k = seq_k // blk_k

    def body(j, dq):
        k_blk = k_ref[0, pl.ds(j * blk_k, blk_k), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(j * blk_k, blk_k), :].astype(jnp.float32)
        s = (q @ k_blk.T) * scale
        if has_mask:
            km_blk = km_ref[0, pl.ds(j * blk_k, blk_k), 0].astype(jnp.float32)
            s = jnp.where(km_blk[None, :] > 0, s, _NEG)
        if causal:
            s = _causal_mask(s, qi * blk_q, j * blk_k)
        p = jnp.where(s <= _NEG, 0.0, jnp.exp(s - lse[:, None]))
        dp = do @ v_blk.T
        ds = p * (dp - delta[:, None]) * scale
        return dq + ds @ k_blk

    dq = jax.lax.fori_loop(0, n_k, body, dq)
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                          *rest, blk_q: int, causal: bool,
                          blk_k: int, seq_q: int, scale: float,
                          has_mask: bool = False):
    """dK/dV program per (batch*head, k-block): stream Q/dO blocks.

    dV = Pᵀ·dO accumulated over q-blocks; dK = dSᵀ·Q·scale.
    """
    if has_mask:
        km_ref, dk_ref, dv_ref = rest
    else:
        dk_ref, dv_ref = rest
    ki = pl.program_id(1)
    k_blk = k_ref[0].astype(jnp.float32)          # (blk_k, D)
    v_blk = v_ref[0].astype(jnp.float32)
    km_blk = (km_ref[0, :, 0].astype(jnp.float32)
              if has_mask else None)              # (blk_k,)
    dk = jnp.zeros_like(k_blk)
    dv = jnp.zeros_like(v_blk)
    n_q = seq_q // blk_q

    def body(i, carry):
        dk, dv = carry
        q_blk = q_ref[0, pl.ds(i * blk_q, blk_q), :].astype(jnp.float32)
        do_blk = do_ref[0, pl.ds(i * blk_q, blk_q), :].astype(jnp.float32)
        lse_blk = lse_ref[0, pl.ds(i * blk_q, blk_q), 0].astype(jnp.float32)
        delta_blk = delta_ref[0, pl.ds(i * blk_q, blk_q), 0].astype(jnp.float32)
        s = (q_blk @ k_blk.T) * scale             # (blk_q, blk_k)
        if has_mask:
            s = jnp.where(km_blk[None, :] > 0, s, _NEG)
        if causal:
            s = _causal_mask(s, i * blk_q, ki * blk_k)
        p = jnp.where(s <= _NEG, 0.0, jnp.exp(s - lse_blk[:, None]))
        dv = dv + p.T @ do_blk
        dp = do_blk @ v_blk.T
        ds = p * (dp - delta_blk[:, None]) * scale
        dk = dk + ds.T @ q_blk
        return dk, dv

    dk, dv = jax.lax.fori_loop(0, n_q, body, (dk, dv))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _flash_backward(q, k, v, out, lse, g, causal, blk_q: int = None,
                    blk_k: int = None, interpret: bool = False,
                    key_mask: Array = None):
    """Tiled pallas backward from the saved forward logsumexp. key_mask:
    optional [B, Tk] {0,1} key-padding mask, same semantics as forward."""
    B, Tq, H, D = q.shape
    Tk = k.shape[1]
    blk_q = min(blk_q, Tq) if blk_q else _pick_blk(Tq, _BLK_Q)
    blk_k = min(blk_k, Tk) if blk_k else _pick_blk(Tk, _BLK_K)
    if not blk_q or not blk_k or Tq % blk_q or Tk % blk_k:
        raise ValueError(f"sequence lengths ({Tq},{Tk}) must be divisible by "
                         f"block sizes ({blk_q},{blk_k})")
    scale = 1.0 / (D ** 0.5)
    qr, kr, vr = _flatten_heads(q), _flatten_heads(k), _flatten_heads(v)
    gr, outr = _flatten_heads(g), _flatten_heads(out)
    # delta = rowsum(dO ∘ O): one cheap fused elementwise+reduce in XLA;
    # lse/delta carry a trailing singleton for the kernels (see _bh_mask)
    delta = jnp.sum(gr.astype(jnp.float32) * outr.astype(jnp.float32),
                    axis=-1, keepdims=True)
    lse3 = lse[:, :, None]
    has_mask = key_mask is not None
    km = _bh_mask(key_mask, H) if has_mask else None

    dq_kernel = functools.partial(_flash_bwd_dq_kernel, blk_k=blk_k,
                                  causal=causal, blk_q=blk_q, seq_k=Tk,
                                  scale=scale, has_mask=has_mask)
    dq_specs = [
        pl.BlockSpec((1, blk_q, D), lambda bh, i: (bh, i, 0)),
        pl.BlockSpec((1, Tk, D), lambda bh, i: (bh, 0, 0)),
        pl.BlockSpec((1, Tk, D), lambda bh, i: (bh, 0, 0)),
        pl.BlockSpec((1, blk_q, D), lambda bh, i: (bh, i, 0)),
        pl.BlockSpec((1, blk_q, 1), lambda bh, i: (bh, i, 0)),
        pl.BlockSpec((1, blk_q, 1), lambda bh, i: (bh, i, 0)),
    ]
    dq_operands = [qr, kr, vr, gr, lse3, delta]
    if has_mask:
        dq_specs.append(pl.BlockSpec((1, Tk, 1), lambda bh, i: (bh, 0, 0)))
        dq_operands.append(km)
    dq = pl.pallas_call(
        dq_kernel,
        grid=(B * H, Tq // blk_q),
        in_specs=dq_specs,
        out_specs=pl.BlockSpec((1, blk_q, D), lambda bh, i: (bh, i, 0)),
        out_shape=jax.ShapeDtypeStruct((B * H, Tq, D), q.dtype),
        interpret=interpret,
    )(*dq_operands)

    dkv_kernel = functools.partial(_flash_bwd_dkv_kernel, blk_q=blk_q,
                                   causal=causal, blk_k=blk_k, seq_q=Tq,
                                   scale=scale, has_mask=has_mask)
    dkv_specs = [
        pl.BlockSpec((1, Tq, D), lambda bh, j: (bh, 0, 0)),
        pl.BlockSpec((1, blk_k, D), lambda bh, j: (bh, j, 0)),
        pl.BlockSpec((1, blk_k, D), lambda bh, j: (bh, j, 0)),
        pl.BlockSpec((1, Tq, D), lambda bh, j: (bh, 0, 0)),
        pl.BlockSpec((1, Tq, 1), lambda bh, j: (bh, 0, 0)),
        pl.BlockSpec((1, Tq, 1), lambda bh, j: (bh, 0, 0)),
    ]
    dkv_operands = [qr, kr, vr, gr, lse3, delta]
    if has_mask:
        dkv_specs.append(pl.BlockSpec((1, blk_k, 1), lambda bh, j: (bh, j, 0)))
        dkv_operands.append(km)
    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid=(B * H, Tk // blk_k),
        in_specs=dkv_specs,
        out_specs=[
            pl.BlockSpec((1, blk_k, D), lambda bh, j: (bh, j, 0)),
            pl.BlockSpec((1, blk_k, D), lambda bh, j: (bh, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, Tk, D), k.dtype),
            jax.ShapeDtypeStruct((B * H, Tk, D), v.dtype),
        ],
        interpret=interpret,
    )(*dkv_operands)

    return (_unflatten_heads(dq, B, H), _unflatten_heads(dk, B, H),
            _unflatten_heads(dv, B, H))


def _attention_bwd_chunked(q, k, v, g, causal, blk_q: int = None):
    """Chunked attention backward: lax.scan over query blocks, recomputing the
    (blk_q, Tk) score tile per step. dK/dV accumulate in f32 in the carry.

    Standard flash-attention backward identities: with P = softmax(S),
    dV = Pᵀ dO, dP = dO Vᵀ, dS = P ∘ (dP − rowsum(P ∘ dP)), dQ = dS·K·scale,
    dK = dSᵀ·Q·scale. Query rows padded up to a block multiple carry dO = 0,
    which makes their dS exactly 0, so padding contributes nothing.
    None blk_q -> env-tunable module default (_BLK_Q).
    """
    blk_q = blk_q or _BLK_Q
    B, Tq, H, D = q.shape
    Tk = k.shape[1]
    scale = 1.0 / (D ** 0.5)
    blk_q = min(blk_q, Tq)
    pad = (-Tq) % blk_q
    qp = jnp.pad(q, ((0, 0), (0, pad), (0, 0), (0, 0))) if pad else q
    gp = jnp.pad(g, ((0, 0), (0, pad), (0, 0), (0, 0))) if pad else g
    n = (Tq + pad) // blk_q
    # (n, B, blk_q, H, D) chunk-major for scan
    qs = qp.reshape(B, n, blk_q, H, D).transpose(1, 0, 2, 3, 4)
    gs = gp.reshape(B, n, blk_q, H, D).transpose(1, 0, 2, 3, 4)
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)

    def chunk(carry, inp):
        dk, dv = carry
        qc, gc, idx = inp
        qc = qc.astype(jnp.float32)
        gc = gc.astype(jnp.float32)
        s = jnp.einsum("bqhd,bkhd->bhqk", qc, kf) * scale
        if causal:
            q_pos = idx * blk_q + jnp.arange(blk_q)
            mask = q_pos[:, None] >= jnp.arange(Tk)[None, :]
            s = jnp.where(mask[None, None], s, _NEG)
        p = jax.nn.softmax(s, axis=-1)
        dp = jnp.einsum("bqhd,bkhd->bhqk", gc, vf)
        delta = jnp.sum(p * dp, axis=-1, keepdims=True)
        ds = p * (dp - delta) * scale
        dqc = jnp.einsum("bhqk,bkhd->bqhd", ds, kf)
        dk = dk + jnp.einsum("bhqk,bqhd->bkhd", ds, qc)
        dv = dv + jnp.einsum("bhqk,bqhd->bkhd", p, gc)
        return (dk, dv), dqc

    # derive the accumulator zeros from k/v (not fresh arrays) so their
    # device-varying annotation matches inside shard_map bodies
    (dk, dv), dqs = jax.lax.scan(
        chunk, ((kf * 0.0), (vf * 0.0)), (qs, gs, jnp.arange(n)))
    dq = dqs.transpose(1, 0, 2, 3, 4).reshape(B, Tq + pad, H, D)[:, :Tq]
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


#: shortest sequence the tiled pallas BACKWARD engages at (the forward has
#: its own _MIN_SEQ gate). Below this the chunked lax.scan backward wins:
#: measured on-chip (v5e, r5, 512-wide K tiles) T=2048 runs 7% faster
#: chunked while T=4096 runs 35% faster tiled — the dq+dkv kernel pair's
#: fixed overhead amortizes only on long sequences.
_PBWD_MIN_SEQ = int(os.environ.get("DL4J_FLASH_PBWD_MIN_SEQ", "4096"))


def _pallas_bwd_enabled(seq_k: int = None, force: bool = False) -> bool:
    env = os.environ.get("DL4J_FLASH_PALLAS_BWD")
    if env is not None:
        return env != "0"
    return force or seq_k is None or seq_k >= _PBWD_MIN_SEQ


def _flash_fwd_rule(q, k, v, causal, interpret, force):
    if _pallas_ok(q, k, interpret, force) \
            and _pallas_bwd_enabled(k.shape[1], force):
        out, lse = _flash_forward(q, k, v, causal, interpret=interpret)
        return out, (q, k, v, out, lse)
    return (flash_attention(q, k, v, causal, interpret, force),
            (q, k, v, None, None))


def _flash_bwd_rule(causal, interpret, force, res, g):
    q, k, v, out, lse = res
    if lse is not None:
        return _flash_backward(q, k, v, out, lse, g, causal,
                               interpret=interpret)
    return _attention_bwd_chunked(q, k, v, g, causal)


flash_attention.defvjp(_flash_fwd_rule, _flash_bwd_rule)


# ------------------------------------------------------- fused softmax-xent
def _sm_xent_kernel(logits_ref, labels_ref, loss_ref, grad_ref):
    """Row-fused log-softmax + NLL + gradient: one pass over the logits block."""
    x = logits_ref[:].astype(jnp.float32)
    y = labels_ref[:].astype(jnp.float32)
    m = jnp.max(x, axis=1, keepdims=True)
    e = jnp.exp(x - m)
    z = jnp.sum(e, axis=1, keepdims=True)
    logp = x - m - jnp.log(z)
    loss_ref[:] = -jnp.sum(y * logp, axis=1, keepdims=True).astype(loss_ref.dtype)
    grad_ref[:] = (e / z - y).astype(grad_ref.dtype)


def softmax_cross_entropy(logits: Array, labels: Array, blk: int = 256,
                          interpret: bool = False):
    """Fused per-row loss + dlogits. Returns (loss (N,), grad (N, C)).
    Pallas on TPU; identical XLA math elsewhere.

    Under a shard_map trace (non-empty vma on the operands — e.g.
    ParallelWrapper's local-SGD per-replica step) the pallas_call is skipped
    in favor of the XLA math: the vma checker rejects the kernel's
    out_shape and the interpret lowering its internal while_loop carry, and
    XLA fuses this row-wise chain well anyway."""
    N, C = logits.shape
    engaged = ((use_pallas() or interpret) and N % min(blk, N) == 0
               and not _in_shard_map(logits))
    _note_dispatch("softmax_cross_entropy", engaged)
    if engaged:
        blk = min(blk, N)
        loss, grad = pl.pallas_call(
            _sm_xent_kernel,
            grid=(N // blk,),
            in_specs=[
                pl.BlockSpec((blk, C), lambda i: (i, 0)),
                pl.BlockSpec((blk, C), lambda i: (i, 0)),
            ],
            out_specs=[
                pl.BlockSpec((blk, 1), lambda i: (i, 0)),
                pl.BlockSpec((blk, C), lambda i: (i, 0)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((N, 1), jnp.float32),
                jax.ShapeDtypeStruct((N, C), logits.dtype),
            ],
            interpret=interpret,
        )(logits, labels)
        return loss[:, 0], grad
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    loss = -jnp.sum(labels * logp, axis=-1)
    grad = (jnp.exp(logp) - labels).astype(logits.dtype)
    return loss, grad


# ----------------------------------------------- fused batch-norm statistics
def _add2(acc, val):
    return acc[0] + val[0], acc[1] + val[1]


def batch_norm_stats(x: Array, axes, stat_dtype):
    """Single-pass batch statistics: (mean, biased var) over ``axes``.

    ONE variadic ``lax.reduce`` accumulates sum(x) and sum(x*x) together, so
    the whole computation is a single fused pass over the tensor — unlike
    ``jnp.mean`` + ``jnp.var``, which lowers to two full passes (the second
    re-reading x to form (x - mean)^2) with a standalone f32 upcast-reduce
    fusion each on the bf16 path (23% of ResNet-50 device time, r5 profile).

    ``stat_dtype`` is the reduce operand/accumulator dtype
    (DtypePolicy.reduction_dtype): bf16 keeps the pass convert-free on bf16
    activations; f32/f64 inserts one fused upcast prologue. var clamps at 0
    against E[x^2]-mean^2 cancellation noise.
    """
    n = 1
    for a in axes:
        n *= x.shape[a]
    xs = x.astype(stat_dtype)
    zero = jnp.zeros((), stat_dtype)
    s1, s2 = jax.lax.reduce((xs, xs * xs), (zero, zero), _add2, tuple(axes))
    inv_n = jnp.asarray(1.0 / n, stat_dtype)
    mean = s1 * inv_n
    var = jnp.maximum(s2 * inv_n - mean * mean, jnp.zeros((), stat_dtype))
    return mean, var


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def batch_norm_train(x: Array, gamma: Array, beta: Array, axes, eps,
                     stat_dtype):
    """Train-mode batch norm with policy-controlled reduction precision.

    Returns ``(out, mean, var)``; ``axes`` are the leading statistic axes
    (channel axis trailing, reference BN convention). Forward: single-pass
    stats (:func:`batch_norm_stats`) + a folded ``x * scale + shift``
    elementwise pass in x.dtype — no full-tensor upcast. Backward
    (hand-written): dgamma/dbeta in ONE variadic reduce pass, dx as one
    elementwise pass, instead of autodiff's mean/var chains (several
    standalone f32 reduce fusions on the bf16 path).

    The ``mean``/``var`` outputs exist for the EMA running-state update and
    are treated as NON-differentiable — their cotangents are discarded, so
    do not differentiate through them.
    """
    out, mean, var = _bn_train_impl(x, gamma, beta, axes, eps, stat_dtype)
    return out, mean, var


def _bn_train_impl(x, gamma, beta, axes, eps, stat_dtype):
    mean, var = batch_norm_stats(x, axes, stat_dtype)
    # inv in f32-at-least: rsqrt of a bf16 var costs accuracy on a
    # channel-sized vector for no bandwidth win
    wide = jnp.promote_types(stat_dtype, jnp.float32)
    inv = jax.lax.rsqrt(var.astype(wide) + eps)
    scale = gamma.astype(wide) * inv
    shift = beta.astype(wide) - mean.astype(wide) * scale
    out = x * scale.astype(x.dtype) + shift.astype(x.dtype)
    return out, mean, var


def _bn_train_fwd(x, gamma, beta, axes, eps, stat_dtype):
    out, mean, var = _bn_train_impl(x, gamma, beta, axes, eps, stat_dtype)
    wide = jnp.promote_types(stat_dtype, jnp.float32)
    inv = jax.lax.rsqrt(var.astype(wide) + eps)
    return (out, mean, var), (x, gamma, mean, inv)


def _bn_train_bwd(axes, eps, stat_dtype, res, cts):
    x, gamma, mean, inv = res
    dy = cts[0]  # mean/var cotangents: EMA plumbing only, not differentiated
    n = 1
    for a in axes:
        n *= x.shape[a]
    wide = inv.dtype
    xhat = (x - mean.astype(x.dtype)) * inv.astype(x.dtype)
    # dbeta = sum(dy), dgamma = sum(dy * xhat): one fused variadic pass in
    # the policy reduction dtype, same shape discipline as the forward stats
    t1 = dy.astype(stat_dtype)
    t2 = (dy * xhat).astype(stat_dtype)
    zero = jnp.zeros((), stat_dtype)
    dbeta, dgamma = jax.lax.reduce((t1, t2), (zero, zero), _add2,
                                   tuple(axes))
    k = gamma.astype(wide) * inv
    inv_n = 1.0 / n
    dx = k.astype(x.dtype) * (
        dy - (dbeta.astype(wide) * inv_n).astype(x.dtype)
        - xhat * (dgamma.astype(wide) * inv_n).astype(x.dtype))
    return (dx.astype(x.dtype), dgamma.astype(gamma.dtype),
            dbeta.astype(gamma.dtype))


batch_norm_train.defvjp(_bn_train_fwd, _bn_train_bwd)
