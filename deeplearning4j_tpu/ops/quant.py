"""Int8 weight quantization for the serving path (the serving DtypePolicy).

Training precision is governed by ``common.DtypePolicy``; serving adds one
more lever the fit path must never see: **weight-only int8**. At pin time
(:func:`quantize_tree`, called from ``nn/inference.py`` / the decode engine)
every large floating matrix leaf is replaced by a :class:`QuantizedLeaf` —
symmetric per-output-channel scales calibrated from the pinned snapshot
(absmax / 127, no calibration data needed for weight-only) plus an ``int8``
code tensor. The params that live in HBM and in jit arguments are then 8-bit:
a 4x (vs f32) resident-bytes cut per pinned version, which is what lets one
chip hold more hot versions and bigger KV caches.

Compute path, in order of preference:

- :func:`quantized_matmul` — the dequant-free seam. On TPU (or in interpret
  mode) a Pallas kernel streams int8 weight tiles into VMEM and applies the
  per-channel scale to the f32 accumulator tile **in registers**: the dense
  bf16/f32 weight matrix is never materialized anywhere. Elsewhere the XLA
  fallback computes ``(x @ q.astype(compute)) * scale`` — the cast is fused
  into the matmul operand read and the scale into its epilogue, so memory
  traffic stays int8 even though a cast happens per tile.
- :func:`dequantize_tree` — the bf16 fallback for code paths that reach a
  layer's stock ``apply`` (generic ``PredictFn`` forwards): runs INSIDE the
  jitted program, so weights at rest stay int8 and XLA fuses the dequant
  into each consumer.

Accuracy contract (pinned by tests/test_decode.py): per-channel symmetric
int8 keeps serving outputs within a documented drift bound of the bf16/f32
reference — mean |prob drift| <= 2e-2 and >= 90%% greedy top-1 agreement on
the char-RNN and transformer evals. Anything worse is a quantizer bug, not
an expected artifact.
"""
from __future__ import annotations

import functools
from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

Array = jax.Array

#: leaves smaller than this stay dense: biases, LN scales and tiny heads
#: carry no memory win and their quantization error is pure downside
MIN_QUANT_ELEMS = 1024


class QuantizedLeaf(NamedTuple):
    """One int8-quantized weight: ``q`` int8 codes, ``scale`` f32 per
    output channel (last axis), ``float(q) * scale`` reconstructs. A
    NamedTuple is already a pytree node, so quantized trees flow through
    jit/device_put; consumers that must see WHOLE leaves pass
    ``is_leaf=is_quantized``."""

    q: Array      # int8, original weight shape
    scale: Array  # f32, shape == (w.shape[-1],)


def is_quantized(leaf: Any) -> bool:
    return isinstance(leaf, QuantizedLeaf)


def quantize_per_channel(w: Array) -> QuantizedLeaf:
    """Symmetric per-output-channel (last axis) int8 quantization.

    Scales are calibrated from the tensor itself: absmax/127 per channel —
    weight-only quantization needs no activation statistics. All-zero
    channels get scale 1 so reconstruction stays exact (0 * 1 == 0).
    """
    wf = jnp.asarray(w, jnp.float32)
    absmax = jnp.max(jnp.abs(wf), axis=tuple(range(wf.ndim - 1)))
    scale = jnp.where(absmax > 0, absmax / 127.0, 1.0)
    q = jnp.clip(jnp.round(wf / scale), -127, 127).astype(jnp.int8)
    return QuantizedLeaf(q=q, scale=scale.astype(jnp.float32))


def dequantize_leaf(leaf: QuantizedLeaf, dtype=jnp.float32) -> Array:
    return (leaf.q.astype(jnp.float32) * leaf.scale).astype(dtype)


def _eligible(leaf: Any, min_elems: int) -> bool:
    a = leaf
    return (hasattr(a, "ndim") and a.ndim >= 2 and a.size >= min_elems
            and jnp.issubdtype(jnp.asarray(a).dtype, jnp.floating))


def quantize_tree(tree, min_elems: int = MIN_QUANT_ELEMS):
    """Quantize every eligible matrix leaf of a param pytree to int8.

    Eligible = floating, ndim >= 2, size >= ``min_elems``; everything else
    (biases, norms, peepholes, embedded scalars) is kept as-is. Runs at pin
    time, off the serving path.
    """
    return jax.tree_util.tree_map(
        lambda a: quantize_per_channel(a) if _eligible(a, min_elems) else a,
        tree)


def dequantize_tree(tree, dtype=jnp.float32):
    """Reconstruct a dense tree from a quantized one (bf16-fallback seam).

    Called INSIDE a jitted program: the jit arguments (and HBM residents)
    stay int8, and XLA fuses each leaf's dequant into its consumers.
    """
    return jax.tree_util.tree_map(
        lambda a: dequantize_leaf(a, dtype) if is_quantized(a) else a,
        tree, is_leaf=is_quantized)


def gather_rows(w, idx) -> Array:
    """Row gather (embedding lookup) that understands :class:`QuantizedLeaf`:
    the int8 rows are gathered first, so HBM traffic is 1 byte/element, and
    the per-channel scale is applied to the gathered rows only."""
    if is_quantized(w):
        return w.q[idx].astype(jnp.float32) * w.scale
    return jnp.asarray(w)[idx]


def tree_param_bytes(tree) -> int:
    """Resident bytes of a (possibly quantized) param tree — the number the
    int8 policy exists to shrink; surfaced via ModelVersion.describe()."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        total += int(getattr(leaf, "size", 0)) * int(
            jnp.dtype(getattr(leaf, "dtype", jnp.float32)).itemsize)
    return total


# ------------------------------------------------------------ dequant-free matmul
_BLK_N = 128


def _int8_matmul_kernel(x_ref, q_ref, s_ref, o_ref):
    """One N-tile program: f32 accumulate x @ q with the per-channel scale
    applied to the accumulator tile in registers — the dense weight tile
    never exists outside VMEM/registers."""
    acc = jnp.dot(x_ref[...].astype(jnp.float32),
                  q_ref[...].astype(jnp.float32),
                  preferred_element_type=jnp.float32)
    o_ref[...] = acc * s_ref[...]


def _pallas_int8_ok(x: Array, leaf: QuantizedLeaf, interpret: bool) -> bool:
    from deeplearning4j_tpu.ops.pallas_kernels import use_pallas
    if not (use_pallas() or interpret):
        return False
    k, n = leaf.q.shape[-2], leaf.q.shape[-1]
    return (x.ndim == 2 and leaf.q.ndim == 2
            and n % _BLK_N == 0 and k % 128 == 0)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _int8_matmul_pallas(x, q, scale, interpret=False):
    m, k = x.shape
    n = q.shape[-1]
    return pl.pallas_call(
        _int8_matmul_kernel,
        grid=(n // _BLK_N,),
        in_specs=[
            pl.BlockSpec((m, k), lambda j: (0, 0)),
            pl.BlockSpec((k, _BLK_N), lambda j: (0, j)),
            pl.BlockSpec((1, _BLK_N), lambda j: (0, j)),
        ],
        out_specs=pl.BlockSpec((m, _BLK_N), lambda j: (0, j)),
        out_shape=jax.ShapeDtypeStruct((m, n), jnp.float32),
        interpret=interpret,
    )(x, q, scale.reshape(1, n))


def quantized_matmul(x: Array, w, *, compute_dtype=None,
                     interpret: Optional[bool] = None) -> Array:
    """``x @ w`` where ``w`` may be a :class:`QuantizedLeaf` or a dense
    array — THE matmul seam for quantization-aware code paths (the decode
    step). Dense weights take the plain matmul; quantized weights take the
    Pallas dequant-free kernel when the hardware and tile alignment allow,
    else the cast-fused XLA fallback. Output is f32 (callers cast into
    their policy dtype, matching the ``preferred_element_type`` idiom)."""
    if not is_quantized(w):
        cd = compute_dtype or x.dtype
        return jnp.matmul(x.astype(cd), jnp.asarray(w).astype(cd),
                          preferred_element_type=jnp.float32)
    from deeplearning4j_tpu.ops.pallas_kernels import _note_dispatch
    if interpret is None:
        import os
        interpret = os.environ.get("DL4J_INT8_INTERPRET") == "1"
    if _pallas_int8_ok(x, w, interpret):
        _note_dispatch("int8_matmul", True)
        return _int8_matmul_pallas(x, w.q, w.scale, interpret=interpret)
    _note_dispatch("int8_matmul", False)
    cd = compute_dtype or x.dtype
    acc = jnp.matmul(x.astype(cd), w.q.astype(cd),
                     preferred_element_type=jnp.float32)
    return acc * w.scale
