"""Page-table gather for the paged decode plane (ops seam).

The paged decode step stores KV in a physical page pool
``[n_pages + 1, page_size, H, D]`` and resolves each slot's logical
``[max_context, H, D]`` view through its page-table row at attention
time. This module owns that gather, behind the same dispatch-gate idiom
as the int8 matmul (ops/quant.py) and the LSTM engine (ops/lstm.py):

- **Pallas path** (TPU, or interpret mode for CI): the page table rides
  scalar prefetch (``pltpu.PrefetchScalarGridSpec``), so the block
  index map reads the physical page id BEFORE the kernel body runs and
  the DMA engine streams exactly the mapped pages HBM→VMEM — the
  logical view is materialized tile by tile, never as a second dense
  copy in HBM.
- **XLA fallback** (CPU hosts, kill switch): one fused ``take`` along
  the page axis.

Both paths are pure data movement over the same indices, so they are
bitwise identical by construction — the dispatch gate can never change
decoded tokens, only where the gather's bytes move. Selection:
``DL4J_PAGED_GATHER_IMPL`` = ``auto`` (default: Pallas iff the backend
is TPU) | ``pallas`` | ``xla``; ``DL4J_PAGED_GATHER_INTERPRET=1`` runs
the Pallas kernel in interpret mode (CI coverage on CPU). Every call
lands on the shared ``dl4j_pallas_dispatch_total`` counter under kernel
``paged_gather``.
"""
from __future__ import annotations

import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pltpu imports fail on builds without the TPU plugin
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover - exercised only on minimal builds
    pltpu = None


def resolve_paged_impl(requested=None):
    """``(impl, interpret)`` for this host: explicit request beats env
    beats auto (Pallas iff TPU, mirroring ops/lstm.py's resolve)."""
    from deeplearning4j_tpu.ops.pallas_kernels import use_pallas
    req = requested or os.environ.get("DL4J_PAGED_GATHER_IMPL", "auto")
    if req not in ("auto", "pallas", "xla"):
        raise ValueError(
            f"DL4J_PAGED_GATHER_IMPL must be auto|pallas|xla, got {req!r}")
    interpret = os.environ.get("DL4J_PAGED_GATHER_INTERPRET") == "1"
    if req == "xla":
        return "xla", False
    if req == "pallas":
        return "pallas", interpret
    if pltpu is not None and (use_pallas() or interpret):
        return "pallas", interpret
    return "xla", False


def _gather_kernel(table_ref, pool_ref, out_ref):
    # the index map already resolved the physical page; one block copy
    out_ref[...] = pool_ref[...].reshape(out_ref.shape)


def _paged_gather_pallas(pool, table, interpret: bool):
    n_total, ps, H, D = pool.shape
    cap, P = table.shape
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(cap, P),
        in_specs=[
            # block (c, p) DMAs physical page table[c, p] — the scalar-
            # prefetched table is visible to the index map pre-kernel
            pl.BlockSpec((1, ps, H, D),
                         lambda c, p, tab: (tab[c, p], 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, ps, H, D),
                               lambda c, p, tab: (c, p, 0, 0, 0)),
    )
    out = pl.pallas_call(
        _gather_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((cap, P, ps, H, D), pool.dtype),
        interpret=interpret,
    )(table, pool)
    return out.reshape(cap, P * ps, H, D)


def paged_gather(pool, table, *, impl=None):
    """Materialize the logical KV view ``[cap, P*page_size, H, D]`` of a
    physical ``pool [n_pages+1, page_size, H, D]`` through ``table
    [cap, P]`` (int32 physical page ids; trash-page rows are garbage the
    caller's attention mask must never select)."""
    from deeplearning4j_tpu.ops.pallas_kernels import _note_dispatch
    kind, interpret = resolve_paged_impl(impl)
    if kind == "pallas" and pltpu is not None:
        _note_dispatch("paged_gather", True)
        return _paged_gather_pallas(pool, table, interpret)
    _note_dispatch("paged_gather", False)
    n_total, ps, H, D = pool.shape
    cap, P = table.shape
    return jnp.take(pool, table.reshape(-1), axis=0).reshape(
        cap, P * ps, H, D)
