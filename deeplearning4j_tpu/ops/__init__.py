"""Low-level op layer: activations, losses, weight init, conv/pool primitives.

This is the substrate the reference gets from ND4J/libnd4j (external C++ backends);
here it is jax.numpy / lax, compiled by XLA:TPU, with Pallas kernels for fused
hot paths (see deeplearning4j_tpu.ops.pallas_kernels).
"""
from deeplearning4j_tpu.ops.activations import get_activation, ACTIVATIONS
from deeplearning4j_tpu.ops.losses import get_loss, LOSSES
