"""graftlint CLI: ``python -m deeplearning4j_tpu.lint [paths] [options]``.

Exit status: 0 clean, 1 unsuppressed violations (or parse errors), 2 usage
error. ``--json`` emits one machine-readable object (the lint_gate.sh /
baseline format); the default human format is one ``path:line: [rule]``
row per finding.
"""
from __future__ import annotations

import argparse
import json
import os
import pathlib
import sys
from typing import List, Optional

from . import REGISTRY, rule_names, rule_versions, run_paths


def _build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m deeplearning4j_tpu.lint",
        description="graftlint: JAX/TPU-aware static analysis "
                    "(rule catalog: --list-rules)")
    p.add_argument("paths", nargs="*",
                   help="files or package dirs to lint (default: the "
                        "deeplearning4j_tpu package this module lives in)")
    p.add_argument("--json", action="store_true",
                   help="emit one JSON object instead of human lines")
    p.add_argument("--rules",
                   help="comma-separated rule subset (default: all)")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule catalog and exit")
    p.add_argument("--show-suppressed", action="store_true",
                   help="also print suppressed findings (human mode; JSON "
                        "always includes them)")
    p.add_argument("--jobs", type=int, default=os.cpu_count() or 1,
                   metavar="N",
                   help="worker processes for the per-file check phase "
                        "(default: all cores; output is deterministic "
                        "at any N; 1 disables forking)")
    return p


def main(argv: Optional[List[str]] = None) -> int:
    args = _build_parser().parse_args(argv)

    if args.list_rules:
        for name in rule_names():
            print(f"{name:24s} {REGISTRY[name].description}")
        return 0

    paths = args.paths or [str(pathlib.Path(__file__).resolve().parents[1])]
    subset = None
    if args.rules:
        subset = [r.strip() for r in args.rules.split(",") if r.strip()]
    try:
        result = run_paths(paths, subset, jobs=max(1, args.jobs))
    except ValueError as e:
        print(str(e), file=sys.stderr)
        return 2

    if args.json:
        payload = result.to_json()
        # implementation hash per rule: the baseline records these so a
        # rule edit invalidates its old suppressions (see lint_gate.sh)
        payload["rule_versions"] = rule_versions()
        print(json.dumps(payload, indent=2, sort_keys=True))
    else:
        for err in result.errors:
            print(f"ERROR {err}")
        for v in result.violations:
            print(v.render())
            if v.snippet:
                print(f"    {v.snippet}")
        if args.show_suppressed:
            for v in result.suppressed:
                print(v.render())
        n, s = len(result.violations), len(result.suppressed)
        print(f"graftlint: {result.files_scanned} files, {n} violation(s), "
              f"{s} suppressed, {len(result.errors)} error(s)")
    return 0 if result.ok else 1


if __name__ == "__main__":
    sys.exit(main())
