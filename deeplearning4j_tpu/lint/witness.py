"""Runtime lock-order witness: the dynamic half of the concurrency plane.

The static ``lock-order`` rule (``lint/concurrency.py``) proves the
*declared* acquisition graph acyclic — every nesting it can see in the
source. This module witnesses the *executed* graph: patch
``threading.Lock``/``threading.RLock`` with recording wrappers, run the
real threaded suites (serve, autoscale, dataplane, ps), and assert at
teardown that no two locks were ever taken in both orders. A cycle here
is a deadlock that needs only the right interleaving — the witness turns
"we never happened to deadlock in CI" into "no run ever acquired locks
in conflicting order".

Opt-in and test-only by design: ``install()`` swaps the factories,
``uninstall()`` restores them, and the pytest session fixture in
``tests/conftest.py`` gates the whole thing behind ``DL4J_LOCK_WITNESS=1``
so production code paths never pay the bookkeeping. Lock identity is the
**creation site** (``file:line`` of the ``Lock()`` call), which collapses
every instance of a class onto one node — the same granularity the static
rule uses, so the two graphs can be compared edge-for-edge.

Protocol notes: the wrappers implement the full ``Condition`` protocol
(``_is_owned`` / ``_release_save`` / ``_acquire_restore``) so
``threading.Condition(wrapped)`` — and bare ``Condition()``, whose
default lock comes from the patched ``RLock`` factory — keep working.
Re-acquiring a held RLock records no edge (reentrancy is not an
ordering), and the re-acquire inside ``Condition.wait`` records no edge
either (waking from a wait is a resume, not a new nesting decision).
"""
from __future__ import annotations

import os
import sys
import threading
from typing import Dict, List, Optional, Tuple

# Real factories, captured at import time so the witness's own
# bookkeeping lock keeps working while the module-level names are
# patched out from under everyone else.
_REAL_LOCK = threading.Lock
_REAL_RLOCK = threading.RLock

_graph_lock = _REAL_LOCK()
#: (from_node, to_node) -> (thread_name, acquire_site) of the FIRST
#: witnessed nesting, so failure messages point at a real stack line.
_edges: Dict[Tuple[str, str], Tuple[str, str]] = {}
_held = threading.local()  # per-thread stack of currently held nodes
_installed = False

_PKG_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_REPO_ROOT = os.path.dirname(_PKG_ROOT)


def _site(depth_hint: int = 2) -> str:
    """``file:line`` of the nearest caller frame outside this module."""
    frame = sys._getframe(depth_hint)
    while frame is not None and frame.f_code.co_filename == __file__:
        frame = frame.f_back
    if frame is None:  # pragma: no cover - interpreter teardown only
        return "<unknown>:0"
    fname = frame.f_code.co_filename
    try:
        rel = os.path.relpath(fname, _REPO_ROOT)
    except ValueError:  # pragma: no cover - different drive on win32
        rel = fname
    if rel.startswith(".."):
        rel = os.path.basename(fname)
    return "%s:%d" % (rel.replace(os.sep, "/"), frame.f_lineno)


def _stack() -> List[str]:
    st = getattr(_held, "stack", None)
    if st is None:
        st = _held.stack = []
    return st


def _record(node: str) -> None:
    """Witness an acquisition of ``node`` with the current held set."""
    st = _stack()
    site = _site(3)
    if st:
        with _graph_lock:
            for outer in st:
                if outer != node and (outer, node) not in _edges:
                    _edges[(outer, node)] = (
                        threading.current_thread().name, site)
    st.append(node)


def _forget(node: str) -> None:
    st = _stack()
    # release order need not mirror acquire order; drop the most
    # recent occurrence so nested re-acquisitions unwind correctly
    for i in range(len(st) - 1, -1, -1):
        if st[i] == node:
            del st[i]
            return


class _WitnessLock:
    """Recording proxy over a real ``Lock``/``RLock``.

    One class serves both: ``reentrant`` switches the inner primitive
    and whether repeated acquisition by the owner is an ordering event.
    """

    __slots__ = ("_inner", "_node", "_reentrant", "_owner", "_count")

    def __init__(self, reentrant: bool, node: Optional[str] = None):
        self._inner = _REAL_RLOCK() if reentrant else _REAL_LOCK()
        self._node = node or _site()
        self._reentrant = reentrant
        self._owner: Optional[int] = None
        self._count = 0

    # -- core lock protocol -------------------------------------------
    def acquire(self, blocking: bool = True, timeout: float = -1):
        me = threading.get_ident()
        if self._reentrant and self._owner == me:
            self._inner.acquire(blocking, timeout)
            self._count += 1
            return True
        got = self._inner.acquire(blocking, timeout)
        if got:
            self._owner = me
            self._count = 1
            _record(self._node)
        return got

    def release(self) -> None:
        if self._owner != threading.get_ident():
            # let the real primitive raise its canonical error
            self._inner.release()
            return
        self._count -= 1
        if self._count == 0:
            self._owner = None
            _forget(self._node)
        self._inner.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        return self._count > 0

    def _at_fork_reinit(self) -> None:
        # fork-safety protocol (concurrent.futures registers this with
        # os.register_at_fork): the child gets a fresh, unheld lock
        self._inner._at_fork_reinit()
        self._owner = None
        self._count = 0

    # -- Condition protocol -------------------------------------------
    def _is_owned(self) -> bool:
        return self._owner == threading.get_ident()

    def _release_save(self):
        # Condition.wait: fully release (all recursion levels) and
        # remember how deep we were. The lock leaves the held stack.
        count, self._count = self._count, 0
        self._owner = None
        _forget(self._node)
        if self._reentrant:
            for _ in range(count):
                self._inner.release()
        else:
            self._inner.release()
        return count

    def _acquire_restore(self, count) -> None:
        # Re-acquiring after a wait is a resume, not a nesting decision:
        # restore the held stack without recording edges.
        if self._reentrant:
            for _ in range(count):
                self._inner.acquire()
        else:
            self._inner.acquire()
        self._owner = threading.get_ident()
        self._count = count
        _stack().append(self._node)

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return "<witness %s %s held=%d>" % (
            "rlock" if self._reentrant else "lock", self._node, self._count)


def _make_lock():
    return _WitnessLock(reentrant=False)


def _make_rlock():
    return _WitnessLock(reentrant=True)


# -- public API -------------------------------------------------------

def install() -> None:
    """Patch ``threading.Lock``/``RLock`` to the recording wrappers.

    Locks created BEFORE install (module-level singletons, the test
    harness's own plumbing) stay unwrapped and invisible — the witness
    only sees locks born during the instrumented window, which is
    exactly the application locks the suites construct.
    """
    global _installed
    if _installed:
        return
    threading.Lock = _make_lock
    threading.RLock = _make_rlock
    _installed = True


def uninstall() -> None:
    """Restore the real factories (wrapped locks keep working)."""
    global _installed
    threading.Lock = _REAL_LOCK
    threading.RLock = _REAL_RLOCK
    _installed = False


def reset() -> None:
    """Drop the witnessed graph (between independent test sessions)."""
    with _graph_lock:
        _edges.clear()


def edges() -> Dict[Tuple[str, str], Tuple[str, str]]:
    """Snapshot of the witnessed graph: (outer, inner) -> (thread, site)."""
    with _graph_lock:
        return dict(_edges)


def cycles() -> List[List[str]]:
    """Cycles in the witnessed acquisition graph (deterministic order)."""
    snap = edges()
    adj: Dict[str, List[str]] = {}
    for (a, b) in snap:
        adj.setdefault(a, []).append(b)
        adj.setdefault(b, [])
    for v in adj.values():
        v.sort()
    found: List[List[str]] = []
    seen_keys = set()
    color: Dict[str, int] = {}  # 0 unvisited / 1 on stack / 2 done
    path: List[str] = []

    def visit(n: str) -> None:
        color[n] = 1
        path.append(n)
        for m in adj[n]:
            c = color.get(m, 0)
            if c == 0:
                visit(m)
            elif c == 1:
                cyc = path[path.index(m):] + [m]
                start = min(range(len(cyc) - 1), key=lambda i: cyc[i])
                norm = cyc[start:-1] + cyc[:start] + [cyc[start]]
                key = tuple(norm)
                if key not in seen_keys:
                    seen_keys.add(key)
                    found.append(norm)
        path.pop()
        color[n] = 2

    for n in sorted(adj):
        if color.get(n, 0) == 0:
            visit(n)
    return found


def assert_acyclic() -> None:
    """Raise ``AssertionError`` naming the cycle if any order inverted."""
    bad = cycles()
    if not bad:
        return
    snap = edges()
    lines = ["lock-order witness: cyclic acquisition order observed"]
    for cyc in bad:
        lines.append("  cycle: " + " -> ".join(cyc))
        for a, b in zip(cyc, cyc[1:]):
            thread, site = snap[(a, b)]
            lines.append(
                "    %s then %s  [thread %s at %s]" % (a, b, thread, site))
    raise AssertionError("\n".join(lines))
