"""graftlint rule catalog: the TPU-training failure modes worth machine-checking.

Each rule is a small static pass with a narrow jurisdiction (see the class
docstrings for exactly what is and is not flagged — precision beats recall
here: a lint that cries wolf gets suppressed wholesale). The registry at the
bottom is what the CLI and the test suite enumerate.
"""
from __future__ import annotations

import ast
import fnmatch
import token
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

from .engine import (FileContext, Rule, Violation, dotted_name, is_literal,
                     walk_functions)


def register(cls):
    REGISTRY[cls.name] = cls
    return cls


REGISTRY: Dict[str, type] = {}


# ---------------------------------------------------------------------------
@register
class BarePrint(Rule):
    """No bare ``print(`` in library code (tokenize-based, so strings and
    docstrings mentioning print don't false-positive).

    Library output must flow through logging or the listener pipeline so it
    is routable and rate-limitable — and so bench.py's one-JSON-line stdout
    contract can't be broken by a stray debug print. CLI entry points are
    scoped out: their stdout IS the product.
    """

    name = "bare-print"
    description = ("bare print() in library code; use logging or a "
                   "listener (stdout is bench.py's JSON channel)")
    exclude = ("*/deeplearning4j_tpu/cli.py",
               "*/deeplearning4j_tpu/lint/__main__.py")

    def check(self, ctx: FileContext) -> Iterator[Violation]:
        toks = ctx.tokens
        for i, t in enumerate(toks):
            if t.type != token.NAME or t.string != "print":
                continue
            # skip attribute access (x.print) and keyword-arg (print=...)
            if i and toks[i - 1].type == token.OP and \
                    toks[i - 1].string == ".":
                continue
            nxt = next((n for n in toks[i + 1:]
                        if n.type not in (token.NL, token.NEWLINE,
                                          token.COMMENT)), None)
            if nxt is not None and nxt.type == token.OP and nxt.string == "(":
                yield self.violation(
                    ctx, t.start[0],
                    "bare print() in library code (use logging or a "
                    "listener)")


# ---------------------------------------------------------------------------
#: function names treated as hot-path (fit loops / jit dispatch seams).
#: Nested defs inherit hotness: staging closures defined inside a fit loop
#: run per batch on the producer thread.
_HOT_EXACT = frozenset({"fit", "fit_iterator", "execute_training"})
_HOT_PREFIXES = ("_fit", "_dispatch")
_HOT_SUFFIXES = ("_step",)


def _is_hot_name(name: str) -> bool:
    return (name in _HOT_EXACT
            or any(name.startswith(p) for p in _HOT_PREFIXES)
            or any(name.endswith(s) for s in _HOT_SUFFIXES))


@register
class HostSyncInHotLoop(Rule):
    """No host<->device synchronization inside fit/step/dispatch code paths.

    ``float(loss)``, ``.item()``, ``np.asarray(device_array)``,
    ``block_until_ready()`` and ``jax.device_get`` each block the host on
    the device stream — behind a network-attached TPU relay that is a full
    round-trip per call, and it serializes the dispatch pipeline the K-step
    and prefetch machinery exist to keep full. The ONE trusted sync point is
    ``LazyScore.score_value`` (cached, listener-driven, measured by
    telemetry); everything else in a hot path must stay device-resident.

    Host-side staging of *iterator* output (numpy in, numpy out) is the
    documented exception — suppress those lines with the reason spelling
    out why no device array can reach them.
    """

    name = "host-sync-in-hot-loop"
    description = ("host/device sync (float/.item/np.asarray/"
                   "block_until_ready) inside a fit/step/dispatch path")

    _SYNC_ATTRS = ("item", "block_until_ready")
    _SYNC_DOTTED = ("np.asarray", "numpy.asarray", "jax.device_get")

    def check(self, ctx: FileContext) -> Iterator[Violation]:
        tree = ctx.tree
        if tree is None:
            return
        for fn in walk_functions(tree):
            if not _is_hot_name(fn.name):
                continue
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                msg = self._sync_call(node)
                if msg:
                    yield self.violation(
                        ctx, node.lineno,
                        f"{msg} inside hot path {fn.name!r} — keep the hot "
                        "loop device-resident (trusted sync point: "
                        "LazyScore.score_value)")

    def _sync_call(self, call: ast.Call) -> Optional[str]:
        f = call.func
        if isinstance(f, ast.Name) and f.id == "float":
            if call.args and not is_literal(call.args[0]):
                return "float() host round-trip"
            return None
        if isinstance(f, ast.Attribute) and f.attr in self._SYNC_ATTRS \
                and not call.args:
            return f".{f.attr}() device sync"
        d = dotted_name(f)
        if d in self._SYNC_DOTTED:
            return f"{d}() host materialization"
        return None


# ---------------------------------------------------------------------------
#: name globs for functions that run under jax tracing by convention even
#: when the jit wrapping happens elsewhere (factory-returned step functions,
#: shard_map bodies). Factories themselves (make_*/_make_*) are host code.
_TRACED_NAME_GLOBS = ("*_step", "*_sharded", "*_local")
_FACTORY_PREFIXES = ("make_", "_make_")


class _TracedFunctions(ast.NodeVisitor):
    """Collect functions that (statically) run under jax tracing in a module:
    decorated with jax.jit / partial(jax.jit, ...), wrapped by name in a
    ``x = jax.jit(f, ...)`` assignment, or matching the step/shard-map
    naming convention."""

    def __init__(self, methods: Optional[Set[ast.AST]] = None):
        self.defs: Dict[str, List[ast.AST]] = {}
        self.traced: Set[ast.AST] = set()
        #: direct class-body function defs — host-side APIs like
        #: rnn_time_step, exempt from the *_step naming convention (a
        #: function nested INSIDE a method is still eligible: factory
        #: methods build trace bodies)
        self._methods = methods or set()

    @staticmethod
    def _is_jit_expr(node: ast.AST) -> bool:
        d = dotted_name(node)
        if d in ("jax.jit", "jit", "pjit", "jax.pjit"):
            return True
        if isinstance(node, ast.Call):
            # partial(jax.jit, ...) / functools.partial(jax.jit, ...)
            fd = dotted_name(node.func)
            if fd in ("functools.partial", "partial") and node.args:
                return _TracedFunctions._is_jit_expr(node.args[0])
            return _TracedFunctions._is_jit_expr(node.func)
        return False

    def visit_FunctionDef(self, node: ast.FunctionDef):
        self.defs.setdefault(node.name, []).append(node)
        if any(self._is_jit_expr(dec) for dec in node.decorator_list):
            self.traced.add(node)
        elif (node not in self._methods
              and not any(node.name.startswith(p)
                          for p in _FACTORY_PREFIXES)
              and any(fnmatch.fnmatch(node.name, g)
                      for g in _TRACED_NAME_GLOBS)):
            self.traced.add(node)
        self.generic_visit(node)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Assign(self, node: ast.Assign):
        # x = jax.jit(f, ...) marks the def of f (same module) as traced
        v = node.value
        if isinstance(v, ast.Call) and self._is_jit_expr(v.func) and v.args:
            inner = v.args[0]
            if isinstance(inner, ast.Name):
                for d in self.defs.get(inner.id, ()):
                    self.traced.add(d)
        self.generic_visit(node)


@register
class RecompileHazard(Rule):
    """Patterns inside jit-traced functions that cause avoidable retraces
    (or silent constant rebuilds) on TPU:

    * ``jnp.array(<python literal>)`` / ``jnp.asarray(<literal>)`` — the
      constant is re-materialized and re-staged on every trace; hoist it to
      module scope (or keep it a Python scalar and let weak types work).
    * Python ``if`` branching on trace-time shapes (``.shape`` / ``.ndim``,
      directly or through locally shape-derived names) — every distinct
      shape takes a different branch and therefore a different compile.
      Intentional shape *specialization* (static guards that raise, fixed
      chunking) is fine — suppress with the reason naming the invariant.
    * Mutable (list/dict/set) parameter defaults on a traced function —
      non-hashable under ``static_argnums`` and aliased across traces.

    Traced functions are found statically: ``@jax.jit`` (bare or through
    ``partial``), ``x = jax.jit(f)`` same-module wrapping, and the framework
    naming convention for factory-built step functions and shard_map bodies
    (``*_step``, ``*_sharded``, ``*_local``).
    """

    name = "recompile-hazard"
    description = ("trace-unstable pattern (literal jnp.array, shape "
                   "branching, mutable default) inside a jitted function")

    _ARRAY_CTORS = ("jnp.array", "jnp.asarray", "jax.numpy.array",
                    "jax.numpy.asarray")

    def check(self, ctx: FileContext) -> Iterator[Violation]:
        tree = ctx.tree
        if tree is None:
            return
        methods = {f for c in ast.walk(tree) if isinstance(c, ast.ClassDef)
                   for f in c.body
                   if isinstance(f, (ast.FunctionDef, ast.AsyncFunctionDef))}
        finder = _TracedFunctions(methods)
        finder.visit(tree)
        for fn in sorted(finder.traced, key=lambda f: f.lineno):
            yield from self._check_traced(ctx, fn)

    def _check_traced(self, ctx: FileContext, fn) -> Iterator[Violation]:
        # mutable defaults on the traced signature
        for default in list(fn.args.defaults) + \
                [d for d in fn.args.kw_defaults if d is not None]:
            if isinstance(default, (ast.List, ast.Dict, ast.Set,
                                    ast.ListComp, ast.DictComp,
                                    ast.SetComp)) or (
                    isinstance(default, ast.Call)
                    and dotted_name(default.func) in ("list", "dict", "set")):
                yield self.violation(
                    ctx, default.lineno,
                    f"mutable default on traced function {fn.name!r} — "
                    "non-hashable under static_argnums and shared across "
                    "traces")
        tainted = self._shape_tainted(fn)
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                d = dotted_name(node.func)
                if d in self._ARRAY_CTORS and node.args \
                        and is_literal(node.args[0]):
                    yield self.violation(
                        ctx, node.lineno,
                        f"{d}() on a Python literal inside traced "
                        f"{fn.name!r} — re-materialized every trace; hoist "
                        "to module scope")
            elif isinstance(node, ast.If):
                if self._mentions_shape(node.test, tainted):
                    yield self.violation(
                        ctx, node.lineno,
                        f"Python branch on trace-time shape inside traced "
                        f"{fn.name!r} — each distinct shape recompiles")

    @staticmethod
    def _shape_tainted(fn) -> Set[str]:
        """Names assigned (transitively) from ``.shape``/``.ndim`` inside
        the function — cheap fixpoint, function-local only."""
        tainted: Set[str] = set()

        def expr_tainted(e: ast.AST) -> bool:
            for n in ast.walk(e):
                if isinstance(n, ast.Attribute) and n.attr in ("shape",
                                                               "ndim"):
                    return True
                if isinstance(n, ast.Name) and n.id in tainted:
                    return True
            return False

        def target_names(t: ast.AST):
            # plain local names only: tainting `self` through an attribute
            # target would smear taint over every method attribute read
            if isinstance(t, ast.Name):
                yield t.id
            elif isinstance(t, (ast.Tuple, ast.List)):
                for e in t.elts:
                    yield from target_names(e)

        changed = True
        while changed:
            changed = False
            for node in ast.walk(fn):
                if isinstance(node, ast.Assign) and expr_tainted(node.value):
                    for t in node.targets:
                        for name in target_names(t):
                            if name not in tainted:
                                tainted.add(name)
                                changed = True
        return tainted

    @staticmethod
    def _mentions_shape(test: ast.AST, tainted: Set[str]) -> bool:
        for n in ast.walk(test):
            if isinstance(n, ast.Attribute) and n.attr in ("shape", "ndim"):
                return True
            if isinstance(n, ast.Name) and n.id in tainted:
                return True
        return False


# ---------------------------------------------------------------------------
@register
class DonationAlias(Rule):
    """No reuse of a buffer after passing it to a donating jit seam.

    ``donate_argnums`` lets XLA update parameters in place (no 2x-params HBM
    spike per step), at the price that the Python-side array is consumed at
    the call — later reads hit a deleted buffer (loud on TPU, silently *not*
    donated on CPU, so tests won't catch it). The safe idiom is rebinding
    the donated names from the call's results in the same statement:
    ``params, ... = step(params, ...)``.

    Donating seams are found statically in each module: ``jax.jit(f,
    donate_argnums=...)`` assignments, ``@partial(jax.jit,
    donate_argnums=...)`` decorators, and the framework's
    ``self._jit(name, fn, donate=...)`` cache (nn/multilayer.py).
    """

    name = "donation-alias"
    description = ("argument used again after being passed at a donated "
                   "position of a donating jit seam")

    @staticmethod
    def _donated_positions(kw_value: ast.AST) -> Tuple[int, ...]:
        if isinstance(kw_value, ast.Constant) and \
                isinstance(kw_value.value, int):
            return (kw_value.value,)
        if isinstance(kw_value, (ast.Tuple, ast.List)):
            out = []
            for e in kw_value.elts:
                if isinstance(e, ast.Constant) and isinstance(e.value, int):
                    out.append(e.value)
            return tuple(out)
        return ()

    def _donating_callables(self, tree: ast.Module) -> Dict[str, Tuple[int, ...]]:
        """Map local callable name -> donated positional indices."""
        seams: Dict[str, Tuple[int, ...]] = {}

        def jit_donation(call: ast.Call) -> Tuple[int, ...]:
            d = dotted_name(call.func)
            if d in ("jax.jit", "jit", "functools.partial", "partial"):
                for kw in call.keywords:
                    if kw.arg in ("donate_argnums", "donate") and kw.value:
                        return self._donated_positions(kw.value)
            return ()

        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    if isinstance(dec, ast.Call):
                        pos = jit_donation(dec)
                        if pos:
                            seams[node.name] = pos
            elif isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Call):
                call = node.value
                pos = jit_donation(call)
                if not pos:
                    # self._jit("name", fn, donate=(0, 1, 2))
                    d = dotted_name(call.func)
                    if d is not None and d.split(".")[-1] == "_jit":
                        for kw in call.keywords:
                            if kw.arg == "donate" and kw.value is not None:
                                pos = self._donated_positions(kw.value)
                if pos:
                    for t in node.targets:
                        td = dotted_name(t)
                        if td:
                            seams[td.split(".")[-1]] = pos
        return seams

    def check(self, ctx: FileContext) -> Iterator[Violation]:
        tree = ctx.tree
        if tree is None:
            return
        seams = self._donating_callables(tree)
        if not seams:
            return
        for fn in walk_functions(tree):
            yield from self._check_fn(ctx, fn, seams)

    def _check_fn(self, ctx, fn, seams) -> Iterator[Violation]:
        # statement-level walk so a donated name rebound by the call's own
        # assignment (the safe idiom) is not flagged
        calls: List[Tuple[ast.Call, str, List[str]]] = []
        for stmt in ast.walk(fn):
            if not isinstance(stmt, (ast.Assign, ast.Expr, ast.AugAssign,
                                     ast.AnnAssign, ast.Return)):
                continue
            value = getattr(stmt, "value", None)
            if not isinstance(value, ast.Call):
                continue
            callee = dotted_name(value.func)
            if callee is None:
                continue
            short = callee.split(".")[-1]
            if short not in seams:
                continue
            donated = [dotted_name(value.args[i])
                       for i in seams[short] if i < len(value.args)]
            donated = [d for d in donated if d]
            if isinstance(stmt, ast.Assign):
                bound: Set[str] = set()
                for t in stmt.targets:
                    for n in ast.walk(t):
                        d = dotted_name(n)
                        if d:
                            bound.add(d)
                donated = [d for d in donated if d not in bound]
            if donated:
                calls.append((value, fn.name, donated))
        for call, fname, donated in calls:
            end = getattr(call, "end_lineno", call.lineno)
            rebound_at: Dict[str, int] = {}
            for node in ast.walk(fn):
                if isinstance(node, ast.Assign) and node.lineno > end:
                    for t in node.targets:
                        for n in ast.walk(t):
                            d = dotted_name(n)
                            if d in donated:
                                rebound_at[d] = min(
                                    rebound_at.get(d, node.lineno),
                                    node.lineno)
            for node in ast.walk(fn):
                if isinstance(node, (ast.Name, ast.Attribute)) and \
                        isinstance(getattr(node, "ctx", None), ast.Load):
                    d = dotted_name(node)
                    if d in donated and node.lineno > end and \
                            node.lineno < rebound_at.get(d, 10 ** 9):
                        yield self.violation(
                            ctx, node.lineno,
                            f"{d!r} is read after being donated to a jit "
                            f"seam in {fname!r} — the buffer is consumed "
                            "at the call (deleted-buffer error on TPU, "
                            "silent on CPU); rebind it from the call's "
                            "results")
                        break


# ---------------------------------------------------------------------------
@register
class UnseededRng(Rule):
    """Library code must not draw from process-global RNG state.

    ``np.random.*`` module functions and stdlib ``random.*`` share hidden
    global state: results depend on import order and thread timing, which
    breaks the prefetch-on/off bit-identical-params guarantee and makes
    multi-host runs diverge. Use ``np.random.default_rng(seed)`` (seeded!)
    or JAX PRNG keys. ``default_rng()`` / ``RandomState()`` with no seed is
    flagged too — a fresh OS-entropy generator is still nondeterministic.
    """

    name = "unseeded-rng"
    description = ("global/unseeded RNG (np.random.* module call or stdlib "
                   "random.*) in library code")

    _NP_CONSTRUCTORS = frozenset({"default_rng", "RandomState", "Generator",
                                  "SeedSequence", "PCG64", "PCG64DXSM",
                                  "Philox", "MT19937", "SFC64",
                                  "BitGenerator"})
    _PY_CONSTRUCTORS = frozenset({"Random", "SystemRandom"})

    def check(self, ctx: FileContext) -> Iterator[Violation]:
        tree = ctx.tree
        if tree is None:
            return
        rand_aliases, from_random = self._random_bindings(tree)
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            d = dotted_name(node.func)
            if d is None:
                continue
            parts = d.split(".")
            if len(parts) >= 3 and parts[-2] == "random" and \
                    parts[0] in ("np", "numpy", "jax"):
                if parts[0] == "jax":
                    continue  # jax.random.* is explicit-key by construction
                last = parts[-1]
                if last not in self._NP_CONSTRUCTORS:
                    yield self.violation(
                        ctx, node.lineno,
                        f"{d}() draws from numpy's global RNG — use a "
                        "seeded np.random.default_rng(seed) Generator or a "
                        "JAX PRNG key")
                elif last in ("default_rng", "RandomState") and \
                        not node.args:
                    yield self.violation(
                        ctx, node.lineno,
                        f"{d}() with no seed — OS-entropy generator breaks "
                        "run-to-run determinism; thread a seed through")
            elif len(parts) == 2 and parts[0] in rand_aliases:
                last = parts[-1]
                if last not in self._PY_CONSTRUCTORS:
                    yield self.violation(
                        ctx, node.lineno,
                        f"stdlib {d}() uses hidden global RNG state — use "
                        "random.Random(seed) or a numpy Generator")
            elif len(parts) == 1 and parts[0] in from_random:
                yield self.violation(
                    ctx, node.lineno,
                    f"{parts[0]}() (imported from stdlib random) uses "
                    "hidden global RNG state — use random.Random(seed)")

    @staticmethod
    def _random_bindings(tree: ast.Module) -> Tuple[Set[str], Set[str]]:
        aliases: Set[str] = set()
        names: Set[str] = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.name == "random":
                        aliases.add(a.asname or "random")
            elif isinstance(node, ast.ImportFrom) and node.module == \
                    "random" and node.level == 0:
                for a in node.names:
                    if a.name not in UnseededRng._PY_CONSTRUCTORS:
                        names.add(a.asname or a.name)
        return aliases, names


# ---------------------------------------------------------------------------
@register
class MetricNameDrift(Rule):
    """Telemetry metric names are API: dashboards, the /metrics scraper and
    bench.py's log reinterpretation all key on them. Every name must (a)
    carry the ``dl4j_`` namespace prefix and (b) live as a constant in
    ``observability/names.py`` — registry call sites import the constant,
    so a rename is one diff line and grep-able, and two subsystems can't
    silently claim the same string with different meanings.

    Flagged at ``<receiver>.counter|gauge|histogram(<name>, ...)`` call
    sites: string literals (hardcoded name — import the constant instead),
    constants imported from the names module that the module doesn't define
    (stale import), and — inside names.py itself — constant values missing
    the ``dl4j_`` prefix. Receivers named np/numpy/jnp are ignored
    (``np.histogram`` is not a metrics registry), as are first arguments
    whose provenance the linter can't see (plain locals); the names-module
    import is the reviewable idiom.
    """

    name = "metric-name-drift"
    description = ("metric name not a dl4j_-prefixed constant imported "
                   "from observability/names.py")

    _METHODS = ("counter", "gauge", "histogram")
    _SKIP_RECEIVERS = frozenset({"np", "numpy", "jnp", "scipy", "cv2"})
    _NAMES_GLOB = "*/observability/names.py"

    def __init__(self, names: Optional[Dict[str, str]] = None):
        #: constant name -> metric string, parsed from the names module
        self._names = names
        self._names_found = names is not None

    # ------------------------------------------------------------- prepare
    def prepare(self, ctxs: Sequence[FileContext]) -> None:
        if self._names_found:
            return
        for ctx in ctxs:
            if fnmatch.fnmatch(ctx.path.as_posix(), self._NAMES_GLOB):
                self._names = self._parse_names(ctx)
                self._names_found = True
                return

    @staticmethod
    def _parse_names(ctx: FileContext) -> Dict[str, str]:
        out: Dict[str, str] = {}
        tree = ctx.tree
        if tree is None:
            return out
        for node in tree.body:
            if isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Constant) and \
                    isinstance(node.value.value, str):
                for t in node.targets:
                    if isinstance(t, ast.Name) and not t.id.startswith("_"):
                        out[t.id] = node.value.value
        return out

    # --------------------------------------------------------------- check
    def check(self, ctx: FileContext) -> Iterator[Violation]:
        tree = ctx.tree
        if tree is None:
            return
        if fnmatch.fnmatch(ctx.path.as_posix(), self._NAMES_GLOB):
            yield from self._check_names_module(ctx, tree)
            return
        imported, module_aliases = self._names_imports(tree)
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call) or \
                    not isinstance(node.func, ast.Attribute):
                continue
            if node.func.attr not in self._METHODS or not node.args:
                continue
            recv = dotted_name(node.func.value)
            if recv is not None and \
                    recv.split(".")[0] in self._SKIP_RECEIVERS:
                continue
            arg0 = node.args[0]
            if isinstance(arg0, ast.Constant) and \
                    isinstance(arg0.value, str):
                yield from self._check_literal(ctx, node, arg0.value)
            elif isinstance(arg0, ast.Name) and arg0.id in imported:
                orig = imported[arg0.id]
                if self._names is not None and orig not in self._names:
                    yield self.violation(
                        ctx, node.lineno,
                        f"metric constant {orig!r} is imported from "
                        "observability.names but not defined there")
            elif isinstance(arg0, ast.Attribute):
                d = dotted_name(arg0.value)
                if d in module_aliases and self._names is not None and \
                        arg0.attr not in self._names:
                    yield self.violation(
                        ctx, node.lineno,
                        f"metric constant names.{arg0.attr} is not defined "
                        "in observability/names.py")

    def _check_literal(self, ctx, node, value: str) -> Iterator[Violation]:
        if not value.startswith("dl4j_"):
            yield self.violation(
                ctx, node.lineno,
                f"metric name {value!r} lacks the dl4j_ namespace prefix "
                "(/metrics stability contract)")
            return
        hint = ""
        if self._names is not None:
            const = next((k for k, v in self._names.items() if v == value),
                         None)
            hint = (f" (import {const} from observability.names)"
                    if const else " (register it in observability/names.py "
                    "first)")
        yield self.violation(
            ctx, node.lineno,
            f"hardcoded metric name {value!r} at a registry call site — "
            f"use the central constant{hint}")

    def _check_names_module(self, ctx, tree) -> Iterator[Violation]:
        for const, value in self._parse_names(ctx).items():
            if not value.startswith("dl4j_"):
                node_line = next(
                    (n.lineno for n in tree.body
                     if isinstance(n, ast.Assign) and any(
                         isinstance(t, ast.Name) and t.id == const
                         for t in n.targets)), 1)
                yield self.violation(
                    ctx, node_line,
                    f"registered metric {const} = {value!r} lacks the "
                    "dl4j_ namespace prefix")

    @staticmethod
    def _names_imports(tree: ast.Module) -> Tuple[Dict[str, str], Set[str]]:
        """(local alias -> original constant name imported from the names
        module, local aliases bound to the names module itself)."""
        consts: Dict[str, str] = {}
        mods: Set[str] = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.ImportFrom):
                mod = node.module or ""
                if mod == "names" or mod.endswith(".names") or \
                        mod.endswith("observability.names") or \
                        (node.level > 0 and mod == "names"):
                    for a in node.names:
                        consts[a.asname or a.name] = a.name
                elif mod.endswith("observability") or mod == "observability":
                    for a in node.names:
                        if a.name == "names":
                            mods.add(a.asname or "names")
            elif isinstance(node, ast.Import):
                for a in node.names:
                    if a.name.endswith("observability.names"):
                        mods.add(a.asname or a.name)
        return consts, mods


# ---------------------------------------------------------------------------
@register
class SwallowedException(Rule):
    """No silently-swallowed exceptions in library code.

    A bare ``except:`` catches KeyboardInterrupt/SystemExit and hides real
    bugs; an ``except X: pass`` with no logging erases the only evidence a
    fit/dispatch loop leaves when it mis-steps. Handlers that genuinely
    must stay silent (``__del__`` close guards, optional-API probes)
    document themselves with a suppression reason — which is the point.
    """

    name = "swallowed-exception"
    description = ("bare except, or handler whose entire body is `pass` "
                   "(exception evidence destroyed)")

    def check(self, ctx: FileContext) -> Iterator[Violation]:
        tree = ctx.tree
        if tree is None:
            return
        for node in ast.walk(tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if node.type is None:
                yield self.violation(
                    ctx, node.lineno,
                    "bare `except:` also catches KeyboardInterrupt/"
                    "SystemExit — name the exception type")
                continue
            if len(node.body) == 1 and isinstance(node.body[0], ast.Pass):
                yield self.violation(
                    ctx, node.lineno,
                    "exception swallowed with `pass` — log it (debug level "
                    "is fine) or suppress with the reason it must stay "
                    "silent")


# ---------------------------------------------------------------------------
@register
class AdhocSharding(Rule):
    """No ``NamedSharding(`` / ``PartitionSpec(`` / ``Mesh(`` construction
    outside the partition-rule engine (``parallel/partition.py`` +
    ``compile_seam.py``; ``Mesh`` additionally allows ``parallel/mesh.py``,
    its one constructor site).

    Hand-built shardings are how the framework ended up with four parallel
    fit paths that each wired their own layouts — and where the layout lives
    determines where it can be fixed. The engine is the one place layout
    decisions are made (rules -> specs), telemetered
    (``dl4j_sharding_spec_total``), and compile-tracked; call sites import
    ``partition.pspec`` for trace-level specs and
    ``partition.named_sharding``/``tree_shardings``/``device_put`` for
    placement, and build meshes through ``parallel.mesh.build_mesh``. That
    jurisdiction covers the serving tier too: a ReplicaSet's per-replica
    mesh slices and every sharded ``PredictFn`` pin route through the same
    engine as the fit paths. Jurisdiction: direct calls to the
    ``jax.sharding`` constructors (by from-import, alias, or dotted
    attribute). A staging path with a genuine reason to hand-place
    (datasets/prefetch producer threads) suppresses with that reason
    spelled out.
    """

    name = "adhoc-sharding"
    description = ("NamedSharding/PartitionSpec/Mesh constructed outside "
                   "parallel/partition.py + compile_seam.py + mesh.py (use "
                   "partition.pspec / partition.named_sharding / "
                   "mesh.build_mesh)")
    exclude = ("*/parallel/partition.py", "*/parallel/compile_seam.py")

    _CTORS = ("NamedSharding", "PartitionSpec", "Mesh")
    #: Mesh's one legitimate constructor site — NamedSharding/PartitionSpec
    #: stay forbidden there, so it is a per-ctor exclusion, not `exclude`
    _MESH_HOME = "parallel/mesh.py"
    _ORIGIN = "jax.sharding"

    def check(self, ctx: FileContext) -> Iterator[Violation]:
        tree = ctx.tree
        if tree is None:
            return
        # local names bound to the jax.sharding constructors by from-import
        # (incl. aliases like `PartitionSpec as P`), and module aliases that
        # can reach them as attributes (import jax / import jax.sharding)
        ctor_names: Dict[str, str] = {}
        mod_aliases: Set[str] = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.ImportFrom) and node.module == self._ORIGIN:
                for a in node.names:
                    if a.name in self._CTORS:
                        ctor_names[a.asname or a.name] = a.name
            elif isinstance(node, ast.Import):
                for a in node.names:
                    if a.name in ("jax", "jax.sharding"):
                        mod_aliases.add((a.asname or a.name).split(".")[0])
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            kind = None
            if isinstance(f, ast.Name) and f.id in ctor_names:
                kind = ctor_names[f.id]
            else:
                d = dotted_name(f)
                if d and "." in d:
                    head, leaf = d.split(".", 1)[0], d.rsplit(".", 1)[-1]
                    if leaf in self._CTORS and head in mod_aliases:
                        kind = leaf
            if kind == "Mesh" and str(ctx.path).replace(
                    "\\", "/").endswith(self._MESH_HOME):
                continue
            if kind:
                yield self.violation(
                    ctx, node.lineno,
                    f"ad-hoc {kind}() construction — layouts come from the "
                    "partition-rule engine (partition.pspec / "
                    "partition.named_sharding / mesh.build_mesh / "
                    "compile_seam.compile_step)")


# ---------------------------------------------------------------------------
@register
class AdhocJit(Rule):
    """No ``jax.jit(`` / ``pjit(`` outside the compile seams
    (``LazyScore._jit`` in ``nn/multilayer.py``,
    ``parallel/compile_seam.py``, ``nn/compile_cache.py``).

    A raw jit call site is a program the compile plane can't see: it is
    not policy-keyed (a dtype flip silently pins the first policy), not
    compile-tracked (storm detection and MFU go blind), and not warm-
    startable (the persistent executable cache never learns about it — a
    respawn or hot swap recompiles it from scratch every time). The seams
    exist so every program inherits all three. Call sites route through
    ``net._jit`` / ``compile_seam.compile_step`` /
    ``compile_cache.build_program``; a site with a genuine reason to stay
    raw (float64 gradient checks outside every policy) suppresses with
    that reason spelled out. Jurisdiction: direct calls by from-import,
    alias, or dotted attribute.
    """

    name = "adhoc-jit"
    description = ("jax.jit/pjit called outside nn/multilayer.py "
                   "(LazyScore._jit) + parallel/compile_seam.py + "
                   "nn/compile_cache.py (use net._jit / "
                   "compile_seam.compile_step / "
                   "compile_cache.build_program)")
    exclude = ("*/nn/multilayer.py", "*/parallel/compile_seam.py",
               "*/nn/compile_cache.py")

    _CTORS = ("jit", "pjit")
    _ORIGINS = ("jax", "jax.experimental.pjit")

    def check(self, ctx: FileContext) -> Iterator[Violation]:
        tree = ctx.tree
        if tree is None:
            return
        # local names bound by from-import (incl. aliases), and module
        # aliases that can reach jit/pjit as attributes
        ctor_names: Dict[str, str] = {}
        mod_aliases: Set[str] = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.ImportFrom) \
                    and node.module in self._ORIGINS:
                for a in node.names:
                    if a.name in self._CTORS:
                        ctor_names[a.asname or a.name] = a.name
            elif isinstance(node, ast.Import):
                for a in node.names:
                    if a.name in ("jax", "jax.experimental",
                                  "jax.experimental.pjit"):
                        mod_aliases.add((a.asname or a.name).split(".")[0])
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            kind = None
            if isinstance(f, ast.Name) and f.id in ctor_names:
                kind = ctor_names[f.id]
            else:
                d = dotted_name(f)
                if d and "." in d:
                    head, leaf = d.split(".", 1)[0], d.rsplit(".", 1)[-1]
                    if leaf in self._CTORS and head in mod_aliases:
                        kind = leaf
            if kind:
                yield self.violation(
                    ctx, node.lineno,
                    f"ad-hoc {kind}() — programs compile through the seams "
                    "(net._jit / compile_seam.compile_step / "
                    "compile_cache.build_program) so they are policy-keyed, "
                    "compile-tracked and warm-startable")


# ---------------------------------------------------------------------------
@register
class HotPathCopy(Rule):
    """No full-buffer copies on the host data plane.

    The wire codec and the shm transport exist so tensor bytes move as
    memoryviews (``sendmsg`` scatter-gather, seqlock slot reads) — a single
    ``.tobytes()`` or ``np.frombuffer(...).copy()`` on those paths silently
    re-introduces the per-batch memcpy the whole plane was built to remove,
    and it never shows up in ``dl4j_wire_copy_bytes_total`` because it
    happens outside the billed fallbacks. Jurisdiction is the data plane
    only: ``streaming/`` and ``parallel/ps_*``. Copies that are genuinely
    required (a pull-slot vector that outlives the slot's reuse window)
    suppress with the lifetime reason spelled out.
    """

    name = "hot-path-copy"
    description = ("`.tobytes()` or `np.frombuffer(...).copy()` on the host "
                   "data plane (streaming/ + parallel/ps_*) — keep tensor "
                   "bytes as memoryviews")

    _JURISDICTION = ("*/streaming/*.py", "*/parallel/ps_*.py")

    def _in_jurisdiction(self, ctx: FileContext) -> bool:
        paths = (ctx.rel, ctx.path.as_posix())
        return any(fnmatch.fnmatch(p, pat)
                   for p in paths for pat in self._JURISDICTION)

    def check(self, ctx: FileContext) -> Iterator[Violation]:
        tree = ctx.tree
        if tree is None or not self._in_jurisdiction(ctx):
            return
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call) or node.args or node.keywords:
                continue
            f = node.func
            if not isinstance(f, ast.Attribute):
                continue
            if f.attr == "tobytes":
                yield self.violation(
                    ctx, node.lineno,
                    ".tobytes() materialises a full copy — pass the "
                    "memoryview (wire._byteview / pack_arrays) instead")
            elif f.attr == "copy":
                # only the precise np.frombuffer(...).copy() shape: copying
                # a freshly-decoded view is the canonical accidental memcpy
                v = f.value
                if (isinstance(v, ast.Call)
                        and (dotted_name(v.func) or "").endswith("frombuffer")):
                    yield self.violation(
                        ctx, node.lineno,
                        "np.frombuffer(...).copy() defeats the zero-copy "
                        "decode — keep the view, or suppress with the "
                        "lifetime reason the copy is required")


# ---------------------------------------------------------------------------
@register
class DenseKvAlloc(Rule):
    """No raw dense KV allocation outside the page allocator.

    The paged memory plane works only if ``keras_server/paging.py`` is the
    ONE place that sizes decode KV memory: a stray
    ``jnp.zeros(..., max_context, ...)`` anywhere else in ``keras_server/``
    silently re-introduces the per-slot dense preallocation the plane
    deleted — it compiles, it is bitwise-correct, and it quietly halves the
    session count per byte. Jurisdiction is ``keras_server/`` only (training
    code allocates sequence-length buffers legitimately); the allocator
    module itself is scoped out. Host scheduling arrays (``np.zeros`` with
    no context dimension) are not flagged.
    """

    name = "dense-kv-alloc"
    description = ("jnp.zeros sized by max_context under keras_server/ — "
                   "decode KV memory is allocated ONLY by "
                   "keras_server/paging.py (alloc_dense_kv / "
                   "alloc_page_pool)")
    exclude = ("*/keras_server/paging.py",)

    _JURISDICTION = ("*/keras_server/*.py",)

    def _in_jurisdiction(self, ctx: FileContext) -> bool:
        paths = (ctx.rel, ctx.path.as_posix())
        return any(fnmatch.fnmatch(p, pat)
                   for p in paths for pat in self._JURISDICTION)

    @staticmethod
    def _mentions_max_context(node: ast.AST) -> bool:
        for sub in ast.walk(node):
            if isinstance(sub, ast.Name) and sub.id == "max_context":
                return True
            if isinstance(sub, ast.Attribute) and sub.attr == "max_context":
                return True
        return False

    def check(self, ctx: FileContext) -> Iterator[Violation]:
        tree = ctx.tree
        if tree is None or not self._in_jurisdiction(ctx):
            return
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func) or ""
            if not name.endswith(".zeros") or name.startswith("np."):
                continue
            if any(self._mentions_max_context(a)
                   for a in list(node.args)
                   + [kw.value for kw in node.keywords]):
                yield self.violation(
                    ctx, node.lineno,
                    "raw dense KV alloc (jnp.zeros sized by max_context) — "
                    "route through keras_server/paging.py so the paged "
                    "plane stays the only decode memory owner")


# ---------------------------------------------------------------------------
@register
class OrphanSpan(Rule):
    """Every manually-started trace span must be finishable on ALL exits.

    ``start_span()`` (observability/tracing.py) exists for cross-thread
    spans whose owner finishes them later — which is exactly how spans
    leak: a local span finished only on the happy path pins its whole
    trace in the store's live table until the leak guard evicts it, and
    the trace is lost. Jurisdiction is the request-path packages
    (``keras_server/``, ``nn/``, ``observability/``); the rule flags:

    - a BARE ``start_span(...)`` statement, or a method chain on it not
      ending in ``.finish()`` — the span is unreachable forever (chain
      ``.finish()`` for an instant span);
    - ``sp = start_span(...)`` into a plain local where ``sp.finish()``
      never appears inside a ``finally`` block of the same function and
      ``sp`` is not returned — a conditional/early-exit path leaks it;
    - a flight-recorder ``record("span_enter", ...)`` with no
      ``record("span_exit", ...)`` anywhere in the same function — the
      pairing ``span()`` guarantees would silently break in crash bundles.

    Assigning to an attribute (``req.span = start_span(...)``) is exempt:
    ownership escapes to the object and its lifecycle (the batcher's
    dispatcher, the decode pump's evict path) finishes it. ``with
    start_span(...)`` is exempt (``__exit__`` finishes). ``tracing.py``
    (the factory) and ``spans.py`` (the pairing owner) are scoped out.
    """

    name = "orphan-span"
    description = ("start_span()/span_enter without a guaranteed "
                   "finish/span_exit on all exits (leaked trace span)")
    exclude = ("*/observability/tracing.py",
               "*/observability/spans.py")

    _JURISDICTION = ("*/keras_server/*.py", "*/nn/*.py",
                     "*/observability/*.py")

    def _in_jurisdiction(self, ctx: FileContext) -> bool:
        paths = (ctx.rel, ctx.path.as_posix())
        return any(fnmatch.fnmatch(p, pat)
                   for p in paths for pat in self._JURISDICTION)

    @staticmethod
    def _own_nodes(fn: ast.AST) -> Iterator[ast.AST]:
        """Nodes of ``fn`` excluding nested function bodies (a closure's
        spans are the closure's problem)."""
        stack = list(ast.iter_child_nodes(fn))
        while stack:
            node = stack.pop()
            yield node
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                     ast.Lambda)):
                stack.extend(ast.iter_child_nodes(node))

    @staticmethod
    def _chain_root_tail(call: ast.Call) -> Tuple[ast.Call, Optional[str]]:
        """For ``start_span(...).set_status(...).finish()`` return the
        innermost call and the OUTERMOST chained method name (None when
        the call is unchained)."""
        tail: Optional[str] = None
        node = call
        while isinstance(node.func, ast.Attribute) \
                and isinstance(node.func.value, ast.Call):
            if tail is None:
                tail = node.func.attr
            node = node.func.value
        return node, tail

    @staticmethod
    def _is_start_span(call: ast.Call) -> bool:
        name = dotted_name(call.func) or ""
        return name == "start_span" or name.endswith(".start_span")

    @staticmethod
    def _record_event(call: ast.Call) -> Optional[str]:
        name = dotted_name(call.func) or ""
        if not (name == "record" or name.endswith(".record")):
            return None
        if call.args and isinstance(call.args[0], ast.Constant) \
                and isinstance(call.args[0].value, str):
            return call.args[0].value
        return None

    def check(self, ctx: FileContext) -> Iterator[Violation]:
        tree = ctx.tree
        if tree is None or not self._in_jurisdiction(ctx):
            return
        for fn in walk_functions(tree):
            nodes = list(self._own_nodes(fn))
            with_exprs = set()
            for node in nodes:
                if isinstance(node, (ast.With, ast.AsyncWith)):
                    for item in node.items:
                        with_exprs.add(id(item.context_expr))
            finished_in_finally: Set[str] = set()
            for node in nodes:
                if not isinstance(node, ast.Try):
                    continue
                for stmt in node.finalbody:
                    for sub in ast.walk(stmt):
                        if isinstance(sub, ast.Call) \
                                and isinstance(sub.func, ast.Attribute) \
                                and sub.func.attr == "finish" \
                                and isinstance(sub.func.value, ast.Name):
                            finished_in_finally.add(sub.func.value.id)
            returned: Set[str] = {
                node.value.id for node in nodes
                if isinstance(node, ast.Return)
                and isinstance(node.value, ast.Name)}
            enter_lines: List[int] = []
            has_exit = False
            for node in nodes:
                if not isinstance(node, ast.Call):
                    continue
                ev = self._record_event(node)
                if ev == "span_enter":
                    enter_lines.append(node.lineno)
                elif ev == "span_exit":
                    has_exit = True
                if not self._is_start_span(node) or id(node) in with_exprs:
                    continue
                sink = self._span_sink(nodes, node)
                if sink in ("attribute", "escapes", "finish-chain"):
                    continue
                if sink is None:
                    yield self.violation(
                        ctx, node.lineno,
                        "start_span() result discarded — the span can "
                        "never finish; chain .finish() or own it on an "
                        "object/local")
                    continue
                if sink in finished_in_finally or sink in returned:
                    continue
                yield self.violation(
                    ctx, node.lineno,
                    f"span {sink!r} from start_span() has no "
                    f"{sink}.finish() in a finally block (and is not "
                    "returned) — an exception path leaks the trace")
            for line in enter_lines if not has_exit else ():
                yield self.violation(
                    ctx, line,
                    'record("span_enter") without a matching '
                    'record("span_exit") in this function — the flight-'
                    "recorder span timeline would dangle")

    @staticmethod
    def _span_sink(nodes: List[ast.AST],
                   call: ast.Call) -> Optional[str]:
        """Where the span value lands: a local name, ``'attribute'`` /
        ``'escapes'`` for exempt sinks, ``'finish-chain'`` when a method
        chain on the call ends in ``.finish()``, None when discarded.
        Assignment/return sinks win over intermediate chain calls (the
        node list is unordered DFS output)."""
        for node in nodes:
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                v = node.value
                root = OrphanSpan._chain_root_tail(v)[0] \
                    if isinstance(v, ast.Call) else None
                if v is call or root is call:
                    t = node.targets[0]
                    return t.id if isinstance(t, ast.Name) else "attribute"
            if isinstance(node, ast.Return) and node.value is call:
                return "escapes"
        for node in nodes:
            if isinstance(node, ast.Call) and node is not call:
                inner, tail = OrphanSpan._chain_root_tail(node)
                if inner is call and tail == "finish":
                    # the OUTERMOST chained call reports the final method;
                    # any chain ending .finish() lands here
                    return "finish-chain"
        return None


# ---------------------------------------------------------------------------
@register
class ReplicaLifecycle(Rule):
    """Replica lifecycle mutations only through the ReplicaSet public API.

    The autoscaling fleet's invariants — a replica is routable only after
    its warmup completes, removal drains without loss, indices are never
    reused, the fleet gauge and scale-event counters stay truthful, leases
    are registered/deregistered in step — all live inside
    ``ReplicaSet.add_replica()`` / ``remove_replica()`` /
    ``register()``. Direct surgery on ``ReplicaSet._replicas`` from
    anywhere else (an append, a ``del``, even a read that is then
    mutated) silently bypasses every one of them: the router can see a
    cold replica, a drain can be skipped, a zombie's lease outlives its
    process. Any ``._replicas`` attribute access outside ``replica.py``
    is flagged — readers have the ``replicas`` property and
    ``n_replicas``; mutators have the lifecycle API.
    """

    name = "replica-lifecycle"
    description = ("direct ReplicaSet._replicas access outside "
                   "keras_server/replica.py — use the replicas property "
                   "to read and add_replica()/remove_replica() to mutate")
    exclude = ("*/keras_server/replica.py",)

    def check(self, ctx: FileContext) -> Iterator[Violation]:
        tree = ctx.tree
        if tree is None:
            return
        for node in ast.walk(tree):
            if isinstance(node, ast.Attribute) \
                    and node.attr == "_replicas":
                yield self.violation(
                    ctx, node.lineno,
                    "ReplicaSet._replicas touched outside replica.py — "
                    "read via the replicas property, mutate via "
                    "add_replica()/remove_replica() so warmup-before-"
                    "routable, drain-without-loss and lease accounting "
                    "hold")


# ---------------------------------------------------------------------------
@register
class FleetTruth(Rule):
    """A ``/fleet/*`` surface must serve the FEDERATED view, never a
    process-local registry read dressed up as fleet-wide truth.

    The whole point of observability/federation.py is that every other
    process's counters are invisible to a local ``MetricsRegistry``;
    handing ``global_registry().snapshot()`` (or ``.prometheus_text()``)
    to a fleet route silently reports one process as if it were the
    fleet — totals look plausible and are wrong, which is worse than
    absent. Flagged are local-registry ``snapshot()``/``prometheus_text()``
    calls in fleet scope: inside a function whose name contains ``fleet``,
    or inside an ``if``/``elif`` branch whose test compares against a
    string starting with ``/fleet`` (the route-dispatcher shape). The
    local ``/metrics`` branch of the same dispatcher stays legal.
    ``observability/federation.py`` is scoped out — it is the one module
    allowed to fold the local registry into the merged view (labeled).
    """

    name = "fleet-truth"
    description = ("process-local registry snapshot()/prometheus_text() "
                   "served from a /fleet surface — merge through "
                   "observability/federation.py instead")
    exclude = ("*/observability/federation.py",)

    _READS = ("snapshot", "prometheus_text")

    @staticmethod
    def _is_local_registry_read(call: ast.Call) -> bool:
        if not isinstance(call.func, ast.Attribute) \
                or call.func.attr not in FleetTruth._READS:
            return False
        base = call.func.value
        if isinstance(base, ast.Call):
            name = dotted_name(base.func) or ""
            return name == "global_registry" \
                or name.endswith(".global_registry")
        name = (dotted_name(base) or "").lower()
        leaf = name.rsplit(".", 1)[-1]
        # a receiver that names the federation is the fix, not the bug
        return "registry" in leaf and "fed" not in name \
            and "fleet" not in name

    @staticmethod
    def _mentions_fleet_route(node: ast.AST) -> bool:
        return any(isinstance(n, ast.Constant)
                   and isinstance(n.value, str)
                   and n.value.startswith("/fleet")
                   for n in ast.walk(node))

    def check(self, ctx: FileContext) -> Iterator[Violation]:
        tree = ctx.tree
        if tree is None:
            return
        flagged = set()

        def flag(scope_nodes, why):
            for node in scope_nodes:
                for call in ast.walk(node):
                    if isinstance(call, ast.Call) \
                            and self._is_local_registry_read(call) \
                            and call.lineno not in flagged:
                        flagged.add(call.lineno)
                        yield self.violation(
                            ctx, call.lineno,
                            f"process-local registry "
                            f".{call.func.attr}() {why} — one process's "
                            "series served as fleet truth; go through "
                            "FederatedRegistry / fleet_metrics_text() "
                            "(observability/federation.py)")

        for fn in walk_functions(tree):
            if "fleet" in fn.name.lower():
                yield from flag(fn.body,
                                f"inside fleet-scoped {fn.name}()")
                continue
            for node in ast.walk(fn):
                if isinstance(node, ast.If) \
                        and self._mentions_fleet_route(node.test):
                    yield from flag(node.body,
                                    "inside a /fleet route branch")


def default_rules() -> List[Rule]:
    """Fresh instances of every registered rule, in registration order."""
    return [cls() for cls in REGISTRY.values()]


def rule_names() -> List[str]:
    return list(REGISTRY)
