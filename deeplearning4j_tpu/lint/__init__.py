"""graftlint: JAX/TPU-aware static analysis for the framework's own code.

The rules encode hot-path invariants the profiles keep re-teaching: no host
syncs inside fit loops, no donated-buffer reuse, no recompile-triggering
patterns inside jit seams, no global RNG in library code, one central module
for telemetry metric names, no bare prints past bench.py's stdout contract,
no silently-swallowed exceptions.

    python -m deeplearning4j_tpu.lint deeplearning4j_tpu          # human
    python -m deeplearning4j_tpu.lint deeplearning4j_tpu --json   # for gates

Suppress a deliberate finding inline, reason required::

    x = np.asarray(batch)  # lint: host-sync-in-hot-loop-ok (host ndarray in)

See docs/GUIDE.md "Static analysis" for the rule catalog and how to add one.
"""
from __future__ import annotations

import pathlib
from typing import Iterable, Optional, Sequence

from .engine import (BAD_SUPPRESSION, FileContext, LintResult, Rule,
                     Suppression, Violation, run)
from .rules import REGISTRY, default_rules, rule_names
from . import concurrency as _concurrency  # noqa: F401 - registers rules

__all__ = [
    "BAD_SUPPRESSION", "FileContext", "LintResult", "Rule", "Suppression",
    "Violation", "REGISTRY", "default_rules", "rule_names", "rule_version",
    "rule_versions", "run", "run_paths",
]


def rule_version(name: str) -> str:
    """Short content hash of a rule's implementation source.

    Baseline suppressions record the version of the rule they silence;
    when a rule's code changes, its hash changes, and the gate forces a
    re-review of every suppression keyed to the old version — editing a
    rule must not leave stale suppressions silently trusted."""
    import hashlib
    import inspect

    src = inspect.getsource(REGISTRY[name])
    return hashlib.sha1(src.encode()).hexdigest()[:12]


def rule_versions() -> dict:
    """{rule name -> implementation hash} for the whole registry."""
    return {name: rule_version(name) for name in rule_names()}


def run_paths(paths: Sequence, rule_subset: Optional[Iterable[str]] = None,
              jobs: int = 1) -> LintResult:
    """Lint ``paths`` (files or package dirs) with the full registry, or
    with ``rule_subset`` names. Unknown names in the subset raise — a gate
    script must not silently run fewer checks than it was asked for.
    ``jobs`` > 1 fans the per-file check phase across worker processes
    (deterministic output at any N; see ``engine.run``)."""
    if rule_subset is None:
        rules = default_rules()
    else:
        unknown = [n for n in rule_subset if n not in REGISTRY]
        if unknown:
            raise ValueError(
                f"unknown rule(s) {unknown}; known: {rule_names()}")
        rules = [REGISTRY[n]() for n in rule_subset]
    return run([pathlib.Path(p) for p in paths], rules,
               known_rule_names=rule_names(), jobs=jobs)
