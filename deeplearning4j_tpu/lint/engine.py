"""graftlint core: file contexts, suppression parsing, rule base, runner.

The framework's hot-path invariants (no host syncs inside fit loops, no
donated-buffer reuse, no recompile-triggering captures inside jit seams, one
stdout contract for bench.py) were enforced by convention and rediscovered in
profiles when broken. graftlint machine-checks them: rules are small
AST/tokenize passes over library code, wired into the test suite and a CLI
(``python -m deeplearning4j_tpu.lint``).

Design choices worth stating:

* **Static only.** Rules never import the code under analysis — linting a
  broken tree must not execute it (and must work before jax is importable on
  a given host). Everything is ``ast`` + ``tokenize``.
* **Suppressions are loud.** ``# lint: <rule>-ok (reason)`` on the offending
  line (or a standalone comment on the line above). The reason is mandatory:
  a suppression without one is itself a violation (``bad-suppression``), as
  is a suppression naming an unknown rule — typos must not silently disable
  a check. Suppressed findings stay in the report (flagged), so the gate
  script can show when a diff adds new suppressions.
* **Per-rule path scoping.** A rule owns glob excludes (e.g. ``bare-print``
  skips the CLI entry points, whose stdout IS the product). Scoping is for
  whole files that are out of a rule's jurisdiction; single deliberate lines
  use suppressions, keeping the decision next to the code it covers.
"""
from __future__ import annotations

import ast
import dataclasses
import fnmatch
import io
import pathlib
import re
import token
import tokenize
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Tuple

#: the marker that introduces suppressions inside a comment
_SUPPRESS_INTRO = re.compile(r"#\s*lint:\s*(?P<body>.*)$")
#: one suppression: "<rule>-ok" optionally followed by "(reason)"
_SUPPRESS_MARKER = re.compile(
    r"(?P<rule>[a-z][a-z0-9]*(?:-[a-z0-9]+)*)-ok(?:\s*\((?P<reason>[^)]*)\))?")

#: rule id reserved for malformed/unknown suppressions (engine-level)
BAD_SUPPRESSION = "bad-suppression"


@dataclasses.dataclass(frozen=True)
class Violation:
    """One finding. ``path`` is scan-root-relative posix (stable across
    machines, so baselines diff cleanly)."""

    rule: str
    path: str
    line: int
    message: str
    snippet: str = ""
    suppressed: bool = False
    reason: str = ""

    def key(self) -> Tuple[str, int, str]:
        return (self.path, self.line, self.rule)

    def to_json(self) -> dict:
        d = {"rule": self.rule, "path": self.path, "line": self.line,
             "message": self.message}
        if self.snippet:
            d["snippet"] = self.snippet
        if self.suppressed:
            d["suppressed"] = True
            d["reason"] = self.reason
        return d

    def render(self) -> str:
        tag = " (suppressed: %s)" % self.reason if self.suppressed else ""
        loc = f"{self.path}:{self.line}"
        return f"{loc}: [{self.rule}] {self.message}{tag}"


@dataclasses.dataclass
class Suppression:
    rule: str
    reason: str
    line: int          # source line the marker sits on
    applies_to: int    # line the suppression covers


class FileContext:
    """Lazily-parsed view of one source file shared by every rule: raw text,
    token stream, AST, and the parsed suppression table."""

    def __init__(self, path: pathlib.Path, rel: str):
        self.path = path
        self.rel = rel
        self.text = path.read_text()
        self.lines = self.text.splitlines()
        self._tokens: Optional[List[tokenize.TokenInfo]] = None
        self._tree: Optional[ast.Module] = None
        self._tree_error: Optional[str] = None
        #: applies_to line -> {rule -> Suppression}
        self.suppressions: Dict[int, Dict[str, Suppression]] = {}
        #: suppressions with a missing reason (reported as bad-suppression)
        self.malformed: List[Suppression] = []
        self._parse_suppressions()

    # ------------------------------------------------------------ lazy parses
    @property
    def tokens(self) -> List[tokenize.TokenInfo]:
        if self._tokens is None:
            self._tokens = list(tokenize.generate_tokens(
                io.StringIO(self.text).readline))
        return self._tokens

    @property
    def tree(self) -> Optional[ast.Module]:
        if self._tree is None and self._tree_error is None:
            try:
                self._tree = ast.parse(self.text)
            except SyntaxError as e:  # surfaced by the runner, not swallowed
                self._tree_error = f"{self.rel}:{e.lineno}: {e.msg}"
        return self._tree

    @property
    def tree_error(self) -> Optional[str]:
        self.tree  # noqa: B018 - force the parse attempt
        return self._tree_error

    def line_at(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""

    # ---------------------------------------------------------- suppressions
    def _parse_suppressions(self) -> None:
        try:
            toks = self.tokens
        except (tokenize.TokenError, SyntaxError, IndentationError):
            return
        for i, t in enumerate(toks):
            if t.type != token.COMMENT:
                continue
            m = _SUPPRESS_INTRO.search(t.string)
            if m is None:
                continue
            standalone = t.line.lstrip().startswith("#")
            applies_to = t.start[0]
            if standalone:
                # standalone comment: covers the next code line (multi-line
                # statements get annotated above their first line)
                nxt = next((n for n in toks[i + 1:]
                            if n.type not in (token.NL, token.NEWLINE,
                                              token.COMMENT, token.INDENT,
                                              token.DEDENT)), None)
                if nxt is not None:
                    applies_to = nxt.start[0]
            body = m.group("body")
            found_any = False
            for sm in _SUPPRESS_MARKER.finditer(body):
                found_any = True
                reason = (sm.group("reason") or "").strip()
                sup = Suppression(sm.group("rule"), reason, t.start[0],
                                  applies_to)
                if not reason:
                    self.malformed.append(sup)
                    continue
                self.suppressions.setdefault(applies_to, {})[sup.rule] = sup
            if not found_any:
                # the intro marker with nothing parseable after it — flag it
                # rather than silently ignoring an intended suppression
                self.malformed.append(Suppression("", "", t.start[0],
                                                  applies_to))

    def suppression_for(self, rule: str, line: int) -> Optional[Suppression]:
        return self.suppressions.get(line, {}).get(rule)


class Rule:
    """Base rule: subclasses set ``name``/``description``, optionally
    ``exclude`` (fnmatch globs tested against the scan-relative posix path
    AND the absolute posix path), and implement ``check``."""

    name: str = ""
    description: str = ""
    exclude: Sequence[str] = ()

    def applies_to(self, ctx: FileContext) -> bool:
        full = self.exclude
        rel = ctx.rel
        ab = ctx.path.as_posix()
        return not any(fnmatch.fnmatch(rel, g) or fnmatch.fnmatch(ab, g)
                       for g in full)

    def prepare(self, ctxs: Sequence[FileContext]) -> None:
        """Called once per run with every file in scope, before ``check``.
        Cross-file rules (metric-name-drift reads the names module) hook in
        here; the default is stateless."""

    def check(self, ctx: FileContext) -> Iterator[Violation]:
        raise NotImplementedError

    def violation(self, ctx: FileContext, line: int, message: str) -> Violation:
        return Violation(self.name, ctx.rel, line, message,
                         snippet=ctx.line_at(line))


@dataclasses.dataclass
class LintResult:
    violations: List[Violation]       # unsuppressed — these fail the build
    suppressed: List[Violation]       # found but covered by a reasoned marker
    files_scanned: int
    errors: List[str]                 # syntax/read errors (also build-failing)

    @property
    def ok(self) -> bool:
        return not self.violations and not self.errors

    def to_json(self) -> dict:
        counts: Dict[str, int] = {}
        for v in self.violations:
            counts[v.rule] = counts.get(v.rule, 0) + 1
        return {
            "ok": self.ok,
            "files_scanned": self.files_scanned,
            "counts": counts,
            "violations": [v.to_json() for v in self.violations],
            "suppressed": [v.to_json() for v in self.suppressed],
            "errors": list(self.errors),
        }


def iter_py_files(paths: Sequence[pathlib.Path]) -> List[Tuple[pathlib.Path, str]]:
    """Expand files/dirs into sorted (path, scan-relative posix) pairs.
    Relative paths are taken against the argument's parent so a package dir
    argument yields ``pkgname/sub/mod.py`` — the baseline-stable form."""
    out: Dict[pathlib.Path, str] = {}
    for p in paths:
        p = pathlib.Path(p)
        if p.is_dir():
            base = p.resolve().parent
            for f in sorted(p.resolve().rglob("*.py")):
                out[f] = f.relative_to(base).as_posix()
        elif p.suffix == ".py":
            out[p.resolve()] = p.name
    return sorted(out.items(), key=lambda kv: kv[1])


def _check_ctx(ctx: FileContext, rules: Sequence[Rule],
               known: set) -> Tuple[List[Violation], List[Violation],
                                    List[str]]:
    """Run every rule over ONE file and resolve its suppressions.

    Pure per-file work — no shared mutable state — which is what lets the
    runner fan files out across worker processes (``jobs``)."""
    open_v: List[Violation] = []
    suppressed: List[Violation] = []
    errors: List[str] = []
    seen: set = set()
    for rule in rules:
        if not rule.applies_to(ctx):
            continue
        try:
            found = list(rule.check(ctx))
        except (SyntaxError, tokenize.TokenError, IndentationError):
            if ctx.tree_error and ctx.tree_error not in errors:
                errors.append(ctx.tree_error)
            continue
        for v in found:
            if v.key() in seen:
                continue
            seen.add(v.key())
            sup = ctx.suppression_for(v.rule, v.line)
            if sup is not None:
                suppressed.append(dataclasses.replace(
                    v, suppressed=True, reason=sup.reason))
            else:
                open_v.append(v)
    if ctx.tree_error and ctx.tree_error not in errors:
        errors.append(ctx.tree_error)
    # engine-level: malformed suppressions + unknown rule names
    for sup in ctx.malformed:
        what = (f"suppression {sup.rule!r}-ok is missing its required "
                "(reason)" if sup.rule else
                "'# lint:' comment with no parseable '<rule>-ok' marker")
        open_v.append(Violation(BAD_SUPPRESSION, ctx.rel, sup.line, what,
                                snippet=ctx.line_at(sup.line)))
    for by_rule in ctx.suppressions.values():
        for sup in by_rule.values():
            if sup.rule not in known and sup.rule != BAD_SUPPRESSION:
                open_v.append(Violation(
                    BAD_SUPPRESSION, ctx.rel, sup.line,
                    f"suppression names unknown rule {sup.rule!r} "
                    "(typo? see --list-rules)",
                    snippet=ctx.line_at(sup.line)))
    return open_v, suppressed, errors


#: (ctxs, rules, known) snapshot the forked pool workers inherit — set
#: immediately before the fork, cleared right after. Fork (not spawn) is
#: load-bearing: prepared cross-file rule state and parsed FileContexts
#: travel to the children by address-space copy, and only the picklable
#: Violation lists travel back.
_pool_state: Optional[tuple] = None


def _pool_check(i: int):
    ctxs, rules, known = _pool_state
    return _check_ctx(ctxs[i], rules, known)


def _fan_out(ctxs: Sequence[FileContext], rules: Sequence[Rule],
             known: set, jobs: int) -> Optional[List[tuple]]:
    """Per-file results in file order via a fork pool, or None when the
    platform can't fork (the caller falls back to the sequential path)."""
    import multiprocessing

    global _pool_state
    try:
        mp = multiprocessing.get_context("fork")
    except ValueError:  # pragma: no cover - no fork on this platform
        return None
    _pool_state = (ctxs, rules, known)
    try:
        with mp.Pool(min(jobs, len(ctxs))) as pool:
            # map (not imap_unordered) pins result order to file order, so
            # parallel output is byte-identical to sequential output
            return pool.map(_pool_check, range(len(ctxs)),
                            chunksize=max(1, len(ctxs) // (4 * jobs)))
    finally:
        _pool_state = None


def run(paths: Sequence[pathlib.Path], rules: Sequence[Rule],
        known_rule_names: Optional[Iterable[str]] = None,
        jobs: int = 1) -> LintResult:
    """Run ``rules`` over every .py under ``paths``; resolve suppressions.

    ``known_rule_names``: full registry (suppressions may name a rule that
    exists but isn't selected this run — that is not a typo).

    ``jobs``: worker processes for the per-file check phase. File parsing
    and ``prepare`` (the cross-file hooks) stay sequential in the parent —
    they build shared state — then the independent per-file checks fan out
    and merge back in file order, so results are deterministic at any N."""
    known = set(known_rule_names or ()) | {r.name for r in rules}
    files = iter_py_files(paths)
    ctxs: List[FileContext] = []
    errors: List[str] = []
    for path, rel in files:
        try:
            ctxs.append(FileContext(path, rel))
        except (OSError, UnicodeDecodeError, tokenize.TokenError) as e:
            errors.append(f"{rel}: unreadable: {e}")

    for rule in rules:
        rule.prepare(ctxs)

    per_file: Optional[List[tuple]] = None
    if jobs and jobs > 1 and len(ctxs) > 1:
        per_file = _fan_out(ctxs, rules, known, jobs)
    if per_file is None:
        per_file = [_check_ctx(ctx, rules, known) for ctx in ctxs]

    open_v: List[Violation] = []
    suppressed: List[Violation] = []
    for f_open, f_sup, f_err in per_file:
        open_v.extend(f_open)
        suppressed.extend(f_sup)
        for e in f_err:
            if e not in errors:
                errors.append(e)

    open_v.sort(key=lambda v: v.key())
    suppressed.sort(key=lambda v: v.key())
    return LintResult(open_v, suppressed, len(ctxs), errors)


# --------------------------------------------------------------- AST helpers
def dotted_name(node: ast.AST) -> Optional[str]:
    """'np.random.seed' for a Name/Attribute chain; None for anything else
    (calls, subscripts — chains through those are not static receivers)."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = dotted_name(node.value)
        return f"{base}.{node.attr}" if base else None
    return None


def is_literal(node: ast.AST) -> bool:
    """True for pure Python literals (including nested list/tuple/dict of
    literals) — the payloads jnp.array() re-materializes on every trace."""
    if isinstance(node, ast.Constant):
        return True
    if isinstance(node, (ast.List, ast.Tuple, ast.Set)):
        return all(is_literal(e) for e in node.elts)
    if isinstance(node, ast.Dict):
        return all(k is not None and is_literal(k) and is_literal(v)
                   for k, v in zip(node.keys, node.values))
    if isinstance(node, ast.UnaryOp) and isinstance(node.op,
                                                    (ast.USub, ast.UAdd)):
        return is_literal(node.operand)
    return False


def walk_functions(tree: ast.Module) -> Iterator[ast.AST]:
    """Every FunctionDef/AsyncFunctionDef in the module, any nesting."""
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node
