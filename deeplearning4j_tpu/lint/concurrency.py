"""graftlint concurrency plane: lockset inference, lock-order cycles,
blocking-under-lock, and thread lifecycle.

Four whole-scan rules over the package's threading discipline, in the
spirit of Eraser's lockset algorithm (Savage et al., 1997) and RacerX's
static lock-order pass (Engler & Ashcraft, 2003), scaled down to the
idioms this codebase actually uses: ``threading.Lock/RLock/Condition``
attributes created in ``__init__``, ``with self._lock:`` critical
sections, and worker threads started from class methods.

The rules share one package model built in ``prepare()`` (the engine's
cross-file hook): per-class locksets, a resolved intra-package call
graph, and the static lock-acquisition graph. Call resolution is
deliberately modest — ``self.m()`` within a class, bare names within a
module, and one level of attribute typing from ``self.x = ClassName()``
constructor assignments — because every resolved edge must be right:
precision beats recall, a concurrency lint that cries wolf gets
suppressed wholesale.

Annotation grammar (sphinx-style ``#:`` comments, so they double as
attribute docs):

``#: guarded-by: _lock`` — trailing on the ``self.attr = ...`` line in
``__init__`` (or standalone on the line above). Declares the guard;
bare writes AND bare reads of the attribute are then flagged, not just
writes that contradict an observed locked write.

``#: requires-lock: _lock`` — standalone on the line above a ``def``
(or trailing on the def line). Declares a lock the CALLER must hold;
the body is analysed as if the lock were held. This is how helper
methods like "take from the queue, caller holds the condition" state
their contract instead of tripping the lockset inference.

Static only, like every graftlint rule: nothing here imports the code
under analysis. The runtime counterpart (``lint/witness.py``) is the
dynamic cross-check: a patched Lock wrapper that records the actual
acquisition-order graph under the threaded suites and asserts it is
acyclic, so a disputed static cycle gets a reasoned suppression backed
by witness evidence.
"""
from __future__ import annotations

import ast
import re
import token
from typing import Dict, FrozenSet, Iterator, List, Optional, Sequence, Set, Tuple

from .engine import FileContext, Rule, Violation, dotted_name
from .rules import register

#: one annotation: kind + comma-separated lock attribute names
_ANNOT = re.compile(
    r"#:\s*(?P<kind>guarded-by|requires-lock):\s*"
    r"(?P<names>[A-Za-z_]\w*(?:\s*,\s*[A-Za-z_]\w*)*)")

_LOCK_CTORS = {"Lock": "lock", "RLock": "rlock", "Condition": "condition"}

#: method calls that mutate their receiver in place (lockset inference
#: treats ``self.x.append(...)`` as a write of ``x``)
_MUTATORS = frozenset({
    "append", "appendleft", "extend", "insert", "add", "update",
    "setdefault", "pop", "popitem", "popleft", "remove", "discard",
    "clear", "sort", "reverse",
})

#: dotted callables that block the calling thread outright
_BLOCK_DOTTED = {
    "time.sleep": "time.sleep()",
    "subprocess.run": "subprocess.run()",
    "subprocess.call": "subprocess.call()",
    "subprocess.check_call": "subprocess.check_call()",
    "subprocess.check_output": "subprocess.check_output()",
    "urllib.request.urlopen": "urlopen() network I/O",
    "socket.create_connection": "socket connect",
}

#: attribute-call names that are socket/network waits regardless of the
#: receiver (the names are specific enough not to collide in this tree)
_BLOCK_SOCKET = frozenset({"accept", "recv", "recv_into", "sendall",
                           "connect"})

#: compile seams: resolving one of these under a lock serializes every
#: other thread behind an XLA compile
_BLOCK_COMPILE = frozenset({"compile_step", "build_program"})


def _ctor_kind(value: ast.AST) -> Optional[str]:
    """'lock' / 'rlock' / 'condition' when ``value`` constructs one."""
    if not isinstance(value, ast.Call):
        return None
    d = dotted_name(value.func)
    if not d:
        return None
    last = d.rsplit(".", 1)[-1]
    if last in _LOCK_CTORS and d in (last, "threading." + last):
        return _LOCK_CTORS[last]
    return None


def _is_ctor(value: ast.AST, name: str) -> bool:
    if not isinstance(value, ast.Call):
        return False
    d = dotted_name(value.func)
    return d in (name, "threading." + name)


def _annotations(ctx: FileContext) -> Dict[int, List[Tuple[str, List[str]]]]:
    """line -> [(kind, names)]; standalone ``#:`` comments apply to the
    next code line (same scoping as suppressions)."""
    out: Dict[int, List[Tuple[str, List[str]]]] = {}
    try:
        toks = ctx.tokens
    except (SyntaxError, IndentationError):
        return out
    for i, t in enumerate(toks):
        if t.type != token.COMMENT:
            continue
        m = _ANNOT.search(t.string)
        if m is None:
            continue
        applies = t.start[0]
        if t.line.lstrip().startswith("#"):
            nxt = next((n for n in toks[i + 1:]
                        if n.type not in (token.NL, token.NEWLINE,
                                          token.COMMENT, token.INDENT,
                                          token.DEDENT)), None)
            if nxt is not None:
                applies = nxt.start[0]
        names = [s.strip() for s in m.group("names").split(",")]
        out.setdefault(applies, []).append((m.group("kind"), names))
    return out


class _ClassModel:
    __slots__ = ("name", "rel", "modname", "locks", "alias", "guarded",
                 "requires", "methods", "attr_ctors", "thread_attrs",
                 "event_attrs", "thread_targets")

    def __init__(self, name: str, rel: str, modname: str):
        self.name = name
        self.rel = rel
        self.modname = modname
        self.locks: Dict[str, str] = {}        # attr -> lock kind
        self.alias: Dict[str, str] = {}        # condition attr -> wrapped attr
        self.guarded: Dict[str, str] = {}      # attr -> declared lock attr
        self.requires: Dict[str, Tuple[str, ...]] = {}
        self.methods: Dict[str, ast.AST] = {}
        self.attr_ctors: Dict[str, str] = {}   # attr -> ctor class name
        self.thread_attrs: Set[str] = set()
        self.event_attrs: Set[str] = set()
        self.thread_targets: Set[str] = set()

    def node_for(self, attr: str, module: "_ModuleModel") -> str:
        """Canonical graph node for a lock attribute. A Condition built
        over an explicit lock IS that lock — holding either is holding
        both — so both names collapse to the wrapped attribute."""
        a = self.alias.get(attr, attr)
        if a not in self.locks and attr not in self.locks \
                and a in module.locks:
            return f"{module.modname}.{a}"
        return f"{self.modname}.{self.name}.{a}"

    def reentrant(self, attr: str) -> bool:
        a = self.alias.get(attr, attr)
        kind = self.locks.get(a)
        if kind == "condition":
            # Condition() with no explicit lock wraps a fresh RLock
            return True
        return kind == "rlock"


class _ModuleModel:
    __slots__ = ("rel", "modname", "classes", "locks", "functions")

    def __init__(self, rel: str):
        self.rel = rel
        self.modname = rel[:-3].replace("/", ".") if rel.endswith(".py") \
            else rel.replace("/", ".")
        self.classes: Dict[str, _ClassModel] = {}
        self.locks: Dict[str, str] = {}        # module-global locks
        self.functions: Dict[str, ast.AST] = {}


class _FnFacts:
    """Everything the rules need about one function: events with the
    statically-held lockset at each, plus resolution inputs."""

    __slots__ = ("key", "rel", "module", "cls", "fname", "events",
                 "local_ctors", "local_threads")

    def __init__(self, key, rel, module, cls, fname):
        self.key = key
        self.rel = rel
        self.module = module
        self.cls = cls
        self.fname = fname
        #: ("acq", node, line, held, is_self_attr)
        #: ("call", dotted, line, held)
        #: ("block", desc, line, held)
        #: ("write", attr, line, held) / ("read", attr, line, held)
        self.events: List[tuple] = []
        self.local_ctors: Dict[str, str] = {}
        self.local_threads: Set[str] = set()


def _iter_expr(node: ast.AST) -> Iterator[ast.AST]:
    """ast.walk over an expression, skipping Lambda bodies (they run at
    some later time, under an unknowable lockset)."""
    stack = [node]
    while stack:
        n = stack.pop()
        yield n
        for child in ast.iter_child_nodes(n):
            if isinstance(child, ast.Lambda):
                continue
            stack.append(child)


class _Walker:
    """One pass over a function body tracking the statically-held
    lockset: ``with self._lock:`` nesting plus statement-level
    ``.acquire()``/``.release()`` pairs, seeded from any
    ``#: requires-lock:`` contract."""

    def __init__(self, model: "_PackageModel", mm: _ModuleModel,
                 cm: Optional[_ClassModel], fname: str, fn: ast.AST):
        self.model = model
        self.mm = mm
        self.cm = cm
        key = (mm.rel, cm.name if cm else None, fname)
        self.facts = _FnFacts(key, mm.rel, mm, cm, fname)
        for sub in ast.walk(fn):
            if isinstance(sub, ast.Assign) and len(sub.targets) == 1 \
                    and isinstance(sub.targets[0], ast.Name) \
                    and isinstance(sub.value, ast.Call):
                d = dotted_name(sub.value.func)
                if not d:
                    continue
                name = sub.targets[0].id
                if d in ("Thread", "threading.Thread"):
                    self.facts.local_threads.add(name)
                else:
                    self.facts.local_ctors[name] = d.rsplit(".", 1)[-1]
        held0: Set[str] = set()
        if cm is not None:
            for a in cm.requires.get(fname, ()):
                held0.add(cm.node_for(a, mm))
        self._stmts(fn.body, frozenset(held0))

    # ------------------------------------------------------------ plumbing
    def _lock_node(self, expr: ast.AST) -> Tuple[Optional[str], bool]:
        """(graph node, is-self-attribute) for a lock expression."""
        d = dotted_name(expr)
        if d is None:
            return None, False
        parts = d.split(".")
        if parts[0] == "self" and len(parts) == 2 and self.cm is not None \
                and self.cm.alias.get(parts[1], parts[1]) in self.cm.locks:
            return self.cm.node_for(parts[1], self.mm), True
        if len(parts) == 1 and d in self.mm.locks:
            return f"{self.mm.modname}.{d}", False
        return None, False

    def _ev(self, *tup) -> None:
        self.facts.events.append(tup)

    # ------------------------------------------------------------ statements
    def _stmts(self, body: Sequence[ast.stmt], held: FrozenSet[str]) -> None:
        extra: List[str] = []
        for st in body:
            self._stmt(st, held | frozenset(extra), extra)

    def _stmt(self, st: ast.stmt, held: FrozenSet[str],
              extra: List[str]) -> None:
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # nested def: runs later (often as a thread target) — analyse
            # the body with an empty lockset, attributed to this method
            self._stmts(st.body, frozenset())
            return
        if isinstance(st, ast.ClassDef):
            return
        if isinstance(st, (ast.With, ast.AsyncWith)):
            acquired: List[str] = []
            for item in st.items:
                node, is_self = self._lock_node(item.context_expr)
                h = held | frozenset(acquired)
                if node is not None:
                    self._ev("acq", node, item.context_expr.lineno, h,
                             is_self)
                    acquired.append(node)
                else:
                    self._expr(item.context_expr, h)
            self._stmts(st.body, held | frozenset(acquired))
            return
        if isinstance(st, ast.Expr) and isinstance(st.value, ast.Call):
            call = st.value
            f = call.func
            if isinstance(f, ast.Attribute) and f.attr in ("acquire",
                                                           "release"):
                node, is_self = self._lock_node(f.value)
                if node is not None:
                    if f.attr == "acquire":
                        self._ev("acq", node, st.lineno, held, is_self)
                        extra.append(node)
                    elif node in extra:
                        extra.remove(node)
                    return
            self._expr(call, held)
            return
        if isinstance(st, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = st.targets if isinstance(st, ast.Assign) \
                else [st.target]
            for t in targets:
                self._write_target(t, st.lineno, held)
            if getattr(st, "value", None) is not None:
                self._expr(st.value, held)
            return
        if isinstance(st, ast.Delete):
            for t in st.targets:
                self._write_target(t, st.lineno, held)
            return
        for _field, val in ast.iter_fields(st):
            if isinstance(val, list):
                if val and isinstance(val[0], ast.stmt):
                    self._stmts(val, held)
                else:
                    for v in val:
                        if isinstance(v, ast.expr):
                            self._expr(v, held)
                        elif hasattr(v, "body") and \
                                isinstance(getattr(v, "body"), list):
                            # excepthandler / match_case arms
                            self._stmts(v.body, held)
            elif isinstance(val, ast.expr):
                self._expr(val, held)
            elif isinstance(val, ast.stmt):
                self._stmt(val, held, extra)

    def _write_target(self, t: ast.AST, line: int,
                      held: FrozenSet[str]) -> None:
        if isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                self._write_target(e, line, held)
            return
        base = t
        while isinstance(base, ast.Subscript):
            if isinstance(base.slice, ast.expr):
                self._expr(base.slice, held)
            base = base.value
        d = dotted_name(base)
        if d and d.startswith("self.") and self.cm is not None:
            self._ev("write", d.split(".")[1], line, held)

    # ------------------------------------------------------------ expressions
    def _expr(self, node: ast.AST, held: FrozenSet[str]) -> None:
        for n in _iter_expr(node):
            if isinstance(n, ast.Call):
                self._call(n, held)
            elif isinstance(n, ast.Attribute) and isinstance(n.ctx, ast.Load)\
                    and isinstance(n.value, ast.Name) \
                    and n.value.id == "self" and self.cm is not None:
                self._ev("read", n.attr, n.lineno, held)

    def _call(self, call: ast.Call, held: FrozenSet[str]) -> None:
        d = dotted_name(call.func)
        if isinstance(call.func, ast.Attribute):
            m = call.func.attr
            recv = dotted_name(call.func.value)
            if m in _MUTATORS and recv and recv.startswith("self.") \
                    and self.cm is not None:
                self._ev("write", recv.split(".")[1], call.lineno, held)
        desc = self._blocking_desc(call, held)
        if desc is not None:
            self._ev("block", desc, call.lineno, held)
        if d is not None:
            self._ev("call", d, call.lineno, held)

    def _blocking_desc(self, call: ast.Call,
                       held: FrozenSet[str]) -> Optional[str]:
        d = dotted_name(call.func)
        if d in _BLOCK_DOTTED:
            return _BLOCK_DOTTED[d]
        if d in _BLOCK_COMPILE:
            return f"compile seam {d}()"
        if not isinstance(call.func, ast.Attribute):
            return None
        m = call.func.attr
        recv = dotted_name(call.func.value)
        if m == "block_until_ready":
            return "device sync (.block_until_ready())"
        if m == "item" and not call.args and not call.keywords:
            return "device sync (.item())"
        if m in _BLOCK_COMPILE:
            return f"compile seam .{m}()"
        if m in _BLOCK_SOCKET:
            return f"socket .{m}() I/O"
        if m == "result":
            return "future .result() wait"
        if m == "join":
            attr = None
            if recv and recv.startswith("self.") and len(recv.split(".")) == 2:
                attr = recv.split(".")[1]
            if (attr and self.cm is not None
                    and attr in self.cm.thread_attrs) \
                    or (recv in self.facts.local_threads):
                return "thread .join() wait"
            return None
        if m == "wait":
            if recv and recv.startswith("self.") and self.cm is not None:
                parts = recv.split(".")
                if len(parts) == 2:
                    attr = parts[1]
                    if self.cm.locks.get(attr) == "condition":
                        # waiting on a condition whose lock you hold is
                        # THE condition idiom, not a finding; waiting on
                        # one you don't hold raises at runtime anyway
                        return None
                    if attr in self.cm.event_attrs:
                        return "Event .wait()"
            return "blocking .wait()"
        if m == "get" and any(kw.arg in ("timeout", "block")
                              for kw in call.keywords):
            return "queue .get() wait"
        return None


class _PackageModel:
    """Cross-file model shared by the four rules (built once per run,
    cached on the first FileContext)."""

    def __init__(self, ctxs: Sequence[FileContext]):
        self.modules: Dict[str, _ModuleModel] = {}
        self.classes: Dict[str, _ClassModel] = {}
        self.attr_types: Dict[str, str] = {}
        self.fn_facts: Dict[tuple, _FnFacts] = {}
        self.edges: Dict[Tuple[str, str], Tuple[str, int]] = {}
        self.node_kinds: Dict[str, str] = {}
        self.self_deadlocks: List[Tuple[str, int, str]] = []
        self._build(ctxs)

    # ------------------------------------------------------------ phase 1+2
    def _build(self, ctxs: Sequence[FileContext]) -> None:
        parsed: List[Tuple[FileContext, ast.Module]] = []
        for ctx in sorted(ctxs, key=lambda c: c.rel):
            tree = ctx.tree
            if tree is None:
                continue
            parsed.append((ctx, tree))
            self.modules[ctx.rel] = self._module(ctx, tree)
        ambiguous: Set[str] = set()
        for mm in self.modules.values():
            for cname, cm in mm.classes.items():
                if cname in self.classes:
                    ambiguous.add(cname)
                else:
                    self.classes[cname] = cm
        for a in ambiguous:
            self.classes.pop(a, None)
        attr_amb: Set[str] = set()
        for mm in self.modules.values():
            for cm in mm.classes.values():
                for attr, ctor in cm.attr_ctors.items():
                    if ctor not in self.classes:
                        continue
                    prev = self.attr_types.get(attr)
                    if prev is not None and prev != ctor:
                        attr_amb.add(attr)
                    self.attr_types[attr] = ctor
        for a in attr_amb:
            self.attr_types.pop(a, None)
        for mm in self.modules.values():
            for name, kind in mm.locks.items():
                self.node_kinds[f"{mm.modname}.{name}"] = kind
            for cm in mm.classes.values():
                for attr, kind in cm.locks.items():
                    self.node_kinds[cm.node_for(attr, mm)] = \
                        cm.locks.get(cm.alias.get(attr, attr), kind)
        # phase 3: walk every function
        for ctx, tree in parsed:
            mm = self.modules[ctx.rel]
            for item in tree.body:
                if isinstance(item, ast.ClassDef) \
                        and item.name in mm.classes:
                    cm = mm.classes[item.name]
                    for name, fn in cm.methods.items():
                        w = _Walker(self, mm, cm, name, fn)
                        self.fn_facts[w.facts.key] = w.facts
                elif isinstance(item, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                    w = _Walker(self, mm, None, item.name, item)
                    self.fn_facts[w.facts.key] = w.facts
        self._link()

    def _module(self, ctx: FileContext, tree: ast.Module) -> _ModuleModel:
        mm = _ModuleModel(ctx.rel)
        annots = _annotations(ctx)
        for item in tree.body:
            if isinstance(item, ast.ClassDef):
                mm.classes[item.name] = self._class(item, mm, annots)
            elif isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                mm.functions[item.name] = item
            elif isinstance(item, ast.Assign) and len(item.targets) == 1 \
                    and isinstance(item.targets[0], ast.Name):
                kind = _ctor_kind(item.value)
                if kind is not None:
                    mm.locks[item.targets[0].id] = kind
        return mm

    def _class(self, node: ast.ClassDef, mm: _ModuleModel,
               annots: Dict[int, List[Tuple[str, List[str]]]]) -> _ClassModel:
        cm = _ClassModel(node.name, mm.rel, mm.modname)
        for item in node.body:
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                cm.methods[item.name] = item
        for name, fn in cm.methods.items():
            lines = {fn.lineno} | {d.lineno for d in fn.decorator_list}
            for ln in lines:
                for kind, names in annots.get(ln, []):
                    if kind == "requires-lock":
                        cm.requires[name] = tuple(names)
        for fn in cm.methods.values():
            for sub in ast.walk(fn):
                if isinstance(sub, ast.Assign) and len(sub.targets) == 1:
                    t = sub.targets[0]
                    if isinstance(t, ast.Attribute) \
                            and isinstance(t.value, ast.Name) \
                            and t.value.id == "self":
                        self._class_attr(cm, t.attr, sub, annots)
                if isinstance(sub, ast.Call) \
                        and dotted_name(sub.func) in ("Thread",
                                                      "threading.Thread"):
                    for kw in sub.keywords:
                        if kw.arg != "target":
                            continue
                        d = dotted_name(kw.value)
                        if d and d.startswith("self.") \
                                and len(d.split(".")) == 2:
                            cm.thread_targets.add(d.split(".")[1])
        return cm

    def _class_attr(self, cm: _ClassModel, attr: str, assign: ast.Assign,
                    annots: Dict[int, List[Tuple[str, List[str]]]]) -> None:
        v = assign.value
        kind = _ctor_kind(v)
        if kind is not None:
            cm.locks[attr] = kind
            if kind == "condition" and isinstance(v, ast.Call) and v.args:
                w = dotted_name(v.args[0])
                if w and w.startswith("self.") and len(w.split(".")) == 2:
                    cm.alias[attr] = w.split(".")[1]
        elif _is_ctor(v, "Thread"):
            cm.thread_attrs.add(attr)
        elif _is_ctor(v, "Event"):
            cm.event_attrs.add(attr)
        elif isinstance(v, ast.Call):
            d = dotted_name(v.func)
            if d:
                cm.attr_ctors.setdefault(attr, d.rsplit(".", 1)[-1])
        for kind_a, names in annots.get(assign.lineno, []):
            if kind_a == "guarded-by" and names:
                cm.guarded[attr] = names[0]

    # ------------------------------------------------------------ phase 4
    def resolve_call(self, facts: _FnFacts, dotted: str) -> Optional[tuple]:
        parts = dotted.split(".")
        if parts[0] == "self":
            if facts.cls is None:
                return None
            if len(parts) == 2:
                if parts[1] in facts.cls.methods:
                    return (facts.rel, facts.cls.name, parts[1])
                return None
            return self._by_attr(parts[-2], parts[-1])
        if len(parts) == 1:
            if parts[0] in facts.module.functions:
                return (facts.rel, None, parts[0])
            return None
        if len(parts) == 2 and parts[0] in facts.local_ctors:
            return self._method_of(facts.local_ctors[parts[0]], parts[1])
        return self._by_attr(parts[-2], parts[-1])

    def _by_attr(self, attr: str, meth: str) -> Optional[tuple]:
        cname = self.attr_types.get(attr)
        return self._method_of(cname, meth) if cname else None

    def _method_of(self, cname: str, meth: str) -> Optional[tuple]:
        cm = self.classes.get(cname)
        if cm is not None and meth in cm.methods:
            return (cm.rel, cm.name, meth)
        return None

    def _link(self) -> None:
        """Resolve calls, run the transitive-acquisition fixpoint, and
        materialise the lock-order edge set."""
        resolved: Dict[tuple, List[Tuple[tuple, int, FrozenSet[str]]]] = {}
        direct_acq: Dict[tuple, Set[str]] = {}
        self.direct_blocking: Dict[tuple, Tuple[str, int]] = {}
        for key in sorted(self.fn_facts, key=str):
            f = self.fn_facts[key]
            direct_acq[key] = set()
            resolved[key] = []
            for ev in f.events:
                if ev[0] == "acq":
                    direct_acq[key].add(ev[1])
                elif ev[0] == "call":
                    c = self.resolve_call(f, ev[1])
                    if c is not None and c in self.fn_facts:
                        resolved[key].append(
                            (c, ev[2], ev[3], ev[1].startswith("self.")))
                elif ev[0] == "block" and key not in self.direct_blocking:
                    self.direct_blocking[key] = (ev[1], ev[2])
        self.resolved_calls = resolved
        trans: Dict[tuple, Set[str]] = {k: set(v)
                                        for k, v in direct_acq.items()}
        for _ in range(30):
            changed = False
            for key, calls in resolved.items():
                acc = trans[key]
                for c, _line, _held, _via_self in calls:
                    extra = trans.get(c, ())
                    if not set(extra) <= acc:
                        acc |= set(extra)
                        changed = True
            if not changed:
                break
        self.trans_acq = trans
        for key in sorted(self.fn_facts, key=str):
            f = self.fn_facts[key]
            for ev in f.events:
                if ev[0] == "acq":
                    _t, node, line, held, is_self = ev
                    for h in sorted(held):
                        if h == node:
                            if is_self and \
                                    self.node_kinds.get(node) == "lock":
                                self.self_deadlocks.append(
                                    (f.rel, line, node))
                        else:
                            self.edges.setdefault((h, node), (f.rel, line))
            for c, line, held, via_self in resolved[key]:
                if not held:
                    continue
                for n in sorted(self.trans_acq.get(c, ())):
                    for h in sorted(held):
                        if h != n:
                            self.edges.setdefault((h, n), (f.rel, line))
                        elif via_self and \
                                self.node_kinds.get(n) == "lock":
                            # self.m() re-acquiring a plain Lock the
                            # caller already holds: same instance, so
                            # this is a guaranteed self-deadlock (other
                            # receivers share the node but may be a
                            # different instance - skip those)
                            self.self_deadlocks.append((f.rel, line, n))


def _model_for(ctxs: Sequence[FileContext]) -> _PackageModel:
    if not ctxs:
        return _PackageModel([])
    cached = getattr(ctxs[0], "_graftlint_concurrency", None)
    if cached is not None and cached[0] == len(ctxs):
        return cached[1]
    model = _PackageModel(ctxs)
    try:
        ctxs[0]._graftlint_concurrency = (len(ctxs), model)
    except Exception:  # lint: swallowed-exception-ok (cache attach is best-effort; a slotted/frozen ctx just rebuilds the model per rule)
        pass
    return model


def _disp(node: str) -> str:
    """Strip the package prefix off a graph node for messages."""
    return node[len("deeplearning4j_tpu."):] \
        if node.startswith("deeplearning4j_tpu.") else node


# ---------------------------------------------------------------------------
@register
class LockGuard(Rule):
    """Eraser-style per-class lockset inference.

    An attribute written under ``with self._lock:`` in some methods of a
    class but mutated bare in others (``__init__`` excepted — the object
    is not shared yet) violates the inferred discipline; a bare mutation
    from a ``Thread`` target method is called out as such. A
    ``#: guarded-by: _lock`` annotation pins the guard explicitly and
    tightens the check to bare READS as well; ``#: requires-lock:`` on a
    helper method declares the caller-holds-the-lock contract instead of
    tripping the inference.
    """

    name = "lockguard"
    description = ("class attribute written under a lock in one method "
                   "but mutated bare in another (lockset inference; "
                   "'#: guarded-by:' pins intent)")

    def prepare(self, ctxs: Sequence[FileContext]) -> None:
        self._by_file: Dict[str, List[Tuple[int, str]]] = {}
        model = _model_for(ctxs)
        for mm in model.modules.values():
            for cm in mm.classes.values():
                self._check_class(model, mm, cm)

    def _check_class(self, model: _PackageModel, mm: _ModuleModel,
                     cm: _ClassModel) -> None:
        writes: Dict[str, List[tuple]] = {}
        reads: Dict[str, List[tuple]] = {}
        for fname in cm.methods:
            # construction runs before the object is shared — dataclass
            # __post_init__ included
            if fname in ("__init__", "__new__", "__post_init__"):
                continue
            facts = model.fn_facts.get((mm.rel, cm.name, fname))
            if facts is None:
                continue
            for ev in facts.events:
                if ev[0] == "write":
                    writes.setdefault(ev[1], []).append(
                        (ev[2], ev[3], fname))
                elif ev[0] == "read":
                    reads.setdefault(ev[1], []).append(
                        (ev[2], ev[3], fname))
        own_nodes = {cm.node_for(a, mm) for a in cm.locks}
        for attr in sorted(set(writes) | set(cm.guarded)):
            if attr in cm.locks:
                continue
            ann = cm.guarded.get(attr)
            if ann is not None:
                guards = {cm.node_for(ann, mm)}
            else:
                guards = set()
                for (_l, held, _f) in writes.get(attr, []):
                    guards |= (held & own_nodes)
            if not guards:
                continue
            locked_in = sorted({f for (_l, held, f) in writes.get(attr, [])
                                if held & guards})
            disp = "/".join(sorted(_disp(g) for g in guards))
            for (line, held, fname) in writes.get(attr, []):
                if held & guards:
                    continue
                tt = " (a Thread target)" if fname in cm.thread_targets \
                    else ""
                if ann is not None:
                    msg = (f"self.{attr} is '#: guarded-by: {ann}' but "
                           f"mutated in {fname}(){tt} without holding it")
                else:
                    where = f" (locked writes in {', '.join(locked_in)})" \
                        if locked_in else ""
                    msg = (f"self.{attr} is written under {disp} elsewhere "
                           f"in {cm.name} but mutated bare in "
                           f"{fname}(){tt}{where}")
                self._by_file.setdefault(mm.rel, []).append((line, msg))
            if ann is not None:
                flagged = {line for (line, held, _f) in writes.get(attr, [])
                           if not (held & guards)}
                for (line, held, fname) in reads.get(attr, []):
                    if held & guards or line in flagged:
                        continue
                    self._by_file.setdefault(mm.rel, []).append(
                        (line, f"self.{attr} is '#: guarded-by: {ann}' but "
                               f"read in {fname}() without holding it"))

    def check(self, ctx: FileContext) -> Iterator[Violation]:
        if ctx.tree is None:
            return
        for line, msg in sorted(self._by_file.get(ctx.rel, [])):
            yield self.violation(ctx, line, msg)


# ---------------------------------------------------------------------------
@register
class LockOrder(Rule):
    """RacerX-style static lock-order analysis.

    Builds the interprocedural lock-acquisition graph — ``with`` blocks
    and ``.acquire()`` calls, with method calls resolved within the
    package — and flags cycles as potential deadlocks, plus direct
    re-acquisition of a non-reentrant lock (self-deadlock). Nodes are
    per-class lock attributes (all instances collapse to one node, so a
    consistent hierarchy between peers is assumed); a Condition built
    over an explicit lock shares that lock's node. The runtime witness
    (``lint/witness.py``) records the same graph dynamically under the
    threaded suites — a disputed static cycle gets a suppression citing
    witness evidence.
    """

    name = "lock-order"
    description = ("cycle in the interprocedural lock-acquisition graph "
                   "(potential ABBA deadlock), or re-acquisition of a "
                   "non-reentrant lock")

    def prepare(self, ctxs: Sequence[FileContext]) -> None:
        self._by_file: Dict[str, List[Tuple[int, str]]] = {}
        model = _model_for(ctxs)
        for rel, line, node in model.self_deadlocks:
            self._by_file.setdefault(rel, []).append(
                (line, f"non-reentrant lock {_disp(node)} acquired while "
                       "already held on this path — self-deadlock"))
        for cycle in self._cycles(model):
            path = " -> ".join(_disp(n) for n in cycle + (cycle[0],))
            hops = []
            for a, b in zip(cycle, cycle[1:] + (cycle[0],)):
                rel, line = model.edges[(a, b)]
                hops.append(f"{_disp(a)}->{_disp(b)} at {rel}:{line}")
            rel0, line0 = model.edges[(cycle[0], cycle[1])]
            self._by_file.setdefault(rel0, []).append(
                (line0, f"potential deadlock: lock-order cycle {path} "
                        f"({'; '.join(hops)})"))

    def _cycles(self, model: _PackageModel) -> List[tuple]:
        adj: Dict[str, List[str]] = {}
        for (a, b) in model.edges:
            adj.setdefault(a, []).append(b)
            adj.setdefault(b, [])
        for nbrs in adj.values():
            nbrs.sort()
        sccs = _tarjan(adj)
        out = []
        for scc in sccs:
            if len(scc) < 2:
                continue
            scc_set = set(scc)
            start = min(scc)
            path = self._find_cycle(adj, scc_set, start)
            if path:
                out.append(tuple(path))
        out.sort()
        return out

    def _find_cycle(self, adj, scc_set, start):
        """Deterministic cycle through ``start`` inside one SCC."""
        stack = [(start, [start])]
        seen = set()
        while stack:
            node, path = stack.pop()
            for nxt in reversed(adj.get(node, [])):
                if nxt == start and len(path) > 1:
                    return path
                if nxt in scc_set and nxt not in seen:
                    seen.add(nxt)
                    stack.append((nxt, path + [nxt]))
        return None

    def check(self, ctx: FileContext) -> Iterator[Violation]:
        if ctx.tree is None:
            return
        for line, msg in sorted(self._by_file.get(ctx.rel, [])):
            yield self.violation(ctx, line, msg)


# ---------------------------------------------------------------------------
@register
class BlockingUnderLock(Rule):
    """No unbounded waits inside a critical section.

    Device syncs (``block_until_ready``, trusted ``.item()`` reads),
    compile seams, socket I/O, ``time.sleep``, thread joins, future
    results and queue waits while statically holding a lock stall every
    thread that contends on it — on the serving hot path (batcher, PS,
    replica set, tracing) that is a fleet-wide latency cliff. One level
    of call resolution: a call under a lock to a package function whose
    body directly blocks is flagged at the call site. ``Condition.wait``
    on the held condition's own lock is the idiom, not a finding.
    """

    name = "blocking-under-lock"
    description = ("blocking call (device sync, compile seam, socket, "
                   "sleep, join, queue wait) while holding a lock")
    #: the UI plane serves a browser over HTTP from its own threads —
    #: socket writes under its session locks are its whole job
    exclude = ("*/deeplearning4j_tpu/ui/*",)

    def prepare(self, ctxs: Sequence[FileContext]) -> None:
        self._by_file: Dict[str, List[Tuple[int, str]]] = {}
        model = _model_for(ctxs)
        for key in sorted(model.fn_facts, key=str):
            f = model.fn_facts[key]
            for ev in f.events:
                if ev[0] == "block" and ev[3]:
                    locks = "/".join(sorted(_disp(h) for h in ev[3]))
                    self._by_file.setdefault(f.rel, []).append(
                        (ev[2], f"{ev[1]} while holding {locks}"))
            for c, line, held, _via_self in model.resolved_calls.get(key, ()):
                if not held:
                    continue
                blk = model.direct_blocking.get(c)
                if blk is None:
                    continue
                locks = "/".join(sorted(_disp(h) for h in held))
                cname = f"{c[1]}.{c[2]}" if c[1] else c[2]
                self._by_file.setdefault(f.rel, []).append(
                    (line, f"call to {cname}() ({blk[0]} at {c[0]}:{blk[1]}) "
                           f"while holding {locks}"))

    def check(self, ctx: FileContext) -> Iterator[Violation]:
        if ctx.tree is None:
            return
        for line, msg in sorted(self._by_file.get(ctx.rel, [])):
            yield self.violation(ctx, line, msg)


# ---------------------------------------------------------------------------
@register
class ThreadLifecycle(Rule):
    """Every worker thread needs an owner.

    ``threading.Thread(...)`` without ``daemon=True`` and without a
    reachable ``join()``/``.daemon = True`` on its handle leaks a
    non-daemon thread that blocks interpreter shutdown — the
    stop-seam-less worker is exactly the zombie the elastic plane
    fences. Handles stored on ``self`` are searched class-wide for a
    join; locals are searched within the creating function; anonymous
    ``Thread(...).start()`` chains need a join somewhere in the same
    scope (the ``for t in threads: t.join()`` idiom) to pass.
    """

    name = "thread-lifecycle"
    description = ("Thread started without daemon=True or a reachable "
                   "join()/stop seam")

    def check(self, ctx: FileContext) -> Iterator[Violation]:
        tree = ctx.tree
        if tree is None:
            return
        cls_of: Dict[int, ast.ClassDef] = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef):
                for item in node.body:
                    if isinstance(item, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        cls_of[id(item)] = node
        scopes: List[Tuple[ast.AST, Optional[ast.ClassDef]]] = [(tree, None)]
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                scopes.append((node, cls_of.get(id(node))))
        for scope, cls in scopes:
            for call, target in self._thread_ctors(scope):
                if self._has_daemon_kwarg(call):
                    continue
                if self._owned(ctx, tree, scope, cls, call, target):
                    continue
                yield self.violation(
                    ctx, call.lineno,
                    "Thread without an owner: pass daemon=True, or keep "
                    "the handle and join() it from a close()/stop() seam")

    def _thread_ctors(self, scope: ast.AST):
        """(ctor call, assignment target dotted name or None) for Thread
        constructions directly in this scope (nested defs excluded —
        they are their own scope)."""
        out = []
        targeted: Set[int] = set()

        def visit(node):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                      ast.ClassDef)):
                    continue  # nested scopes report their own threads
                if isinstance(child, ast.Assign) and len(child.targets) == 1\
                        and isinstance(child.value, ast.Call) \
                        and dotted_name(child.value.func) in (
                            "Thread", "threading.Thread"):
                    out.append((child.value, dotted_name(child.targets[0])))
                    targeted.add(id(child.value))
                elif isinstance(child, ast.Call) \
                        and dotted_name(child.func) in ("Thread",
                                                        "threading.Thread") \
                        and id(child) not in targeted:
                    out.append((child, None))
                visit(child)
        visit(scope)
        return [(c, t) for c, t in out
                if t is not None or id(c) not in targeted]

    def _has_daemon_kwarg(self, call: ast.Call) -> bool:
        for kw in call.keywords:
            if kw.arg == "daemon":
                return not (isinstance(kw.value, ast.Constant)
                            and kw.value.value is False)
        return False

    def _owned(self, ctx, tree, scope, cls, call, target) -> bool:
        if target is None:
            search: ast.AST = scope
            suffix = None
        elif target.startswith("self."):
            search = cls if cls is not None else tree
            suffix = target.split(".", 1)[1]
        else:
            search = scope
            suffix = target
        for node in ast.walk(search):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "join":
                recv = dotted_name(node.func.value)
                if suffix is None:
                    return True
                if recv is not None and (recv == suffix
                                         or recv.endswith("." + suffix)):
                    return True
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                d = dotted_name(node.targets[0])
                if d and d.endswith(".daemon") \
                        and isinstance(node.value, ast.Constant) \
                        and node.value.value is True:
                    stem = d[:-len(".daemon")]
                    if suffix is None or stem == suffix \
                            or stem.endswith("." + suffix):
                        return True
        return False


def _tarjan(adj: Dict[str, List[str]]) -> List[List[str]]:
    """Iterative Tarjan SCC (the lock graph is small, but recursion
    depth must not depend on it)."""
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on_stack: Set[str] = set()
    stack: List[str] = []
    sccs: List[List[str]] = []
    counter = [0]

    for root in sorted(adj):
        if root in index:
            continue
        work = [(root, iter(adj.get(root, [])))]
        index[root] = low[root] = counter[0]
        counter[0] += 1
        stack.append(root)
        on_stack.add(root)
        while work:
            node, it = work[-1]
            advanced = False
            for nxt in it:
                if nxt not in index:
                    index[nxt] = low[nxt] = counter[0]
                    counter[0] += 1
                    stack.append(nxt)
                    on_stack.add(nxt)
                    work.append((nxt, iter(adj.get(nxt, []))))
                    advanced = True
                    break
                elif nxt in on_stack:
                    low[node] = min(low[node], index[nxt])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                scc = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    scc.append(w)
                    if w == node:
                        break
                sccs.append(sorted(scc))
    return sccs
