"""Model savers for early stopping (reference earlystopping/saver/*.java)."""
from __future__ import annotations

import copy
import os


class EarlyStoppingModelSaver:
    def save_best_model(self, model, score: float) -> None:
        raise NotImplementedError

    def save_latest_model(self, model, score: float) -> None:
        raise NotImplementedError

    def get_best_model(self):
        raise NotImplementedError

    def get_latest_model(self):
        raise NotImplementedError


class InMemoryModelSaver(EarlyStoppingModelSaver):
    """Keeps deep copies in memory (reference saver/InMemoryModelSaver.java)."""

    def __init__(self):
        self._best = None
        self._latest = None

    @staticmethod
    def _snapshot(model):
        # jax arrays are immutable and train steps replace rather than mutate
        # them, so a structural copy holding the same leaves is a safe snapshot
        import jax

        snap = copy.copy(model)
        ident = lambda tree: jax.tree_util.tree_map(lambda a: a, tree)
        snap.params_list = ident(model.params_list)
        snap.state_list = ident(model.state_list)
        snap.updater_state = ident(model.updater_state)
        return snap

    def save_best_model(self, model, score: float) -> None:
        self._best = self._snapshot(model)

    def save_latest_model(self, model, score: float) -> None:
        self._latest = self._snapshot(model)

    def get_best_model(self):
        return self._best

    def get_latest_model(self):
        return self._latest


class LocalFileModelSaver(EarlyStoppingModelSaver):
    """Writes bestModel/latestModel checkpoint archives in a directory
    (reference saver/LocalFileModelSaver.java + LocalFileGraphSaver.java —
    one class here; the container format already distinguishes model kinds)."""

    BEST = "bestModel.dl4jtpu.zip"
    LATEST = "latestModel.dl4jtpu.zip"

    def __init__(self, directory: str):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)

    def _write(self, model, name: str) -> None:
        from deeplearning4j_tpu.utils.model_serializer import write_model

        write_model(model, os.path.join(self.directory, name))

    def _read(self, name: str):
        from deeplearning4j_tpu.utils.model_serializer import guess_model

        path = os.path.join(self.directory, name)
        return guess_model(path) if os.path.exists(path) else None

    def save_best_model(self, model, score: float) -> None:
        self._write(model, self.BEST)

    def save_latest_model(self, model, score: float) -> None:
        self._write(model, self.LATEST)

    def get_best_model(self):
        return self._read(self.BEST)

    def get_latest_model(self):
        return self._read(self.LATEST)
