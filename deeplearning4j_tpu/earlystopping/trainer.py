"""Early stopping trainer loop.

Reference: earlystopping/trainer/BaseEarlyStoppingTrainer.java:76 (fit()) —
per-minibatch iteration termination checks, per-epoch score calculation every
``evaluate_every_n_epochs``, best-model tracking/saving, listener hooks. One
trainer serves MultiLayerNetwork and ComputationGraph (the reference splits
EarlyStoppingTrainer / EarlyStoppingGraphTrainer over Java generics only).
"""
from __future__ import annotations

import logging
from typing import Optional

from deeplearning4j_tpu.earlystopping.config import (
    EarlyStoppingConfiguration, EarlyStoppingResult, TerminationReason,
)

log = logging.getLogger(__name__)


class EarlyStoppingListener:
    def on_start(self, config, model) -> None:
        pass

    def on_epoch(self, epoch: int, score: float, config, model) -> None:
        pass

    def on_completion(self, result: EarlyStoppingResult) -> None:
        pass


class EarlyStoppingTrainer:
    def __init__(self, config: EarlyStoppingConfiguration, model, iterator,
                 listener: Optional[EarlyStoppingListener] = None):
        self.config = config
        self.model = model
        self.iterator = iterator
        self.listener = listener

    def _fit_one(self, ds) -> None:
        from deeplearning4j_tpu.nn.graph_network import ComputationGraph, MultiDataSet

        if isinstance(self.model, ComputationGraph):
            self.model.fit(ds if isinstance(ds, MultiDataSet)
                           else MultiDataSet([ds.features], [ds.labels]))
        else:
            self.model.fit(ds.features, ds.labels)

    @staticmethod
    def _check_iteration_termination(cfg, score):
        for c in cfg.iteration_termination_conditions:
            if c.terminate(score):
                return c
        return None

    def _run_epoch(self, cfg):
        """One epoch of training; returns the iteration termination condition
        that fired, or None."""
        for ds in self.iterator:
            self._fit_one(ds)
            fired = self._check_iteration_termination(cfg,
                                                      self.model.score_value)
            if fired is not None:
                return fired
        return None

    def fit(self) -> EarlyStoppingResult:
        cfg = self.config
        for c in cfg.iteration_termination_conditions:
            c.initialize()
        for c in cfg.epoch_termination_conditions:
            c.initialize()
        if self.listener:
            self.listener.on_start(cfg, self.model)

        score_vs_epoch: dict = {}
        best_score = float("inf")
        best_epoch = -1
        epoch = 0
        while True:
            if hasattr(self.iterator, "reset"):
                self.iterator.reset()
            terminate_reason = None
            try:
                terminate_reason = self._run_epoch(cfg)
            except Exception as e:  # reference returns Error result, not raise
                log.warning("early stopping terminated by exception at epoch %d: %s",
                            epoch, e)
                result = EarlyStoppingResult(
                    TerminationReason.ERROR, str(e), score_vs_epoch, best_epoch,
                    best_score, epoch, cfg.model_saver.get_best_model())
                if self.listener:
                    self.listener.on_completion(result)
                return result

            if terminate_reason is not None:
                if cfg.save_last_model:
                    cfg.model_saver.save_latest_model(self.model, 0.0)
                result = EarlyStoppingResult(
                    TerminationReason.ITERATION_TERMINATION_CONDITION,
                    repr(terminate_reason), score_vs_epoch, best_epoch,
                    best_score, epoch, cfg.model_saver.get_best_model())
                if self.listener:
                    self.listener.on_completion(result)
                return result

            epoch += 1
            if (epoch - 1) % cfg.evaluate_every_n_epochs == 0:
                sc = cfg.score_calculator
                score = sc.calculate_score(self.model) if sc else 0.0
                score_vs_epoch[epoch - 1] = score
                if sc is not None and score < best_score:
                    best_score = score
                    best_epoch = epoch - 1
                    cfg.model_saver.save_best_model(self.model, score)
                if cfg.save_last_model:
                    cfg.model_saver.save_latest_model(self.model, score)
                if self.listener:
                    self.listener.on_epoch(epoch - 1, score, cfg, self.model)

                for c in cfg.epoch_termination_conditions:
                    if c.terminate(epoch - 1, score):
                        result = EarlyStoppingResult(
                            TerminationReason.EPOCH_TERMINATION_CONDITION,
                            repr(c), score_vs_epoch, best_epoch, best_score,
                            epoch, cfg.model_saver.get_best_model())
                        if self.listener:
                            self.listener.on_completion(result)
                        return result


class EarlyStoppingParallelTrainer(EarlyStoppingTrainer):
    """Early stopping with data-parallel epochs (reference deeplearning4j-
    scaleout EarlyStoppingParallelTrainer.java:49 — each epoch trains through
    ParallelWrapper instead of single-device fit; scoring/saving/termination
    logic is shared with the base trainer)."""

    def __init__(self, config, model, iterator, workers=None, listener=None,
                 averaging_frequency: int = 1, mesh=None):
        super().__init__(config, model, iterator, listener)
        from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper

        self.wrapper = ParallelWrapper(
            model, workers=workers, averaging_frequency=averaging_frequency,
            prefetch=0, mesh=mesh)

    def _run_epoch(self, cfg):
        from deeplearning4j_tpu.datasets.iterators import ExistingDataSetIterator

        if self.wrapper.averaging_frequency > 1:
            # local-SGD semantics need the whole epoch in one wrapper.fit()
            # (per-batch calls would force an averaging sync at each fit()
            # end); divergence checks run once at epoch end here.
            self.wrapper.fit(self.iterator, epochs=1)
            return self._check_iteration_termination(cfg,
                                                     self.model.score_value)
        # Per-minibatch termination checks (divergence guards must abort
        # promptly, as in the base trainer): feed the wrapper one global
        # batch at a time — the sharded step stays jit-cached across calls.
        for ds in self.iterator:
            self.wrapper.fit(ExistingDataSetIterator([ds]), epochs=1)
            fired = self._check_iteration_termination(cfg,
                                                      self.model.score_value)
            if fired is not None:
                return fired
        return None


# Back-compat aliases mirroring the reference class names.
EarlyStoppingGraphTrainer = EarlyStoppingTrainer
