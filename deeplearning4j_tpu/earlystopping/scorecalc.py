"""Score calculators (reference earlystopping/scorecalc/DataSetLossCalculator.java).

One class serves both MultiLayerNetwork and ComputationGraph (the reference needs
DataSetLossCalculator vs DataSetLossCalculatorCG because of Java generics only).
"""
from __future__ import annotations


class ScoreCalculator:
    def calculate_score(self, model) -> float:
        raise NotImplementedError


class DataSetLossCalculator(ScoreCalculator):
    """Average loss over a held-out iterator, weighted by example count when
    ``average=True`` (reference DataSetLossCalculator.java:55-77)."""

    def __init__(self, iterator, average: bool = True):
        self.iterator = iterator
        self.average = average

    def calculate_score(self, model) -> float:
        from deeplearning4j_tpu.nn.graph_network import ComputationGraph, MultiDataSet

        if hasattr(self.iterator, "reset"):
            self.iterator.reset()
        total, n = 0.0, 0
        for ds in self.iterator:
            if isinstance(model, ComputationGraph):
                mds = (ds if isinstance(ds, MultiDataSet)
                       else MultiDataSet([ds.features], [ds.labels]))
                score = model.score(mds)
                examples = mds.num_examples()
            else:
                score = model.score(ds.features, ds.labels)
                examples = int(ds.features.shape[0])
            total += score * examples
            n += examples
        if n == 0:
            return 0.0
        return total / n if self.average else total
