"""EarlyStoppingConfiguration + result types.

Reference: earlystopping/EarlyStoppingConfiguration.java (builder with
epoch/iteration termination conditions, score calculator, model saver,
saveLastModel, evaluateEveryNEpochs) and EarlyStoppingResult.java.
"""
from __future__ import annotations

import dataclasses
import enum
from typing import Dict, List, Optional

from deeplearning4j_tpu.earlystopping.savers import (
    EarlyStoppingModelSaver, InMemoryModelSaver,
)
from deeplearning4j_tpu.earlystopping.scorecalc import ScoreCalculator
from deeplearning4j_tpu.earlystopping.termination import (
    EpochTerminationCondition, IterationTerminationCondition,
)


class TerminationReason(enum.Enum):
    ERROR = "Error"
    ITERATION_TERMINATION_CONDITION = "IterationTerminationCondition"
    EPOCH_TERMINATION_CONDITION = "EpochTerminationCondition"


@dataclasses.dataclass
class EarlyStoppingResult:
    termination_reason: TerminationReason
    termination_details: str
    score_vs_epoch: Dict[int, float]
    best_model_epoch: int
    best_model_score: float
    total_epochs: int
    best_model: object

    def __repr__(self):
        return (f"EarlyStoppingResult(terminationReason={self.termination_reason},"
                f" details={self.termination_details},"
                f" bestModelEpoch={self.best_model_epoch},"
                f" bestModelScore={self.best_model_score},"
                f" totalEpochs={self.total_epochs})")


class EarlyStoppingConfiguration:
    def __init__(self, epoch_termination_conditions=None,
                 iteration_termination_conditions=None,
                 score_calculator: Optional[ScoreCalculator] = None,
                 model_saver: Optional[EarlyStoppingModelSaver] = None,
                 save_last_model: bool = False,
                 evaluate_every_n_epochs: int = 1):
        self.epoch_termination_conditions: List[EpochTerminationCondition] = (
            list(epoch_termination_conditions or []))
        self.iteration_termination_conditions: List[IterationTerminationCondition] = (
            list(iteration_termination_conditions or []))
        self.score_calculator = score_calculator
        self.model_saver = model_saver or InMemoryModelSaver()
        self.save_last_model = save_last_model
        self.evaluate_every_n_epochs = evaluate_every_n_epochs

    @staticmethod
    def builder() -> "EarlyStoppingConfigurationBuilder":
        return EarlyStoppingConfigurationBuilder()


class EarlyStoppingConfigurationBuilder:
    """Fluent builder (reference EarlyStoppingConfiguration.Builder:64)."""

    def __init__(self):
        self._epoch: list = []
        self._iteration: list = []
        self._score_calculator = None
        self._saver = None
        self._save_last = False
        self._every_n = 1

    def epoch_termination_conditions(self, *conds) -> "EarlyStoppingConfigurationBuilder":
        self._epoch.extend(conds)
        return self

    def iteration_termination_conditions(self, *conds) -> "EarlyStoppingConfigurationBuilder":
        self._iteration.extend(conds)
        return self

    def score_calculator(self, calc) -> "EarlyStoppingConfigurationBuilder":
        self._score_calculator = calc
        return self

    def model_saver(self, saver) -> "EarlyStoppingConfigurationBuilder":
        self._saver = saver
        return self

    def save_last_model(self, flag: bool = True) -> "EarlyStoppingConfigurationBuilder":
        self._save_last = flag
        return self

    def evaluate_every_n_epochs(self, n: int) -> "EarlyStoppingConfigurationBuilder":
        self._every_n = n
        return self

    def build(self) -> EarlyStoppingConfiguration:
        return EarlyStoppingConfiguration(
            epoch_termination_conditions=self._epoch,
            iteration_termination_conditions=self._iteration,
            score_calculator=self._score_calculator,
            model_saver=self._saver,
            save_last_model=self._save_last,
            evaluate_every_n_epochs=self._every_n,
        )
