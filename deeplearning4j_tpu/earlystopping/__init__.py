from deeplearning4j_tpu.earlystopping.config import (
    EarlyStoppingConfiguration, EarlyStoppingResult, TerminationReason,
)
from deeplearning4j_tpu.earlystopping.savers import (
    InMemoryModelSaver, LocalFileModelSaver,
)
from deeplearning4j_tpu.earlystopping.scorecalc import (
    DataSetLossCalculator, ScoreCalculator,
)
from deeplearning4j_tpu.earlystopping.termination import (
    BestScoreEpochTerminationCondition, InvalidScoreIterationTerminationCondition,
    MaxEpochsTerminationCondition, MaxScoreIterationTerminationCondition,
    MaxTimeIterationTerminationCondition, ScoreImprovementEpochTerminationCondition,
)
from deeplearning4j_tpu.earlystopping.trainer import EarlyStoppingTrainer

__all__ = [
    "EarlyStoppingConfiguration", "EarlyStoppingResult", "TerminationReason",
    "InMemoryModelSaver", "LocalFileModelSaver", "ScoreCalculator",
    "DataSetLossCalculator", "MaxEpochsTerminationCondition",
    "MaxTimeIterationTerminationCondition", "MaxScoreIterationTerminationCondition",
    "InvalidScoreIterationTerminationCondition", "ScoreImprovementEpochTerminationCondition",
    "BestScoreEpochTerminationCondition", "EarlyStoppingTrainer",
]
