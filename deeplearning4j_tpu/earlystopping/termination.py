"""Termination conditions for early stopping.

Reference: earlystopping/termination/*.java — epoch conditions receive
(epoch, score); iteration conditions receive the latest minibatch score.
"""
from __future__ import annotations

import time


class EpochTerminationCondition:
    def initialize(self) -> None:
        pass

    def terminate(self, epoch: int, score: float) -> bool:
        raise NotImplementedError


class IterationTerminationCondition:
    def initialize(self) -> None:
        pass

    def terminate(self, score: float) -> bool:
        raise NotImplementedError


class MaxEpochsTerminationCondition(EpochTerminationCondition):
    """Stop after N epochs (reference MaxEpochsTerminationCondition.java)."""

    def __init__(self, max_epochs: int):
        if max_epochs <= 0:
            raise ValueError("max_epochs must be > 0")
        self.max_epochs = max_epochs

    def terminate(self, epoch: int, score: float) -> bool:
        return epoch + 1 >= self.max_epochs

    def __repr__(self):
        return f"MaxEpochsTerminationCondition({self.max_epochs})"


class ScoreImprovementEpochTerminationCondition(EpochTerminationCondition):
    """Stop when score hasn't improved (by min_improvement) in N epochs
    (reference ScoreImprovementEpochTerminationCondition.java)."""

    def __init__(self, max_epochs_without_improvement: int,
                 min_improvement: float = 0.0):
        self.max_epochs_without_improvement = max_epochs_without_improvement
        self.min_improvement = min_improvement
        self.best_score = None
        self.epochs_without = 0

    def initialize(self) -> None:
        self.best_score = None
        self.epochs_without = 0

    def terminate(self, epoch: int, score: float) -> bool:
        if self.best_score is None or self.best_score - score > self.min_improvement:
            self.best_score = score if self.best_score is None else min(
                self.best_score, score)
            self.epochs_without = 0
            return False
        self.epochs_without += 1
        return self.epochs_without > self.max_epochs_without_improvement

    def __repr__(self):
        return (f"ScoreImprovementEpochTerminationCondition("
                f"{self.max_epochs_without_improvement}, {self.min_improvement})")


class BestScoreEpochTerminationCondition(EpochTerminationCondition):
    """Stop once score drops at/below a target (reference
    BestScoreEpochTerminationCondition.java — lesserBetter semantics)."""

    def __init__(self, best_expected_score: float, lesser_better: bool = True):
        self.best_expected_score = best_expected_score
        self.lesser_better = lesser_better

    def terminate(self, epoch: int, score: float) -> bool:
        if self.lesser_better:
            return score < self.best_expected_score
        return score > self.best_expected_score

    def __repr__(self):
        return f"BestScoreEpochTerminationCondition({self.best_expected_score})"


class MaxTimeIterationTerminationCondition(IterationTerminationCondition):
    """Wall-clock budget (reference MaxTimeIterationTerminationCondition.java)."""

    def __init__(self, max_seconds: float):
        self.max_seconds = max_seconds
        self._end = None

    def initialize(self) -> None:
        self._end = time.monotonic() + self.max_seconds

    def terminate(self, score: float) -> bool:
        return self._end is not None and time.monotonic() >= self._end

    def __repr__(self):
        return f"MaxTimeIterationTerminationCondition({self.max_seconds}s)"


class MaxScoreIterationTerminationCondition(IterationTerminationCondition):
    """Stop if minibatch score exceeds a bound — divergence guard
    (reference MaxScoreIterationTerminationCondition.java)."""

    def __init__(self, max_score: float):
        self.max_score = max_score

    def terminate(self, score: float) -> bool:
        return score > self.max_score

    def __repr__(self):
        return f"MaxScoreIterationTerminationCondition({self.max_score})"


class InvalidScoreIterationTerminationCondition(IterationTerminationCondition):
    """Stop on NaN/Inf score (reference InvalidScoreIterationTerminationCondition.java
    — the reference's only failure-detection mechanism, SURVEY.md §5). The
    predicate is shared with the training-health monitor so early stopping
    and NanAlertListener agree on what "invalid" means."""

    def terminate(self, score: float) -> bool:
        from deeplearning4j_tpu.observability.health import is_invalid_score
        return is_invalid_score(score)

    def __repr__(self):
        return "InvalidScoreIterationTerminationCondition()"
