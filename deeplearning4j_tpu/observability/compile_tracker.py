"""Compile/retrace tracker for the framework's jit seams.

JAX recompiles silently: a `DtypePolicy` flip, a stray Python-float hparam,
or an unpadded final batch each mint a new executable, and the only symptom
is a step that takes seconds instead of milliseconds. The reference never had
this failure mode (ND4J ops are eager), so its listener pipeline has no slot
for it. This tracker closes the gap: every policy-keyed cache miss in
``LazyScore._jit`` (multilayer + graph networks), every parallel-wrapper /
training-master / pipeline-trainer program build, goes through ``wrap()``,
which records the compile — cache key, wall time, triggering abstract
shapes, active dtype-policy key — and raises a rate-limited warning when the
same function recompiles often enough to look like a retrace storm.

Two timing sources are recorded when available:

* **wall**: ``perf_counter`` around the first call for a new abstract
  signature — dispatch + trace + lower + compile as the user experiences it.
* **backend**: ``jax.monitoring`` duration events whose key mentions
  compile/lowering, attributed to whichever tracked call is active on this
  thread. This isolates genuine XLA compile time from tracing overhead.

Steps are counted by the fit loops calling ``note_step()``; the storm window
is measured in those steps so the warning threshold reads as "N compiles of
one function within M training steps" regardless of dispatch fusion.
"""
from __future__ import annotations

import logging
import os
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np

from .metrics import global_registry
from .names import (JIT_BACKEND_COMPILE_SECONDS, JIT_COMPILE_SECONDS,
                    JIT_COMPILE_TOTAL, RECOMPILE_STORM_WARNINGS_TOTAL,
                    STEP_MFU)

log = logging.getLogger(__name__)

#: storm defaults: >= STORM_THRESHOLD compiles of one function within
#: STORM_WINDOW_STEPS training steps -> one warning (then suppressed for a
#: window so a pathological loop logs once per window, not once per step)
STORM_THRESHOLD = 3
STORM_WINDOW_STEPS = 200

_MAX_EVENTS = 1000

#: assumed accelerator peak when nothing is configured and the backend is a
#: TPU (v4 chip bf16 peak, matching bench.py); on CPU the default is "peak
#: unknown" and the MFU gauge stays silent
_DEFAULT_TPU_PEAK_FLOPS = 197e12


def _abstractify_for_lowering(x: Any) -> Any:
    """Array leaves -> ShapeDtypeStruct so a compiled program can be
    re-lowered for cost analysis without keeping live buffers alive."""
    import jax

    if hasattr(x, "shape") and hasattr(x, "dtype"):
        return jax.ShapeDtypeStruct(tuple(x.shape), x.dtype)
    return x


def _abstract(x: Any) -> Any:
    """Abstract one argument leaf the way jit's cache does: arrays by
    (shape, dtype), everything else by value (static/hashable) or type."""
    if hasattr(x, "shape") and hasattr(x, "dtype"):
        return ("array", tuple(x.shape), str(x.dtype))
    try:
        hash(x)
        return x
    except TypeError:
        return type(x).__name__


def _signature(args: tuple, kwargs: dict) -> Tuple:
    import jax

    leaves, treedef = jax.tree_util.tree_flatten((args, kwargs))
    return (tuple(_abstract(l) for l in leaves), str(treedef))


def _policy_key() -> Tuple:
    from deeplearning4j_tpu import common

    return common.policy_key()


def _normalize_cost(analysis: Any) -> Optional[dict]:
    """XLA cost_analysis() -> {str: number} (it returns a list on some
    backends/versions, a mapping on others)."""
    if isinstance(analysis, (list, tuple)):
        analysis = analysis[0] if analysis else None
    if analysis is None:
        return None
    return {str(k): v for k, v in dict(analysis).items()
            if isinstance(v, (int, float))}


def cost_analysis_flops(fn: Callable, *args, **kwargs) -> float:
    """One-dispatch FLOP count of ``fn`` for these (possibly abstract)
    args, without a second backend compile. Shared by the MFU path and
    bench.py. Accepts a compile_cache ``CachedProgram`` (reuses its
    resolved executable), or any lowerable (jitted) fn — the cost comes
    from ``Lowered.cost_analysis()``; ``.compile()`` only as API-drift
    fallback. Returns 0.0 when no analysis is available."""
    try:
        if hasattr(fn, "cost_flops"):
            flops = fn.cost_flops(*args, **kwargs)
            if flops is not None:
                return max(0.0, float(flops))
        lowered = fn.lower(*args, **kwargs)
        try:
            cost = _normalize_cost(lowered.cost_analysis())
        except Exception:
            cost = _normalize_cost(lowered.compile().cost_analysis())
        return max(0.0, float((cost or {}).get("flops", 0.0)))
    except Exception:
        return 0.0


class CompileTracker:
    """Records compile events and watches for retrace storms.

    One process-global instance (``global_tracker()``) is shared by every
    seam; tests may construct private ones and lower the storm knobs.
    """

    def __init__(self, registry=None, storm_threshold: int = STORM_THRESHOLD,
                 storm_window_steps: int = STORM_WINDOW_STEPS):
        self._lock = threading.Lock()
        self._registry = registry
        self.storm_threshold = storm_threshold
        self.storm_window_steps = storm_window_steps
        self._step = 0
        #: fn name -> deque of step indices at which it compiled
        self._compile_steps: Dict[str, deque] = {}
        #: fn name -> step of last storm warning (rate limit)
        self._last_warned: Dict[str, int] = {}
        self.events: deque = deque(maxlen=_MAX_EVENTS)
        #: fn name -> (jitted fn, abstract args, abstract kwargs) captured at
        #: first call, so cost analysis can be computed lazily without live
        #: buffers
        self._lowerable: Dict[str, Tuple] = {}
        #: fn name -> cost_analysis dict (None caches "analysis unavailable"
        #: so a failing lower is attempted once, not every step)
        self._cost: Dict[str, Optional[dict]] = {}
        #: fn name -> last Compiled executable noted by an AOT seam
        #: (compile_cache), so flops_for reads its cost_analysis directly
        #: instead of re-lowering
        self._executables: Dict[str, Any] = {}
        #: fn name -> perf_counter of the previous note_step(fn=...) — the
        #: rolling-MFU time base
        self._mfu_last: Dict[str, float] = {}
        self._backend_peak: Optional[float] = None
        self._backend_peak_resolved = False
        # thread-local stack of active tracked calls, so jax.monitoring
        # compile-duration events can be attributed to the right function
        self._active = threading.local()
        self._monitoring_hooked = False

    # ------------------------------------------------------------ registry
    @property
    def registry(self):
        return self._registry if self._registry is not None else global_registry()

    def _metrics(self):
        reg = self.registry
        return (
            reg.counter(JIT_COMPILE_TOTAL,
                        "jit/pjit compiles recorded at framework seams"),
            reg.histogram(JIT_COMPILE_SECONDS,
                          "wall time of first-call trace+lower+compile"),
            reg.histogram(JIT_BACKEND_COMPILE_SECONDS,
                          "backend compile time from jax.monitoring events"),
            reg.counter(RECOMPILE_STORM_WARNINGS_TOTAL,
                        "rate-limited retrace-storm warnings emitted"),
        )

    # ------------------------------------------------------------ stepping
    def note_step(self, n: int = 1, fn: Optional[str] = None) -> None:
        """Advance the training-step clock (fit loops call this; a K-step
        fused dispatch advances by K). When ``fn`` names the wrapped program
        that just dispatched, a rolling MFU sample is also recorded — see
        ``_note_mfu``."""
        with self._lock:
            self._step += n
        if fn is not None:
            self._note_mfu(fn, n)

    @property
    def step(self) -> int:
        return self._step

    # ----------------------------------------------------------------- mfu
    def peak_flops(self) -> Optional[float]:
        """Accelerator peak FLOP/s for MFU: ``DL4J_PEAK_FLOPS`` (or bench's
        ``BENCH_PEAK_FLOPS``) if set, else a TPU default when the backend is
        a TPU, else None — on CPU the MFU gauge deliberately stays silent
        rather than report a meaningless ratio."""
        env = os.environ.get("DL4J_PEAK_FLOPS") \
            or os.environ.get("BENCH_PEAK_FLOPS")
        if env:
            try:
                return float(env)
            except ValueError:
                log.warning("unparseable peak-FLOPS override %r", env)
        if not self._backend_peak_resolved:
            self._backend_peak_resolved = True
            try:
                import jax

                if jax.default_backend() == "tpu":
                    self._backend_peak = _DEFAULT_TPU_PEAK_FLOPS
            except Exception:  # pragma: no cover - no backend available  # lint: swallowed-exception-ok (MFU stays disabled when the backend is unknown)
                pass
        return self._backend_peak

    def note_executable(self, name: str, compiled: Any) -> None:
        """An AOT seam (compile_cache) built or loaded an executable for
        ``name``: keep it so ``flops_for`` reads its cost analysis directly
        — no second lowering, no second compile."""
        with self._lock:
            self._executables[name] = compiled
            self._cost.pop(name, None)

    def flops_for(self, name: str) -> Optional[float]:
        """FLOPs of ONE training step of the wrapped program ``name``.
        Preference order: a noted executable's own ``cost_analysis()``
        (zero extra work), else the lowering captured at first call —
        ``Lowered.cost_analysis()`` never triggers a second backend
        compile; ``.compile()`` remains only as an API-drift fallback.
        Computed lazily once per (re)compile and cached; XLA counts a scan
        body once regardless of trip count (pinned by test), so the value is
        per-step even for the K-step fused programs. Returns None when no
        analysis is available (never retried until the next compile)."""
        with self._lock:
            if name in self._cost:
                cost = self._cost[name]
                return None if cost is None else cost.get("flops")
            exe = self._executables.get(name)
            lowerable = self._lowerable.get(name)
        cost = None
        if exe is not None:
            try:
                cost = _normalize_cost(exe.cost_analysis())
            except Exception as e:
                log.debug("executable cost analysis failed for %s: %r",
                          name, e)
        if cost is None and lowerable is not None:
            fn, aargs, akwargs = lowerable
            try:
                lowered = fn.lower(*aargs, **akwargs)
                try:
                    cost = _normalize_cost(lowered.cost_analysis())
                except Exception:
                    cost = _normalize_cost(lowered.compile().cost_analysis())
            except Exception as e:  # non-jit wrappee, API drift: MFU off
                log.debug("cost analysis unavailable for %s: %r", name, e)
        with self._lock:
            self._cost[name] = cost
        return None if cost is None else cost.get("flops")

    def _note_mfu(self, fn_name: str, n: int) -> None:
        now = time.perf_counter()
        last = self._mfu_last.get(fn_name)
        self._mfu_last[fn_name] = now
        if last is None:
            return
        elapsed = now - last
        peak = self.peak_flops()
        if elapsed <= 0 or not peak:
            return
        flops = self.flops_for(fn_name)
        if not flops:
            return
        mfu = min(1.0, (flops * n) / (elapsed * peak))
        self.registry.gauge(
            STEP_MFU, "rolling model FLOP utilization per dispatched "
            "program").labels(fn=fn_name).set(mfu)

    # -------------------------------------------------- monitoring bridge
    def _ensure_monitoring(self) -> None:
        if self._monitoring_hooked:
            return
        self._monitoring_hooked = True
        try:
            from jax import monitoring as jmon

            def _on_duration(event: str, duration: float, **kw):
                if "compile" not in event and "lower" not in event:
                    return
                stack = getattr(self._active, "stack", None)
                if not stack:
                    return
                name = stack[-1]
                _, _, backend_hist, _ = self._metrics()
                backend_hist.labels(fn=name).observe(duration)

            jmon.register_event_duration_secs_listener(_on_duration)
        except Exception:  # pragma: no cover - monitoring API moved/absent  # lint: swallowed-exception-ok (tracker degrades to wall timing only)
            pass

    # ------------------------------------------------------------ tracking
    def record_compile(self, name: str, *, cache_key: Any = None,
                       wall_s: float = 0.0, shapes: Any = None,
                       policy: Any = None, cache_hit: bool = False) -> dict:
        """Record one compile event (the wrap() path calls this; seams that
        build executables eagerly may call it directly). ``cache_hit=True``
        marks a warm load from the executable cache: counted and flight-
        recorded like any compile, but excluded from storm accounting —
        warm loads are the fix for compile storms, not a symptom of one."""
        total, wall_hist, _, storm_total = self._metrics()
        total.labels(fn=name).inc()
        if wall_s:
            wall_hist.labels(fn=name).observe(wall_s)
        if policy is None:
            try:
                policy = _policy_key()
            except Exception:
                policy = None
        with self._lock:
            step = self._step
            event = {"fn": name, "step": step, "wall_s": wall_s,
                     "cache_key": repr(cache_key), "shapes": repr(shapes),
                     "policy": repr(policy), "cache_hit": cache_hit}
            self.events.append(event)
            storm = False
            if not cache_hit:
                dq = self._compile_steps.setdefault(
                    name, deque(maxlen=max(64, self.storm_threshold * 4)))
                dq.append(step)
                lo = step - self.storm_window_steps
                recent = sum(1 for s in dq if s >= lo)
                warned = self._last_warned.get(name)
                storm = (recent >= self.storm_threshold
                         and (warned is None
                              or step - warned > self.storm_window_steps))
                if storm:
                    self._last_warned[name] = step
        try:
            from .flight_recorder import global_recorder

            global_recorder().record("compile", **event)
        except Exception:  # pragma: no cover - recorder import cycle guard  # lint: swallowed-exception-ok (recorder forwarding is best-effort)
            pass
        if storm:
            storm_total.labels(fn=name).inc()
            log.warning(
                "recompile storm: %s compiled %d times in the last %d steps "
                "(step %d, policy=%s) — check for shape churn or dtype-policy "
                "flips; further warnings suppressed for %d steps",
                name, recent, self.storm_window_steps, step, event["policy"],
                self.storm_window_steps)
        return event

    def wrap(self, name: str, fn: Callable, *,
             cache_key: Any = None) -> Callable:
        """Wrap a freshly-built jitted callable. The first call for each new
        abstract argument signature is timed and recorded as a compile; later
        calls with a seen signature pay one dict lookup and a tree-flatten.

        Seams create a NEW wrap per cache entry (``LazyScore._jit`` et al.),
        so a dtype-policy flip — which changes the cache key and rebuilds the
        jit — naturally lands here again and is counted as a fresh compile
        of the same ``name``, which is exactly what the storm detector
        watches for.
        """
        self._ensure_monitoring()
        seen: Dict[Tuple, bool] = {}
        tracker = self

        def tracked(*args, **kwargs):
            try:
                sig = _signature(args, kwargs)
            except Exception:
                sig = None
            if sig is not None and sig in seen:
                return fn(*args, **kwargs)
            stack = getattr(tracker._active, "stack", None)
            if stack is None:
                stack = tracker._active.stack = []
            stack.append(name)
            import time as _time
            t0 = _time.perf_counter()
            try:
                out = fn(*args, **kwargs)
            finally:
                stack.pop()
            wall = _time.perf_counter() - t0
            if sig is not None:
                seen[sig] = True
            tracker._capture_lowerable(name, fn, args, kwargs)
            tracker.record_compile(name, cache_key=cache_key, wall_s=wall,
                                   shapes=None if sig is None else sig[0])
            return out

        tracked.__wrapped__ = fn  # type: ignore[attr-defined]
        tracked.__name__ = getattr(fn, "__name__", name)
        return tracked

    def _capture_lowerable(self, name: str, fn: Callable, args: tuple,
                           kwargs: dict) -> None:
        """Remember the abstract signature of a freshly-compiled program so
        ``flops_for`` can re-lower it later; invalidates any cached cost
        analysis for the name (shapes may have changed)."""
        try:
            import jax

            aargs, akwargs = jax.tree_util.tree_map(
                _abstractify_for_lowering, (args, kwargs))
        except Exception:  # unflattenable args: cost analysis just stays off  # lint: swallowed-exception-ok (MFU degrades to unavailable for this program)
            return
        with self._lock:
            self._lowerable[name] = (fn, aargs, akwargs)
            self._cost.pop(name, None)

    # ------------------------------------------------------------ export
    def snapshot_events(self) -> list:
        with self._lock:
            return list(self.events)

    def snapshot_cost_analyses(self) -> dict:
        """Cached per-program cost analyses (no new lowering/compiling —
        safe to call from a crash dump)."""
        with self._lock:
            return {name: (dict(cost) if cost else None)
                    for name, cost in self._cost.items()}


_GLOBAL = CompileTracker()


def global_tracker() -> CompileTracker:
    """THE process-global tracker the framework seams report into."""
    return _GLOBAL
