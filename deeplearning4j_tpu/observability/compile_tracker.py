"""Compile/retrace tracker for the framework's jit seams.

JAX recompiles silently: a `DtypePolicy` flip, a stray Python-float hparam,
or an unpadded final batch each mint a new executable, and the only symptom
is a step that takes seconds instead of milliseconds. The reference never had
this failure mode (ND4J ops are eager), so its listener pipeline has no slot
for it. This tracker closes the gap: every policy-keyed cache miss in
``LazyScore._jit`` (multilayer + graph networks), every parallel-wrapper /
training-master / pipeline-trainer program build, goes through ``wrap()``,
which records the compile — cache key, wall time, triggering abstract
shapes, active dtype-policy key — and raises a rate-limited warning when the
same function recompiles often enough to look like a retrace storm.

Two timing sources are recorded when available:

* **wall**: ``perf_counter`` around the first call for a new abstract
  signature — dispatch + trace + lower + compile as the user experiences it.
* **backend**: ``jax.monitoring`` duration events whose key mentions
  compile/lowering, attributed to whichever tracked call is active on this
  thread. This isolates genuine XLA compile time from tracing overhead.

Steps are counted by the fit loops calling ``note_step()``; the storm window
is measured in those steps so the warning threshold reads as "N compiles of
one function within M training steps" regardless of dispatch fusion.
"""
from __future__ import annotations

import logging
import threading
from collections import deque
from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np

from .metrics import global_registry
from .names import (JIT_BACKEND_COMPILE_SECONDS, JIT_COMPILE_SECONDS,
                    JIT_COMPILE_TOTAL, RECOMPILE_STORM_WARNINGS_TOTAL)

log = logging.getLogger(__name__)

#: storm defaults: >= STORM_THRESHOLD compiles of one function within
#: STORM_WINDOW_STEPS training steps -> one warning (then suppressed for a
#: window so a pathological loop logs once per window, not once per step)
STORM_THRESHOLD = 3
STORM_WINDOW_STEPS = 200

_MAX_EVENTS = 1000


def _abstract(x: Any) -> Any:
    """Abstract one argument leaf the way jit's cache does: arrays by
    (shape, dtype), everything else by value (static/hashable) or type."""
    if hasattr(x, "shape") and hasattr(x, "dtype"):
        return ("array", tuple(x.shape), str(x.dtype))
    try:
        hash(x)
        return x
    except TypeError:
        return type(x).__name__


def _signature(args: tuple, kwargs: dict) -> Tuple:
    import jax

    leaves, treedef = jax.tree_util.tree_flatten((args, kwargs))
    return (tuple(_abstract(l) for l in leaves), str(treedef))


def _policy_key() -> Tuple:
    from deeplearning4j_tpu import common

    return common.policy_key()


class CompileTracker:
    """Records compile events and watches for retrace storms.

    One process-global instance (``global_tracker()``) is shared by every
    seam; tests may construct private ones and lower the storm knobs.
    """

    def __init__(self, registry=None, storm_threshold: int = STORM_THRESHOLD,
                 storm_window_steps: int = STORM_WINDOW_STEPS):
        self._lock = threading.Lock()
        self._registry = registry
        self.storm_threshold = storm_threshold
        self.storm_window_steps = storm_window_steps
        self._step = 0
        #: fn name -> deque of step indices at which it compiled
        self._compile_steps: Dict[str, deque] = {}
        #: fn name -> step of last storm warning (rate limit)
        self._last_warned: Dict[str, int] = {}
        self.events: deque = deque(maxlen=_MAX_EVENTS)
        # thread-local stack of active tracked calls, so jax.monitoring
        # compile-duration events can be attributed to the right function
        self._active = threading.local()
        self._monitoring_hooked = False

    # ------------------------------------------------------------ registry
    @property
    def registry(self):
        return self._registry if self._registry is not None else global_registry()

    def _metrics(self):
        reg = self.registry
        return (
            reg.counter(JIT_COMPILE_TOTAL,
                        "jit/pjit compiles recorded at framework seams"),
            reg.histogram(JIT_COMPILE_SECONDS,
                          "wall time of first-call trace+lower+compile"),
            reg.histogram(JIT_BACKEND_COMPILE_SECONDS,
                          "backend compile time from jax.monitoring events"),
            reg.counter(RECOMPILE_STORM_WARNINGS_TOTAL,
                        "rate-limited retrace-storm warnings emitted"),
        )

    # ------------------------------------------------------------ stepping
    def note_step(self, n: int = 1) -> None:
        """Advance the training-step clock (fit loops call this; a K-step
        fused dispatch advances by K)."""
        with self._lock:
            self._step += n

    @property
    def step(self) -> int:
        return self._step

    # -------------------------------------------------- monitoring bridge
    def _ensure_monitoring(self) -> None:
        if self._monitoring_hooked:
            return
        self._monitoring_hooked = True
        try:
            from jax import monitoring as jmon

            def _on_duration(event: str, duration: float, **kw):
                if "compile" not in event and "lower" not in event:
                    return
                stack = getattr(self._active, "stack", None)
                if not stack:
                    return
                name = stack[-1]
                _, _, backend_hist, _ = self._metrics()
                backend_hist.labels(fn=name).observe(duration)

            jmon.register_event_duration_secs_listener(_on_duration)
        except Exception:  # pragma: no cover - monitoring API moved/absent  # lint: swallowed-exception-ok (tracker degrades to wall timing only)
            pass

    # ------------------------------------------------------------ tracking
    def record_compile(self, name: str, *, cache_key: Any = None,
                       wall_s: float = 0.0, shapes: Any = None,
                       policy: Any = None) -> dict:
        """Record one compile event (the wrap() path calls this; seams that
        build executables eagerly may call it directly)."""
        total, wall_hist, _, storm_total = self._metrics()
        total.labels(fn=name).inc()
        if wall_s:
            wall_hist.labels(fn=name).observe(wall_s)
        if policy is None:
            try:
                policy = _policy_key()
            except Exception:
                policy = None
        with self._lock:
            step = self._step
            event = {"fn": name, "step": step, "wall_s": wall_s,
                     "cache_key": repr(cache_key), "shapes": repr(shapes),
                     "policy": repr(policy)}
            self.events.append(event)
            dq = self._compile_steps.setdefault(
                name, deque(maxlen=max(64, self.storm_threshold * 4)))
            dq.append(step)
            lo = step - self.storm_window_steps
            recent = sum(1 for s in dq if s >= lo)
            warned = self._last_warned.get(name)
            storm = (recent >= self.storm_threshold
                     and (warned is None
                          or step - warned > self.storm_window_steps))
            if storm:
                self._last_warned[name] = step
        if storm:
            storm_total.labels(fn=name).inc()
            log.warning(
                "recompile storm: %s compiled %d times in the last %d steps "
                "(step %d, policy=%s) — check for shape churn or dtype-policy "
                "flips; further warnings suppressed for %d steps",
                name, recent, self.storm_window_steps, step, event["policy"],
                self.storm_window_steps)
        return event

    def wrap(self, name: str, fn: Callable, *,
             cache_key: Any = None) -> Callable:
        """Wrap a freshly-built jitted callable. The first call for each new
        abstract argument signature is timed and recorded as a compile; later
        calls with a seen signature pay one dict lookup and a tree-flatten.

        Seams create a NEW wrap per cache entry (``LazyScore._jit`` et al.),
        so a dtype-policy flip — which changes the cache key and rebuilds the
        jit — naturally lands here again and is counted as a fresh compile
        of the same ``name``, which is exactly what the storm detector
        watches for.
        """
        self._ensure_monitoring()
        seen: Dict[Tuple, bool] = {}
        tracker = self

        def tracked(*args, **kwargs):
            try:
                sig = _signature(args, kwargs)
            except Exception:
                sig = None
            if sig is not None and sig in seen:
                return fn(*args, **kwargs)
            stack = getattr(tracker._active, "stack", None)
            if stack is None:
                stack = tracker._active.stack = []
            stack.append(name)
            import time as _time
            t0 = _time.perf_counter()
            try:
                out = fn(*args, **kwargs)
            finally:
                stack.pop()
            wall = _time.perf_counter() - t0
            if sig is not None:
                seen[sig] = True
            tracker.record_compile(name, cache_key=cache_key, wall_s=wall,
                                   shapes=None if sig is None else sig[0])
            return out

        tracked.__wrapped__ = fn  # type: ignore[attr-defined]
        tracked.__name__ = getattr(fn, "__name__", name)
        return tracked

    # ------------------------------------------------------------ export
    def snapshot_events(self) -> list:
        with self._lock:
            return list(self.events)


_GLOBAL = CompileTracker()


def global_tracker() -> CompileTracker:
    """THE process-global tracker the framework seams report into."""
    return _GLOBAL
