"""Training-health monitor: device-side NaN/divergence detection.

The reference stack diagnoses bad training with host-side listener idioms —
``NanScoreWatcher`` reads the score every iteration,
``InvalidScoreIterationTerminationCondition`` isnan/isinf-checks it — which
translate to a forced device sync per step under lazy dispatch. This module
keeps the judgment on the device: the step builders in ``nn/`` fuse a small
health summary (global grad norm, global param-update norm, non-finite grad
leaf count, loss) into the training step itself when a monitor is attached
and the cadence is due, so off-cadence steps are byte-identical to the
unmonitored program and the only host sync happens when a result is polled.

Flow per cadence-due step::

    train_step(..., health=True)  ->  (..., health_aux)   # on device
    monitor.offer(health_aux, it)                         # pack, no sync
    listener polls next iteration  ->  one np.asarray     # the only sync
        -> gauges, loss-EMA divergence heuristic, alarm -> recorder dump

``is_invalid_score`` is the single shared definition of "invalid" used by
the alarm path and by early stopping's
``InvalidScoreIterationTerminationCondition``.
"""
from __future__ import annotations

import logging
import math
import threading
from typing import Any, Dict, Optional

from .metrics import global_registry
from .names import (HEALTH_ALARMS_TOTAL, HEALTH_CHECKS_TOTAL,
                    HEALTH_GRAD_NORM, HEALTH_LOSS_EMA,
                    HEALTH_NONFINITE_GRADS, HEALTH_UPDATE_NORM)

log = logging.getLogger(__name__)

#: how often (in training steps) the fused health summary runs by default —
#: high enough that the extra reduce is noise, low enough that a NaN is
#: caught within a couple of seconds of wall time
DEFAULT_CADENCE = 50

#: packed-vector layout produced by ``health_terms`` / consumed by ``_resolve``
_PACK_FIELDS = ("grad_norm", "update_norm", "nonfinite_grads", "loss")


class TrainingDivergedError(RuntimeError):
    """Raised by ``NanAlertListener(raise_on_alarm=True)`` when the health
    monitor reports a non-finite or diverged training step."""


def is_invalid_score(score: Any) -> bool:
    """THE shared predicate for "this score means training is broken":
    None, NaN, or +/-inf. Early stopping and the NaN alarm both route
    through here so they can never disagree."""
    if score is None:
        return True
    try:
        value = float(score)
    except (TypeError, ValueError):
        return True
    return math.isnan(value) or math.isinf(value)


def health_terms(grads, params, new_params, loss):
    """Pure-jnp health summary, traced INSIDE the training step.

    Runs where grads, pre-update params, and post-update params all still
    exist as program values, so it composes with buffer donation (nothing is
    held across the step boundary) and costs one fused reduce. Returns a
    single packed f32 vector ordered as ``_PACK_FIELDS``.
    """
    import jax
    import jax.numpy as jnp

    f32 = jnp.float32
    grad_sq = f32(0.0)
    nonfinite = f32(0.0)
    for g in jax.tree_util.tree_leaves(grads):
        gf = g.astype(f32)
        grad_sq = grad_sq + jnp.sum(gf * gf)
        nonfinite = nonfinite + jnp.sum(~jnp.isfinite(gf)).astype(f32)
    upd_sq = f32(0.0)
    for p, q in zip(jax.tree_util.tree_leaves(params),
                    jax.tree_util.tree_leaves(new_params)):
        d = q.astype(f32) - p.astype(f32)
        upd_sq = upd_sq + jnp.sum(d * d)
    return jnp.stack([jnp.sqrt(grad_sq), jnp.sqrt(upd_sq), nonfinite,
                      jnp.asarray(loss, f32)])


class HealthMonitor:
    """Cadenced device-side health checks with a host-side alarm.

    Attach to a network with ``monitor.attach(net)`` (or assign
    ``net.health_monitor``); the fit loops then dispatch the health variant
    of the training step whenever ``due()``/``due_range()`` says a multiple
    of ``cadence`` falls in the dispatched range. Results arrive via
    ``offer()`` (device array, no sync) and are materialized by ``poll()``
    — one host transfer per cadence window, normally issued by
    ``NanAlertListener`` an iteration later, when the step has long
    completed.
    """

    def __init__(self, cadence: int = DEFAULT_CADENCE, *,
                 ema_alpha: float = 0.98, divergence_factor: float = 25.0,
                 min_ema_samples: int = 5, dump_on_alarm: bool = True,
                 recorder=None, registry=None):
        self.cadence = int(cadence)
        self.ema_alpha = float(ema_alpha)
        self.divergence_factor = float(divergence_factor)
        self.min_ema_samples = int(min_ema_samples)
        self.dump_on_alarm = dump_on_alarm
        self._recorder = recorder
        self._registry = registry
        self._lock = threading.Lock()
        self._pending = None  # (packed device vector, iteration)
        self._dumped = False
        self.loss_ema: Optional[float] = None
        self._ema_samples = 0
        self.checks = 0
        self.alarms = 0
        self.alarm: Optional[Dict[str, Any]] = None  # last alarm, sticky
        self.last: Optional[Dict[str, Any]] = None   # last resolved summary

    # ------------------------------------------------------------- wiring
    def attach(self, net):
        """Set this monitor as ``net.health_monitor``; returns the monitor
        (``hm = HealthMonitor(...).attach(net)``)."""
        net.health_monitor = self
        return self

    @property
    def registry(self):
        return self._registry if self._registry is not None \
            else global_registry()

    def _recorder_or_global(self):
        if self._recorder is not None:
            return self._recorder
        from .flight_recorder import global_recorder

        return global_recorder()

    # ------------------------------------------------------------ cadence
    def due(self, iteration: int) -> bool:
        """True when the step at ``iteration`` should carry the health
        summary."""
        return self.cadence > 0 and iteration % self.cadence == 0

    def due_range(self, start: int, n: int) -> bool:
        """True when any iteration in ``[start, start + n)`` is due — the
        K-step fused dispatchers use this to pick the health variant of the
        multistep program for the whole group."""
        return self.due_index(start, n) is not None

    def due_index(self, start: int, n: int) -> Optional[int]:
        """Offset within ``[start, start + n)`` of the first due iteration,
        or None — the dispatcher uses it to pick which row of the stacked
        ``(K, 4)`` health output to offer."""
        if self.cadence <= 0 or n <= 0:
            return None
        first_due = ((start + self.cadence - 1) // self.cadence) * self.cadence
        return first_due - start if first_due < start + n else None

    # ------------------------------------------------------------ results
    def offer(self, packed, iteration: int) -> None:
        """Accept the packed device vector from a completed health step.
        No host sync here: the array is parked until ``poll()``. If an
        earlier offer was never polled (no listener attached), it is
        resolved now — by this point its step has long finished, so the
        transfer is a copy, not a wait."""
        with self._lock:
            prev, self._pending = self._pending, (packed, int(iteration))
        if prev is not None:
            self._resolve(*prev)

    def poll(self) -> Optional[Dict[str, Any]]:
        """Materialize the pending health summary, if any; returns the alarm
        dict when this summary tripped the alarm, else None. The single
        host sync of the health path lives here, outside the fit loops'
        hot dispatch names."""
        with self._lock:
            pending, self._pending = self._pending, None
        if pending is None:
            return None
        return self._resolve(*pending)

    def _resolve(self, packed, iteration: int) -> Optional[Dict[str, Any]]:
        values = np_asarray(packed)
        summary = {k: float(v) for k, v in zip(_PACK_FIELDS, values)}
        summary["iteration"] = iteration
        reg = self.registry
        reg.gauge(HEALTH_GRAD_NORM,
                  "global grad L2 norm at the last health check").set(
                      summary["grad_norm"])
        reg.gauge(HEALTH_UPDATE_NORM,
                  "global param-update L2 norm at the last health check").set(
                      summary["update_norm"])
        reg.gauge(HEALTH_NONFINITE_GRADS,
                  "non-finite grad elements at the last health check").set(
                      summary["nonfinite_grads"])
        reg.counter(HEALTH_CHECKS_TOTAL,
                    "health summaries resolved on the host").inc()
        self.checks += 1
        loss = summary["loss"]
        why = None
        if summary["nonfinite_grads"] > 0:
            why = "nonfinite-grads"
        elif is_invalid_score(loss):
            why = "invalid-loss"
        elif not (math.isfinite(summary["grad_norm"])
                  and math.isfinite(summary["update_norm"])):
            why = "nonfinite-norms"
        else:
            if (self.loss_ema is not None
                    and self._ema_samples >= self.min_ema_samples
                    and loss > self.divergence_factor
                    * max(abs(self.loss_ema), 1e-8)):
                why = "loss-divergence"
            a = self.ema_alpha
            self.loss_ema = loss if self.loss_ema is None \
                else a * self.loss_ema + (1.0 - a) * loss
            self._ema_samples += 1
            reg.gauge(HEALTH_LOSS_EMA,
                      "EMA of the training loss at health checks").set(
                          self.loss_ema)
        self.last = summary
        if why is None:
            return None
        return self._raise_alarm(why, summary)

    def _raise_alarm(self, why: str, summary: Dict[str, Any]):
        alarm = dict(summary, why=why, ema=self.loss_ema)
        self.alarm = alarm
        self.alarms += 1
        self.registry.counter(
            HEALTH_ALARMS_TOTAL,
            "health alarms (non-finite or diverged training)").labels(
                why=why).inc()
        rec = self._recorder_or_global()
        rec.record("health_alarm", **alarm)
        log.error("health alarm at iteration %d: %s (loss=%g grad_norm=%g "
                  "update_norm=%g nonfinite_grads=%g ema=%s)",
                  summary["iteration"], why, summary["loss"],
                  summary["grad_norm"], summary["update_norm"],
                  summary["nonfinite_grads"], self.loss_ema)
        if self.dump_on_alarm and not self._dumped:
            if rec.dump(reason=f"health-alarm-{why}") is not None:
                self._dumped = True
        return alarm


def np_asarray(x):
    """Device -> host materialization for resolved health vectors, isolated
    here so the fit-path modules stay free of sync-looking calls."""
    import numpy as np

    return np.asarray(x, dtype=np.float64)


class NanAlertListener:
    """Listener that polls the attached ``HealthMonitor`` and turns alarms
    into action: record + flight-recorder dump (done by the monitor) and,
    with ``raise_on_alarm=True``, a ``TrainingDivergedError`` that stops the
    fit. Without a monitor it degrades to the reference ``NanScoreWatcher``
    idiom — checking ``score_value`` every ``check_every`` iterations, which
    costs a host sync at that cadence."""

    def __init__(self, monitor: Optional[HealthMonitor] = None, *,
                 check_every: int = 1, raise_on_alarm: bool = False,
                 recorder=None):
        self.monitor = monitor
        self.check_every = max(1, int(check_every))
        self.raise_on_alarm = raise_on_alarm
        self._recorder = recorder
        self._score_alarmed = False
        self._seen_alarm = None

    def _recorder_or_global(self):
        if self._recorder is not None:
            return self._recorder
        from .flight_recorder import global_recorder

        return global_recorder()

    def iteration_done(self, model, iteration: int) -> None:
        hm = self.monitor or getattr(model, "health_monitor", None)
        if hm is not None:
            hm.poll()
            # the sticky alarm also covers summaries resolved by offer()'s
            # backlog path, which poll() never returned to us
            alarm = hm.alarm
            if (alarm is not None and alarm is not self._seen_alarm
                    and self.raise_on_alarm):
                self._seen_alarm = alarm
                raise TrainingDivergedError(
                    f"training health alarm at iteration "
                    f"{alarm['iteration']}: {alarm['why']} "
                    f"(loss={alarm['loss']!r})")
            return
        if iteration % self.check_every != 0:
            return
        score = model.score_value  # forces the sync, as the reference did
        if not is_invalid_score(score) or self._score_alarmed:
            return
        self._score_alarmed = True
        reg = global_registry()
        reg.counter(HEALTH_ALARMS_TOTAL,
                    "health alarms (non-finite or diverged training)").labels(
                        why="invalid-score").inc()
        rec = self._recorder_or_global()
        rec.record("health_alarm", why="invalid-score", iteration=iteration,
                   loss=None if score is None else float(score))
        rec.dump(reason="health-alarm-invalid-score")
        if self.raise_on_alarm:
            raise TrainingDivergedError(
                f"invalid score {score!r} at iteration {iteration}")
