"""TelemetryListener: the registry's tap into the listener pipeline.

The fit loops already time their own phases (staging/dispatch/listeners) at
the call sites; what a listener adds is the *model-visible* view — wall time
between iterations, the training score, device memory — sampled through the
same ``iteration_done`` hook every other listener uses, so attaching
telemetry needs no fit-loop changes on the user's side.

Device-time discipline: the ONLY trusted sync point is ``float(loss)``
through ``LazyScore.score_value`` (an extra ``block_until_ready`` through
the axon relay measures the relay, not the device — the reason LazyScore
exists). So device time is sampled by timing that exact read, every
``sync_every`` iterations, and the fit loop's cached read afterwards is
free. ``memory_stats()`` returns None on CPU and on some backends; the HBM
gauge degrades to 0.0 rather than vanishing so dashboards keep the series.
"""
from __future__ import annotations

import time
from typing import Optional

from .metrics import global_registry
from .names import (DEVICE_HBM_BYTES, DEVICE_HBM_PEAK_BYTES,
                    STEP_DEVICE_SYNC_SECONDS, STEP_HOST_SECONDS,
                    TRAIN_ITERATION, TRAIN_SCORE)


def record_hbm_gauges(registry=None) -> None:
    """Set ``dl4j_device_hbm_bytes{device=...}`` for every local device,
    None-safe (CPU backends report no memory_stats -> 0.0)."""
    reg = registry if registry is not None else global_registry()
    gauge = reg.gauge(DEVICE_HBM_BYTES,
                      "bytes in use per device (0 when the backend "
                      "reports no memory_stats, e.g. CPU)")
    peak = reg.gauge(DEVICE_HBM_PEAK_BYTES,
                     "peak bytes in use per device (0 when unreported)")
    try:
        import jax
        devices = jax.local_devices()
    except Exception:  # pragma: no cover - no backend at all
        return
    for d in devices:
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        stats = stats or {}
        label = f"{d.platform}:{d.id}"
        gauge.labels(device=label).set(stats.get("bytes_in_use", 0) or 0)
        peak.labels(device=label).set(stats.get("peak_bytes_in_use", 0) or 0)


class TelemetryListener:
    """IterationListener feeding the metrics registry (and optionally the
    StatsStorage pipeline) from any fit loop.

    Parameters
    ----------
    sync_every: sample device time by timing ``float(model.score_value)``
        every N iterations (0 disables; the read is the trusted lazy sync,
        so sampled iterations cost exactly one host round-trip that the
        score-reading listeners would have paid anyway).
    hbm_every: refresh per-device HBM gauges every N iterations.
    router: optional ``StatsStorageRouter``; when given, a ``StatsReport``
        carrying score/iteration-time/device-memory is posted every
        ``report_every`` iterations so the training UI's existing charts see
        telemetry without a separate StatsListener.
    snapshot_path: optional JSONL path; a full registry snapshot is appended
        on every epoch end (the ``--telemetry-out`` format).
    """

    def __init__(self, sync_every: int = 10, hbm_every: int = 10,
                 router=None, report_every: int = 1,
                 snapshot_path: Optional[str] = None,
                 worker_id: str = "main", registry=None):
        self.sync_every = max(0, sync_every)
        self.hbm_every = max(1, hbm_every)
        self.router = router
        self.report_every = max(1, report_every)
        self.snapshot_path = snapshot_path
        self.worker_id = worker_id
        self._registry = registry
        self._last_done: Optional[float] = None
        self._session_id = f"telemetry_{int(time.time() * 1000)}"
        reg = self.registry
        self._step_hist = reg.histogram(
            STEP_HOST_SECONDS,
            "host wall time between consecutive iterations").labels(
                worker=worker_id)
        self._sync_hist = reg.histogram(
            STEP_DEVICE_SYNC_SECONDS,
            "time to materialize float(loss) at the trusted sync point"
        ).labels(worker=worker_id)
        self._score_gauge = reg.gauge(
            TRAIN_SCORE, "last synced training score").labels(
                worker=worker_id)
        self._iter_gauge = reg.gauge(
            TRAIN_ITERATION, "last completed iteration").labels(
                worker=worker_id)

    @property
    def registry(self):
        return self._registry if self._registry is not None \
            else global_registry()

    # ------------------------------------------------------------ listener
    def iteration_done(self, model, iteration: int) -> None:
        now = time.perf_counter()
        if self._last_done is not None:
            self._step_hist.observe(now - self._last_done)
        self._last_done = now
        self._iter_gauge.set(iteration)

        score = None
        if self.sync_every and iteration % self.sync_every == 0:
            t0 = time.perf_counter()
            score = float(model.score_value)
            self._sync_hist.observe(time.perf_counter() - t0)
            self._score_gauge.set(score)

        if iteration % self.hbm_every == 0:
            record_hbm_gauges(self.registry)

        if self.router is not None and iteration % self.report_every == 0:
            self._post_report(model, iteration, score)

    def on_epoch_start(self, model) -> None:
        pass

    def on_epoch_end(self, model) -> None:
        # epoch boundary: refresh gauges and (optionally) persist a snapshot
        record_hbm_gauges(self.registry)
        if self.snapshot_path:
            self.registry.write_jsonl(self.snapshot_path,
                                      source="TelemetryListener",
                                      epoch=getattr(model, "epoch", None))

    # ------------------------------------------------------------- bridge
    def _post_report(self, model, iteration: int, score) -> None:
        from deeplearning4j_tpu.ui.stats import StatsReport

        r = StatsReport(self._session_id, self.worker_id,
                        int(time.time() * 1000))
        r.iteration = iteration
        if score is not None:
            r.score = score
        snap = self.registry.snapshot()
        hbm = snap.get(DEVICE_HBM_BYTES, {}).get("series", [])
        if hbm:
            r.device_mem_bytes = int(max(s["value"] for s in hbm))
        self.router.put_update(r)
