"""Always-on, low-overhead telemetry for the TPU port.

The reference's observability stack (listeners + StatsStorage + training UI)
is event-push per iteration; this package adds the aggregate layer the
TPU-native failure modes need — silent jit recompiles, host/device skew,
HBM growth, collective traffic — exposed as Prometheus text on the UI
server's ``/metrics`` route and as JSONL snapshots via ``--telemetry-out``.

    from deeplearning4j_tpu.observability import (
        global_registry, global_tracker, span, TelemetryListener)
"""
from . import names
from .metrics import (MetricsRegistry, global_registry, DEFAULT_BUCKETS,
                      tree_nbytes)
from .compile_tracker import CompileTracker, global_tracker
from .spans import span
from .tracing import (TraceStore, Span, SpanRef, trace_span, start_span,
                      current_span, parse_traceparent, format_traceparent,
                      global_trace_store, set_global_trace_store,
                      TRACEPARENT_HEADER)
from .slo import SLO, SLOEngine, default_serve_objectives
from .federation import (FederatedRegistry, MetricsPublisher, FleetCollector,
                         merge_snapshots, global_federation,
                         set_global_federation, global_fleet_collector,
                         set_global_fleet_collector, register_status_provider,
                         fleet_status, fleet_metrics_text,
                         trigger_fleet_dump)
from .listener import TelemetryListener, record_hbm_gauges
from .flight_recorder import (FlightRecorder, global_recorder,
                              dump_on_unhandled, install_signal_handlers,
                              uninstall_signal_handlers)
from .health import (HealthMonitor, NanAlertListener, TrainingDivergedError,
                     is_invalid_score, health_terms)
from .watchdog import (StepWatchdog, install_watchdog, uninstall_watchdog,
                       global_watchdog, beat)
from .profiler import (TraceSession, StepAnomalyWatcher, global_trace_session,
                       install_anomaly_watcher, uninstall_anomaly_watcher,
                       note_dispatch, first_healthy_due, mark_first_healthy)
from . import xplane

__all__ = [
    "MetricsRegistry", "global_registry", "DEFAULT_BUCKETS", "tree_nbytes",
    "CompileTracker", "global_tracker",
    "span", "names",
    "TraceStore", "Span", "SpanRef", "trace_span", "start_span",
    "current_span", "parse_traceparent", "format_traceparent",
    "global_trace_store", "set_global_trace_store", "TRACEPARENT_HEADER",
    "SLO", "SLOEngine", "default_serve_objectives",
    "FederatedRegistry", "MetricsPublisher", "FleetCollector",
    "merge_snapshots", "global_federation", "set_global_federation",
    "global_fleet_collector", "set_global_fleet_collector",
    "register_status_provider", "fleet_status", "fleet_metrics_text",
    "trigger_fleet_dump",
    "TelemetryListener", "record_hbm_gauges",
    "FlightRecorder", "global_recorder", "dump_on_unhandled",
    "install_signal_handlers", "uninstall_signal_handlers",
    "HealthMonitor", "NanAlertListener", "TrainingDivergedError",
    "is_invalid_score", "health_terms",
    "StepWatchdog", "install_watchdog", "uninstall_watchdog",
    "global_watchdog", "beat",
    "TraceSession", "StepAnomalyWatcher", "global_trace_session",
    "install_anomaly_watcher", "uninstall_anomaly_watcher", "note_dispatch",
    "first_healthy_due", "mark_first_healthy", "xplane",
]
