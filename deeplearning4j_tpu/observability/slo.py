"""Declarative SLOs + multi-window burn-rate alerts over the serve metrics.

ROADMAP item 3's autoscaler needs a *decision* signal, not raw histograms:
"is the TTFT objective burning its error budget fast enough to matter".
This module is that layer, Google-SRE shaped:

- An :class:`SLO` declares an objective over metrics that ALREADY exist —
  a latency objective ("99% of requests complete within ``threshold_s``")
  over a ``dl4j_serve_*`` histogram, or an availability objective
  ("99.9% of requests succeed") over a pair of counters. No new
  instrumentation at the call sites.
- :class:`SLOEngine` snapshots the cumulative counters on a cadence and
  evaluates **windowed deltas**: the burn rate over window W is
  ``bad_fraction(W) / (1 - target)`` — burn 1.0 spends exactly the budget
  over the SLO period, 14.4 empties a 30-day budget in 2 days. An alert
  requires EVERY configured window to burn past its threshold (the
  multi-window guard against blips: default 5m@14.4x AND 1h@6x).
- Alerts are *actions*: the ``dl4j_slo_*`` gauges flip, an alert counter
  increments, a flight-recorder bundle dumps (reason ``slo-burn-<name>``),
  and the evaluation carries a histogram→trace **exemplar** — the worst
  recent trace id the TraceStore saw for the objective's histogram — so a
  burning SLO links straight to an offending request tree under
  ``/serve/traces/<id>``.

The engine is pull-friendly (``evaluate()`` runs on ``GET /serve/slo``)
and push-capable (``start()`` spins a daemon ticker so alarms fire with no
scraper attached). Clock injectable; burn math unit-tested on synthetic
histogram windows.
"""
from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

from . import names as _n

#: (window_seconds, burn_rate_threshold) pairs; ALL must exceed to alert
DEFAULT_WINDOWS: Tuple[Tuple[float, float], ...] = ((300.0, 14.4),
                                                    (3600.0, 6.0))
#: min seconds between flight-recorder dumps for one objective
DEFAULT_COOLDOWN_S = 300.0


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except (TypeError, ValueError):
        return default


class SLO:
    """One objective. ``kind="latency"``: ``target`` fraction of
    observations in histogram ``metric`` must be <= ``threshold_s``
    (target 0.99 == "p99 <= threshold"). ``kind="availability"``:
    ``target`` fraction of ``total_metric`` must not appear in
    ``bad_metric``."""

    def __init__(self, name: str, *, kind: str = "latency",
                 metric: Optional[str] = None,
                 threshold_s: Optional[float] = None,
                 target: float = 0.99,
                 total_metric: Optional[str] = None,
                 bad_metric: Optional[str] = None,
                 description: str = ""):
        if kind not in ("latency", "availability"):
            raise ValueError(f"unknown SLO kind {kind!r}")
        if kind == "latency" and (metric is None or threshold_s is None):
            raise ValueError("latency SLOs need metric= and threshold_s=")
        if kind == "availability" and (total_metric is None
                                       or bad_metric is None):
            raise ValueError(
                "availability SLOs need total_metric= and bad_metric=")
        if not 0.0 < target < 1.0:
            raise ValueError("target must be in (0, 1)")
        self.name = name
        self.kind = kind
        self.metric = metric
        self.threshold_s = threshold_s
        self.target = target
        self.total_metric = total_metric
        self.bad_metric = bad_metric
        self.description = description

    @property
    def budget(self) -> float:
        """The error budget: the tolerable bad fraction (1 - target)."""
        return 1.0 - self.target

    # -- cumulative (total, bad) from one registry snapshot -------------
    def counts(self, snapshot: dict) -> Tuple[float, float]:
        if self.kind == "latency":
            fam = snapshot.get(self.metric)
            if not fam:
                return 0.0, 0.0
            total = good = 0.0
            for row in fam.get("series", ()):
                buckets = row.get("buckets") or []
                bc = row.get("bucket_counts") or []
                count = float(row.get("count", 0))
                total += count
                idx = None
                for i, le in enumerate(buckets):
                    if le >= self.threshold_s:
                        idx = i
                        break
                if idx is None:
                    # threshold beyond the last finite bucket: only the
                    # +Inf overflow counts as bad
                    good += count - float(bc[-1] if bc else 0)
                else:
                    good += float(sum(bc[:idx + 1]))
            return total, total - good
        fam = snapshot.get(self.total_metric) or {}
        total = sum(float(r.get("value", 0.0))
                    for r in fam.get("series", ()))
        fam = snapshot.get(self.bad_metric) or {}
        bad = sum(float(r.get("value", 0.0))
                  for r in fam.get("series", ()))
        return total, bad

    def describe(self) -> dict:
        d = {"name": self.name, "kind": self.kind, "target": self.target,
             "description": self.description}
        if self.kind == "latency":
            d.update(metric=self.metric, threshold_s=self.threshold_s)
        else:
            d.update(total_metric=self.total_metric,
                     bad_metric=self.bad_metric)
        return d


def default_serve_objectives() -> List[SLO]:
    """The stock serving objectives (env-tunable thresholds): request p99,
    TTFT p99, availability."""
    p99_s = _env_float("DL4J_SLO_P99_MS", 250.0) / 1e3
    ttft_s = _env_float("DL4J_SLO_TTFT_MS", 500.0) / 1e3
    avail = _env_float("DL4J_SLO_AVAILABILITY", 0.999)
    return [
        SLO("request_p99", kind="latency", metric=_n.SERVE_REQUEST_SECONDS,
            threshold_s=p99_s, target=0.99,
            description=f"99% of HTTP requests within {p99_s * 1e3:g}ms"),
        SLO("ttft_p99", kind="latency", metric=_n.SERVE_TTFT_SECONDS,
            threshold_s=ttft_s, target=0.99,
            description=f"99% of first tokens within {ttft_s * 1e3:g}ms"),
        SLO("availability", kind="availability",
            total_metric=_n.SERVE_REQUESTS_TOTAL,
            bad_metric=_n.SERVE_ERRORS_TOTAL, target=avail,
            description=f"{avail:.3%} of requests succeed"),
    ]


class SLOEngine:
    """Evaluates objectives over windowed deltas of cumulative metrics.

    ``tick()`` appends one (t, counts) snapshot; ``evaluate()`` computes
    per-window burn rates against the snapshot nearest each window's left
    edge, exports the ``dl4j_slo_*`` gauges, and on an alert transition
    (cooldown-limited) dumps a flight-recorder bundle carrying the
    evaluation + exemplar. Never raises into the caller."""

    def __init__(self, objectives: Optional[List[SLO]] = None, *,
                 registry=None, store=None, recorder=None,
                 windows: Tuple[Tuple[float, float], ...] = DEFAULT_WINDOWS,
                 cooldown_s: float = DEFAULT_COOLDOWN_S,
                 clock=time.monotonic):
        if registry is None:
            from .metrics import global_registry
            registry = global_registry()
        self.registry = registry
        self.objectives = list(objectives if objectives is not None
                               else default_serve_objectives())
        self._store = store
        self._recorder = recorder
        self.windows = tuple(windows)
        self.cooldown_s = cooldown_s
        self._clock = clock
        self._lock = threading.Lock()
        #: (t, {slo_name: (total, bad)}) — bounded history
        self._snaps: deque = deque(maxlen=2048)
        self._alerting: Dict[str, bool] = {}
        self._last_dump: Dict[str, float] = {}
        self._g_burn = registry.gauge(
            _n.SLO_BURN_RATE, "error-budget burn rate per SLO and window")
        self._g_budget = registry.gauge(
            _n.SLO_BUDGET_REMAINING,
            "fraction of the error budget left over the longest window")
        self._g_alerting = registry.gauge(
            _n.SLO_ALERTING, "1 while an SLO's multi-window alert is firing")
        self._c_alerts = registry.counter(
            _n.SLO_ALERTS_TOTAL, "SLO alert transitions (not-firing->firing)")
        self._ticker: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self.tick()  # baseline so the first window has a left edge

    def _store_or_none(self):
        if self._store is not None:
            return self._store
        try:
            from .tracing import global_trace_store
            return global_trace_store()
        except Exception:
            return None

    def _recorder_or_none(self):
        if self._recorder is not None:
            return self._recorder
        from .flight_recorder import global_recorder
        return global_recorder()

    # -- sampling -------------------------------------------------------
    def tick(self, now: Optional[float] = None) -> None:
        now = self._clock() if now is None else now
        snap = self.registry.snapshot()
        counts = {slo.name: slo.counts(snap) for slo in self.objectives}
        with self._lock:
            self._snaps.append((now, counts))

    def _window_delta(self, name: str, now: float,
                      window_s: float) -> Tuple[float, float]:
        """(total, bad) accrued over the last ``window_s`` — delta between
        the newest snapshot and the one nearest the window's left edge
        (the oldest snapshot when history is shorter than the window)."""
        with self._lock:
            snaps = list(self._snaps)
        if not snaps:
            return 0.0, 0.0
        t_now, cur = snaps[-1]
        left = now - window_s
        base = snaps[0]
        for t, counts in snaps:
            if t <= left:
                base = (t, counts)
            else:
                break
        ct, cb = cur.get(name, (0.0, 0.0))
        bt, bb = base[1].get(name, (0.0, 0.0))
        return max(0.0, ct - bt), max(0.0, cb - bb)

    # -- evaluation -----------------------------------------------------
    def evaluate(self, now: Optional[float] = None) -> List[dict]:
        """Tick, compute burn rates, export gauges, fire alert actions.
        Returns the ``/serve/slo`` payload."""
        now = self._clock() if now is None else now
        self.tick(now)
        out = []
        for slo in self.objectives:
            rows = []
            firing = True
            for window_s, burn_threshold in self.windows:
                total, bad = self._window_delta(slo.name, now, window_s)
                frac = (bad / total) if total > 0 else 0.0
                burn = frac / slo.budget
                self._g_burn.labels(
                    slo=slo.name, window=f"{int(window_s)}s").set(burn)
                rows.append({"window_s": window_s, "total": total,
                             "bad": bad, "bad_fraction": round(frac, 6),
                             "burn_rate": round(burn, 3),
                             "threshold": burn_threshold})
                if total <= 0 or burn < burn_threshold:
                    firing = False
            long_row = rows[-1] if rows else None
            budget_left = 1.0
            if long_row and long_row["total"] > 0:
                budget_left = max(
                    0.0, 1.0 - long_row["bad_fraction"] / slo.budget)
            self._g_budget.labels(slo=slo.name).set(budget_left)
            self._g_alerting.labels(slo=slo.name).set(1.0 if firing else 0.0)
            exemplar = None
            if slo.kind == "latency":
                store = self._store_or_none()
                if store is not None:
                    exemplar = store.exemplar(slo.metric)
            entry = dict(slo.describe(), windows=rows, alerting=firing,
                         budget_remaining=round(budget_left, 6),
                         exemplar=exemplar)
            was = self._alerting.get(slo.name, False)
            self._alerting[slo.name] = firing
            if firing and not was:
                self._c_alerts.labels(slo=slo.name).inc()
                self._dump_alert(slo, entry, now)
            out.append(entry)
        return out

    def _dump_alert(self, slo: SLO, entry: dict, now: float) -> None:
        last = self._last_dump.get(slo.name)
        if last is not None and now - last < self.cooldown_s:
            return
        self._last_dump[slo.name] = now
        try:
            self._recorder_or_none().dump(
                reason=f"slo-burn-{slo.name}", extra={"slo": entry})
        except Exception:  # lint: swallowed-exception-ok (an alarm dump must never take down the serve path)
            pass
        try:
            # when a fleet collector is installed, the burn also snapshots
            # every member's ring — the alert usually started elsewhere
            from deeplearning4j_tpu.observability.federation import (
                trigger_fleet_dump)
            trigger_fleet_dump(f"slo-burn-{slo.name}")
        except Exception:  # lint: swallowed-exception-ok (an alarm dump must never take down the serve path)
            pass

    # -- background ticker ---------------------------------------------
    def start(self, interval_s: float = 5.0) -> "SLOEngine":
        if self._ticker is not None:
            return self
        self._stop.clear()

        def run():
            while not self._stop.wait(interval_s):
                try:
                    self.evaluate()
                except Exception:  # lint: swallowed-exception-ok (ticker thread must survive any transient registry state)
                    pass

        self._ticker = threading.Thread(target=run, daemon=True,
                                        name="dl4j-slo-ticker")
        self._ticker.start()
        return self

    def stop(self) -> None:
        if self._ticker is None:
            return
        self._stop.set()
        self._ticker.join(timeout=2.0)
        self._ticker = None
