"""Step watchdog: a background thread that notices when training stops.

Round 5's relay outage is the motivating incident: the device link died
mid-run, every step call blocked forever, and the hang was diagnosed by an
out-of-band watcher script because the framework had no notion of "a step
should have finished by now". The watchdog is that notion. Fit loops call
``beat(step)`` after every completed dispatch (a near-zero no-op when no
watchdog is installed); the watchdog thread wakes every ``poll_s`` and, once
the wall time since the last beat crosses ``threshold_s``, it

* logs every thread's Python stack at ERROR level (so the hang site is in
  the training log even if the process is later SIGKILLed),
* dumps the flight recorder (reason ``watchdog-stall``), and
* increments ``dl4j_watchdog_stalls_total``

— once per stall: the alarm re-arms on the next heartbeat, so a recovered
run that stalls again is reported again, but a single wedged step produces a
single bundle, not one per poll.
"""
from __future__ import annotations

import logging
import threading
import time
from typing import Optional

from .metrics import global_registry
from .names import WATCHDOG_STALLS_TOTAL

log = logging.getLogger(__name__)

#: default stall threshold — generous enough that a cold-start compile of a
#: large model does not trip it; tune down for small-step production loops
DEFAULT_THRESHOLD_S = 300.0


class StepWatchdog:
    """Watches wall time since the last completed training step.

    The watchdog only arms after the first ``heartbeat()`` — an installed
    but idle watchdog (before ``fit`` is entered, or after it returns) never
    fires. ``start()``/``stop()`` manage the daemon thread; the instance is
    also a context manager.
    """

    def __init__(self, threshold_s: float = DEFAULT_THRESHOLD_S, *,
                 poll_s: Optional[float] = None, recorder=None,
                 registry=None):
        self.threshold_s = float(threshold_s)
        self.poll_s = max(0.01, float(poll_s) if poll_s is not None
                          else min(self.threshold_s / 4.0, 5.0))
        self._recorder = recorder
        self._registry = registry
        self._last_beat: Optional[float] = None
        self._last_step = None
        self._fired = False
        self.stalls = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------- wiring
    @property
    def registry(self):
        return self._registry if self._registry is not None \
            else global_registry()

    def _recorder_or_global(self):
        if self._recorder is not None:
            return self._recorder
        from .flight_recorder import global_recorder

        return global_recorder()

    # ---------------------------------------------------------- heartbeat
    def heartbeat(self, step=None) -> None:
        """Record that a training step just completed. Cheap and lock-free
        (two attribute stores); the monitor thread tolerates torn reads."""
        self._last_beat = time.monotonic()
        self._last_step = step
        self._fired = False  # re-arm: training made progress

    # ------------------------------------------------------------ thread
    def start(self) -> "StepWatchdog":
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="dl4j-step-watchdog", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=max(1.0, self.poll_s * 4))
        self._thread = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False

    def _run(self) -> None:
        while not self._stop.wait(self.poll_s):
            last = self._last_beat
            if last is None or self._fired:
                continue
            stalled = time.monotonic() - last
            if stalled >= self.threshold_s:
                self._fired = True
                self._on_stall(stalled)

    def _on_stall(self, stalled_s: float) -> None:
        self.stalls += 1
        self.registry.counter(
            WATCHDOG_STALLS_TOTAL,
            "training stalls detected by the step watchdog").inc()
        from .flight_recorder import thread_stacks

        log.error(
            "watchdog: no training step completed for %.1fs "
            "(threshold %.1fs, last step %s); all-thread stacks follow\n%s",
            stalled_s, self.threshold_s, self._last_step, thread_stacks())
        rec = self._recorder_or_global()
        rec.record("watchdog_stall", stalled_s=stalled_s,
                   threshold_s=self.threshold_s, step=self._last_step)
        try:
            rec.dump(reason="watchdog-stall")
        except Exception:
            log.exception("watchdog: flight recorder dump failed")


_GLOBAL: Optional[StepWatchdog] = None


def install_watchdog(threshold_s: float = DEFAULT_THRESHOLD_S,
                     **kwargs) -> StepWatchdog:
    """Create, start, and register the process watchdog the fit loops beat.
    Replaces (and stops) any previously installed one."""
    global _GLOBAL
    if _GLOBAL is not None:
        _GLOBAL.stop()
    _GLOBAL = StepWatchdog(threshold_s, **kwargs).start()
    return _GLOBAL


def uninstall_watchdog() -> None:
    global _GLOBAL
    if _GLOBAL is not None:
        _GLOBAL.stop()
        _GLOBAL = None


def global_watchdog() -> Optional[StepWatchdog]:
    return _GLOBAL


def beat(step=None) -> None:
    """Heartbeat hook for the fit loops: one global read + an early return
    when no watchdog is installed, so always-on call sites cost nothing."""
    wd = _GLOBAL
    if wd is not None:
        wd.heartbeat(step)
