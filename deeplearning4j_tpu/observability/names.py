"""THE registry of telemetry metric names — the /metrics stability contract.

Metric names are external API: Prometheus scrapers alert on them, bench.py's
log reinterpretation greps them, dashboards chart them. Every name therefore
lives here, once, as a ``dl4j_``-prefixed constant; registry call sites
import the constant instead of repeating the string. The
``metric-name-drift`` lint rule enforces both halves (prefix + central
registration), so a rename is one reviewable diff line here and drift
between two subsystems claiming the same string is impossible.

Naming follows Prometheus conventions: ``_total`` for counters, ``_seconds``
/ ``_bytes`` for unit-carrying series, no label names in the metric name.
"""
from __future__ import annotations

# --- spans (observability/spans.py) ----------------------------------------
SPAN_SECONDS = "dl4j_span_seconds"

# --- compile tracking (observability/compile_tracker.py) -------------------
JIT_COMPILE_TOTAL = "dl4j_jit_compile_total"
JIT_COMPILE_SECONDS = "dl4j_jit_compile_seconds"
JIT_BACKEND_COMPILE_SECONDS = "dl4j_jit_backend_compile_seconds"
RECOMPILE_STORM_WARNINGS_TOTAL = "dl4j_recompile_storm_warnings_total"

# --- per-iteration telemetry (observability/listener.py) -------------------
DEVICE_HBM_BYTES = "dl4j_device_hbm_bytes"
DEVICE_HBM_PEAK_BYTES = "dl4j_device_hbm_peak_bytes"
STEP_HOST_SECONDS = "dl4j_step_host_seconds"
STEP_DEVICE_SYNC_SECONDS = "dl4j_step_device_sync_seconds"
TRAIN_SCORE = "dl4j_train_score"
TRAIN_ITERATION = "dl4j_train_iteration"

# --- fit-loop phase attribution (nn/multilayer.py, parallel/wrapper.py) ----
FIT_PHASE_SECONDS = "dl4j_fit_phase_seconds"

# --- collective traffic (parallel/{wrapper,training_master,moe,ring_attention}.py)
COLLECTIVE_BYTES_TOTAL = "dl4j_collective_bytes_total"
COLLECTIVE_BYTES_PER_STEP = "dl4j_collective_bytes_per_step"

# --- sharding engine (parallel/{partition,compile_seam}.py) ----------------
SHARDING_SPEC_TOTAL = "dl4j_sharding_spec_total"
SHARDED_PARAM_BYTES_PER_DEVICE = "dl4j_sharded_param_bytes_per_device"

# --- kernel dispatch (ops/pallas_kernels.py) -------------------------------
PALLAS_DISPATCH_TOTAL = "dl4j_pallas_dispatch_total"

# --- recurrent engine (ops/lstm.py) ----------------------------------------
LSTM_DISPATCH_TOTAL = "dl4j_lstm_dispatch_total"
LSTM_PALLAS_BLOCK_STEPS = "dl4j_lstm_pallas_block_steps"

# --- training health (observability/health.py) -----------------------------
HEALTH_GRAD_NORM = "dl4j_health_grad_norm"
HEALTH_UPDATE_NORM = "dl4j_health_update_norm"
HEALTH_NONFINITE_GRADS = "dl4j_health_nonfinite_grads"
HEALTH_LOSS_EMA = "dl4j_health_loss_ema"
HEALTH_CHECKS_TOTAL = "dl4j_health_checks_total"
HEALTH_ALARMS_TOTAL = "dl4j_health_alarms_total"

# --- flight recorder + watchdog (observability/{flight_recorder,watchdog}.py)
FLIGHT_DUMPS_TOTAL = "dl4j_flight_dumps_total"
WATCHDOG_STALLS_TOTAL = "dl4j_watchdog_stalls_total"

# --- trace capture + attribution (observability/profiler.py) ----------------
PROFILE_CAPTURES_TOTAL = "dl4j_profile_captures_total"
PROFILE_CAPTURE_SECONDS = "dl4j_profile_capture_seconds"
PROFILE_CATEGORY_SHARE = "dl4j_profile_category_share"
PROFILE_COLLISIONS_TOTAL = "dl4j_profile_collisions_total"
PROFILE_ACTIVE = "dl4j_profile_active"

# --- model FLOP utilization (observability/compile_tracker.py) --------------
STEP_MFU = "dl4j_step_mfu"

# --- serving engine (keras_server/{registry,batcher,serving,streaming}.py) -
SERVE_REQUESTS_TOTAL = "dl4j_serve_requests_total"
SERVE_REJECTED_TOTAL = "dl4j_serve_rejected_total"
SERVE_ERRORS_TOTAL = "dl4j_serve_errors_total"
SERVE_REQUEST_SECONDS = "dl4j_serve_request_seconds"
SERVE_BATCH_DISPATCH_SECONDS = "dl4j_serve_batch_dispatch_seconds"
SERVE_BATCHES_TOTAL = "dl4j_serve_batches_total"
SERVE_QUEUE_DEPTH = "dl4j_serve_queue_depth"
SERVE_BATCH_OCCUPANCY = "dl4j_serve_batch_occupancy"
SERVE_MODELS_LOADED = "dl4j_serve_models_loaded"
SERVE_HOT_SWAPS_TOTAL = "dl4j_serve_hot_swaps_total"
SERVE_STREAM_SESSIONS = "dl4j_serve_stream_sessions"
SERVE_STREAM_STEPS_TOTAL = "dl4j_serve_stream_steps_total"

# --- sharded multi-replica serving (keras_server/replica.py) ---------------
SERVE_REPLICA_QUEUE_DEPTH = "dl4j_serve_replica_queue_depth"
SERVE_REPLICA_OCCUPANCY = "dl4j_serve_replica_occupancy"
SERVE_REPLICA_ACTIVE_VERSION = "dl4j_serve_replica_active_version"
SERVE_REPLICA_ROUTED_TOTAL = "dl4j_serve_replica_routed_total"

# --- autoscaling serving fleet (keras_server/{autoscaler,replica,admission}
# .py) -----------------------------------------------------------------------
SERVE_FLEET_SIZE = "dl4j_serve_fleet_size"
SERVE_SCALE_EVENTS_TOTAL = "dl4j_serve_scale_events_total"
SERVE_SHED_TOTAL = "dl4j_serve_shed_total"

# --- continuous-batching decode engine (keras_server/{decode,streaming}.py) -
SERVE_SLOT_OCCUPANCY = "dl4j_serve_slot_occupancy"
SERVE_TTFT_SECONDS = "dl4j_serve_ttft_seconds"
SERVE_TOKENS_TOTAL = "dl4j_serve_tokens_total"
SERVE_EVICTIONS_TOTAL = "dl4j_serve_evictions_total"

# --- paged decode memory plane + spec decoding (keras_server/paging.py,
# keras_server/decode.py) ---------------------------------------------------
DECODE_PAGES_IN_USE = "dl4j_decode_page_in_use"
DECODE_PREFIX_SHARE_RATIO = "dl4j_decode_page_prefix_share_ratio"
DECODE_SPEC_ACCEPTANCE = "dl4j_decode_spec_acceptance_ratio"
DECODE_SPEC_TOKENS_TOTAL = "dl4j_decode_spec_tokens_total"
DECODE_STATE_COPY_BYTES_TOTAL = "dl4j_decode_state_copy_bytes_total"

# --- async parameter server (parallel/{param_server,ps_transport}.py) ------
PS_PUSHES_TOTAL = "dl4j_ps_pushes_total"
PS_PULLS_TOTAL = "dl4j_ps_pulls_total"
PS_STALENESS = "dl4j_ps_staleness"
PS_PUSH_WEIGHT = "dl4j_ps_push_weight"
PS_VERSION = "dl4j_ps_version"
PS_WIRE_BYTES_TOTAL = "dl4j_ps_wire_bytes_total"
PS_WORKER_STEPS_TOTAL = "dl4j_ps_worker_steps_total"

# --- elastic training (parallel/elastic.py, cloud.MembershipOracle) --------
ELASTIC_LIVE_WORKERS = "dl4j_elastic_live_workers"
ELASTIC_LEASE_EXPIRIES_TOTAL = "dl4j_elastic_lease_expiries_total"
ELASTIC_FENCED_PUSHES_TOTAL = "dl4j_elastic_fenced_pushes_total"
ELASTIC_HANDOFFS_TOTAL = "dl4j_elastic_handoffs_total"
ELASTIC_JOINS_TOTAL = "dl4j_elastic_joins_total"

# --- streaming routes + broker (streaming/{__init__,broker}.py) ------------
ROUTE_ERRORS_TOTAL = "dl4j_route_errors_total"
BROKER_MESSAGES_TOTAL = "dl4j_broker_messages_total"
BROKER_RECONNECTS_TOTAL = "dl4j_broker_reconnects_total"

# --- zero-copy host data plane (streaming/wire.py, parallel/ps_transport.py,
# --- nativert ingest decode) ------------------------------------------------
WIRE_COPY_BYTES_TOTAL = "dl4j_wire_copy_bytes_total"
SHM_SEGMENTS = "dl4j_shm_segments"
SHM_BYTES_TOTAL = "dl4j_shm_bytes_total"
SHM_REAPED_TOTAL = "dl4j_shm_reaped_total"
INGEST_DECODE_BYTES_TOTAL = "dl4j_ingest_decode_bytes_total"

# --- warm-start compile plane (nn/compile_cache.py, keras_server/decode.py) -
COMPILE_CACHE_HITS_TOTAL = "dl4j_compile_cache_hits_total"
COMPILE_CACHE_MISSES_TOTAL = "dl4j_compile_cache_misses_total"
COMPILE_CACHE_BYTES = "dl4j_compile_cache_bytes"
COMPILE_CACHE_LOAD_SECONDS = "dl4j_compile_cache_load_seconds"
WARMUP_SECONDS = "dl4j_warmup_seconds"
SERVE_BUCKET_GROWTH_STALL_SECONDS = "dl4j_serve_bucket_growth_stall_seconds"

# --- request tracing plane (observability/tracing.py) ----------------------
TRACE_SPANS_TOTAL = "dl4j_trace_spans_total"
TRACE_TRACES_KEPT_TOTAL = "dl4j_trace_traces_kept_total"
TRACE_TRACES_DROPPED_TOTAL = "dl4j_trace_traces_dropped_total"
TRACE_LIVE_TRACES = "dl4j_trace_live_traces"

# --- SLO / error-budget engine (observability/slo.py) ----------------------
SLO_BURN_RATE = "dl4j_slo_burn_rate"
SLO_BUDGET_REMAINING = "dl4j_slo_budget_remaining"
SLO_ALERTING = "dl4j_slo_alerting"
SLO_ALERTS_TOTAL = "dl4j_slo_alerts_total"

# --- metrics registry self-protection (observability/metrics.py) -----------
METRICS_DROPPED_LABELSETS_TOTAL = "dl4j_metrics_dropped_labelsets_total"

# --- fleet observability federation (observability/federation.py) ----------
FED_FRAMES_TOTAL = "dl4j_fed_frames_total"
FED_BYTES_TOTAL = "dl4j_fed_bytes_total"
FED_MEMBERS = "dl4j_fed_members"
FED_TRACE_RECORDS_TOTAL = "dl4j_fed_trace_records_total"
FED_PUBLISH_SECONDS = "dl4j_fed_publish_seconds"
FLEET_DUMPS_TOTAL = "dl4j_fleet_dumps_total"

# --- input pipeline (datasets/prefetch.py) ---------------------------------
PREFETCH_DEPTH = "dl4j_prefetch_depth"
PREFETCH_BYTES_TOTAL = "dl4j_prefetch_bytes_total"
PREFETCH_STAGING_SECONDS_TOTAL = "dl4j_prefetch_staging_seconds_total"
PREFETCH_WAIT_SECONDS_TOTAL = "dl4j_prefetch_wait_seconds_total"
PREFETCH_OVERLAP_RATIO = "dl4j_prefetch_overlap_ratio"

#: every registered name, sorted by constant name; the lint rule parses
#: this module statically, this tuple is for runtime consumers (tests,
#: /metrics docs)
ALL_METRIC_NAMES = tuple(
    v for k, v in sorted(globals().items())
    if not k.startswith("_") and isinstance(v, str) and k.isupper())
