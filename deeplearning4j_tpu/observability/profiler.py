"""Process-global trace capture engine: TraceSession + capture triggers.

``jax.profiler.start_trace`` is a process singleton — two owners (a
``ProfilerListener`` window and a bench/script capture, say) calling it
concurrently raise from inside a fit loop. This module is the single locked
door in front of it:

* :class:`TraceSession` — one capture at a time, enforced with a lock;
  a collision logs a warning, bumps ``dl4j_profile_collisions_total`` and
  no-ops (``start`` returns None) instead of raising. Every completed
  capture is summarized by :mod:`.xplane` into ``attribution.json`` next to
  the trace, mirrored into ``dl4j_profile_*`` gauges, recorded in the
  flight-recorder ring, and registered in a persistent sqlite index
  (:class:`~deeplearning4j_tpu.ui.storage.FileStatsStorage`) so profiles
  survive process death the way flight-recorder bundles do.
* :class:`StepAnomalyWatcher` — the ``DL4J_PROFILE_TRIGGER=anomaly`` mode:
  watches per-dispatch wall times (the ``dl4j_fit_phase_seconds`` dispatch
  phase, fed via :func:`note_dispatch` from the fit loops), and when a step
  exceeds ``k x rolling-p50`` starts a capture over the next dispatches —
  once per cool-down, so a pathological run cannot trace itself to death.
* ``first-healthy`` — the bench trigger (ROADMAP item 1: capture-first):
  :func:`first_healthy_due` consults a cross-process marker file so the
  first healthy relay window after an outage gets an attribution capture,
  and later windows inside the cool-down don't re-pay the trace overhead.

Env knobs: ``DL4J_PROFILE_TRIGGER`` (off | anomaly | first-healthy),
``DL4J_PROFILE_DIR`` (base directory, default ``profiles/``),
``DL4J_PROFILE_ANOMALY_K`` (default 3.0), ``DL4J_PROFILE_COOLDOWN_S``
(default 600), ``DL4J_PROFILE_WINDOW`` (dispatches per capture, default 2).
"""
from __future__ import annotations

import contextlib
import json
import logging
import os
import statistics
import threading
import time
from collections import deque
from typing import Optional

from . import xplane
from .metrics import global_registry
from .names import (PROFILE_ACTIVE, PROFILE_CAPTURE_SECONDS,
                    PROFILE_CAPTURES_TOTAL, PROFILE_CATEGORY_SHARE,
                    PROFILE_COLLISIONS_TOTAL)

log = logging.getLogger(__name__)

TRIGGER_ENV = "DL4J_PROFILE_TRIGGER"
DIR_ENV = "DL4J_PROFILE_DIR"
ANOMALY_K_ENV = "DL4J_PROFILE_ANOMALY_K"
COOLDOWN_ENV = "DL4J_PROFILE_COOLDOWN_S"
WINDOW_ENV = "DL4J_PROFILE_WINDOW"

DEFAULT_BASE_DIR = "profiles"
ATTRIBUTION_FILE = "attribution.json"
INDEX_DB = "profile_index.db"
FIRST_HEALTHY_MARKER = ".first_healthy_ts"

#: index keying: one fixed session so every process appends to the same
#: stream; the worker id is the pid, the row timestamp orders entries
_INDEX_SESSION = "profiles"
_INDEX_TYPE = "ProfileRecord"


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


class ProfileRecord:
    """Persistable wrapper over one capture's JSON payload (duck-typed to
    ui.storage.Persistable so the sqlite index is the same machinery that
    stores training stats)."""

    def __init__(self, payload: dict):
        self.payload = payload

    def get_session_id(self) -> str:
        return _INDEX_SESSION

    def get_type_id(self) -> str:
        return _INDEX_TYPE

    def get_worker_id(self) -> str:
        return str(self.payload.get("pid", 0))

    def get_timestamp(self) -> int:
        return int(float(self.payload.get("ts", 0.0)) * 1000)

    def encode(self) -> bytes:
        return json.dumps(self.payload).encode("utf-8")

    @classmethod
    def decode(cls, data: bytes) -> "ProfileRecord":
        return cls(json.loads(data.decode("utf-8")))


class TraceSession:
    """Single-owner lock over the process-global jax profiler.

    ``start()`` claims the profiler (returns the trace directory, or None on
    collision/failure — never raises); ``stop()`` ends the trace, writes
    ``attribution.json``, updates gauges/counters, records a flight-recorder
    event and appends to the persistent index. The ``capture()`` context
    manager pairs them for exact windows.
    """

    def __init__(self, base_dir: Optional[str] = None, registry=None,
                 recorder=None):
        self.base_dir = base_dir or os.environ.get(DIR_ENV) \
            or DEFAULT_BASE_DIR
        self._lock = threading.Lock()
        self._current: Optional[dict] = None
        self._registry = registry
        self._recorder = recorder
        self._index = None

    # ------------------------------------------------------------- plumbing
    def _reg(self):
        return self._registry if self._registry is not None \
            else global_registry()

    def _rec(self):
        if self._recorder is not None:
            return self._recorder
        from .flight_recorder import global_recorder
        return global_recorder()

    @property
    def active(self) -> Optional[str]:
        """Trigger name of the live capture, or None when idle."""
        cur = self._current
        return cur["trigger"] if cur else None

    # -------------------------------------------------------------- capture
    def start(self, trigger: str = "manual",
              logdir: Optional[str] = None) -> Optional[str]:
        """Claim the profiler and start tracing into ``logdir`` (default: a
        fresh ``<base_dir>/<trigger>-<stamp>`` directory). Returns the trace
        directory, or None when another capture owns the profiler or jax
        refuses — callers inside fit loops need never guard this."""
        with self._lock:
            if self._current is not None:
                log.warning(
                    "TraceSession: %r capture already active; ignoring "
                    "%r capture request", self._current["trigger"], trigger)
                self._reg().counter(
                    PROFILE_COLLISIONS_TOTAL,
                    "trace capture requests refused because one was live"
                ).labels(trigger=trigger).inc()
                return None
            # claim before releasing the lock so a racing start() collides
            self._current = {"trigger": trigger, "logdir": None,
                             "t0": time.time()}
        sub = logdir or os.path.join(
            self.base_dir,
            f"{trigger}-{time.strftime('%Y%m%d-%H%M%S')}-p{os.getpid()}")
        try:
            os.makedirs(sub, exist_ok=True)
            import jax
            jax.profiler.start_trace(sub)
        except Exception as e:  # profiler/FS refusal must not kill a fit loop
            log.warning("TraceSession: start_trace(%s) failed: %r", sub, e)
            with self._lock:
                self._current = None
            self._reg().counter(
                PROFILE_COLLISIONS_TOTAL,
                "trace capture requests refused because one was live"
            ).labels(trigger=trigger).inc()
            return None
        # the collision guard means one live capture, but stop() hands
        # _current off under the lock — mutate it under the same lock so
        # a concurrent stop never sees a half-written record
        with self._lock:
            if self._current is not None:
                self._current["logdir"] = sub
        self._reg().gauge(PROFILE_ACTIVE,
                          "1 while a profiler trace is being captured").set(1)
        rec = self._rec()
        if rec is not None:
            rec.record("profile_start", trigger=trigger, logdir=sub)
        log.info("TraceSession: capturing %r trace into %s", trigger, sub)
        return sub

    def stop(self, summarize: bool = True) -> Optional[dict]:
        """End the live capture. Returns the attribution summary (or an
        ``{"error": ...}`` record when parsing failed, or None when no
        capture was live / ``summarize=False``). Never raises."""
        cur = self._current
        if cur is None or cur["logdir"] is None:
            log.warning("TraceSession.stop: no active capture")
            return None
        trigger, logdir = cur["trigger"], cur["logdir"]
        try:
            import jax
            jax.profiler.stop_trace()
        except Exception as e:  # a failed stop still releases the session
            log.warning("TraceSession: stop_trace failed: %r", e)
        duration_s = time.time() - cur["t0"]
        summary = None
        summary_path = None
        if summarize:
            summary = xplane.summarize(logdir)
            summary_path = os.path.join(logdir, ATTRIBUTION_FILE)
            try:
                with open(summary_path, "w") as f:
                    json.dump(summary, f, indent=1)
                    f.write("\n")
            except OSError as e:
                log.warning("TraceSession: could not write %s: %r",
                            summary_path, e)
                summary_path = None
            for cat, pct in (summary.get("categories_pct") or {}).items():
                self._reg().gauge(
                    PROFILE_CATEGORY_SHARE,
                    "per-category %% of self time in the latest trace"
                ).labels(category=cat).set(pct)
        reg = self._reg()
        reg.counter(PROFILE_CAPTURES_TOTAL,
                    "completed profiler trace captures").labels(
                        trigger=trigger).inc()
        reg.histogram(PROFILE_CAPTURE_SECONDS,
                      "wall seconds each trace capture stayed open").observe(
                          duration_s)
        reg.gauge(PROFILE_ACTIVE,
                  "1 while a profiler trace is being captured").set(0)
        entry = {
            "ts": cur["t0"], "pid": os.getpid(), "trigger": trigger,
            "logdir": logdir, "duration_s": round(duration_s, 3),
            "summary_path": summary_path,
            "error": (summary or {}).get("error"),
            "categories_pct": (summary or {}).get("categories_pct"),
        }
        self._index_put(entry)
        rec = self._rec()
        if rec is not None:
            rec.record("profile_capture", trigger=trigger, logdir=logdir,
                       duration_s=round(duration_s, 3),
                       error=entry["error"])
        with self._lock:
            self._current = None
        return summary

    @contextlib.contextmanager
    def capture(self, trigger: str = "manual", logdir: Optional[str] = None):
        """``with session.capture("bench") as logdir:`` — exact windows;
        yields None (and skips the stop) when the session was busy."""
        got = self.start(trigger, logdir)
        try:
            yield got
        finally:
            if got is not None:
                self.stop()

    # ---------------------------------------------------------------- index
    def _index_storage(self):
        if self._index is None:
            os.makedirs(self.base_dir, exist_ok=True)
            from ..ui.storage import FileStatsStorage
            self._index = FileStatsStorage(
                os.path.join(self.base_dir, INDEX_DB))
        return self._index

    def _index_put(self, entry: dict) -> None:
        try:
            self._index_storage().put_update(ProfileRecord(entry))
        except Exception as e:  # index damage must not fail the capture path
            log.warning("TraceSession: could not index capture: %r", e)

    def index_entries(self) -> list:
        """All captures ever indexed under ``base_dir``, newest first —
        across process restarts (the ``/train/profiles`` payload)."""
        try:
            st = self._index_storage()
            entries = []
            for wid in st.list_worker_ids_for_session(_INDEX_SESSION):
                for blob in st.get_all_updates_after(
                        _INDEX_SESSION, _INDEX_TYPE, wid, -1):
                    try:
                        entries.append(ProfileRecord.decode(blob).payload)
                    except (ValueError, UnicodeDecodeError):
                        continue
        except Exception as e:  # a corrupt index reads as empty, not a crash
            log.warning("TraceSession: could not read index: %r", e)
            return []
        entries.sort(key=lambda e: -float(e.get("ts") or 0.0))
        return entries


_GLOBAL_SESSION: Optional[TraceSession] = None
_GLOBAL_SESSION_LOCK = threading.Lock()


def global_trace_session() -> TraceSession:
    """THE session every capture path shares — ProfilerListener windows,
    bench attribution, the anomaly watcher, scripts."""
    global _GLOBAL_SESSION
    with _GLOBAL_SESSION_LOCK:
        if _GLOBAL_SESSION is None:
            _GLOBAL_SESSION = TraceSession()
        return _GLOBAL_SESSION


def set_global_trace_session(
        session: Optional[TraceSession]) -> Optional[TraceSession]:
    """Swap the global session (tests); returns the previous one."""
    global _GLOBAL_SESSION
    with _GLOBAL_SESSION_LOCK:
        prev, _GLOBAL_SESSION = _GLOBAL_SESSION, session
        return prev


# ------------------------------------------------------------ anomaly trigger
class StepAnomalyWatcher:
    """Auto-capture when a dispatch exceeds ``k x rolling-p50``.

    ``observe(seconds)`` is called once per fit-loop dispatch (via
    :func:`note_dispatch`). It keeps a rolling window of recent dispatch
    times; once ``min_samples`` have accumulated, a dispatch slower than
    ``k`` times the median starts an ``anomaly`` capture spanning the next
    ``capture_dispatches`` dispatches, then stops and summarizes. At most
    one capture per ``cooldown_s`` (the clock is injectable for tests).
    Anomalous and traced dispatches are excluded from the baseline so one
    stall cannot drag the median up and mask the next one. Nothing in here
    may raise into the fit loop.
    """

    def __init__(self, session: Optional[TraceSession] = None,
                 k: Optional[float] = None, window: int = 128,
                 min_samples: int = 16,
                 cooldown_s: Optional[float] = None,
                 capture_dispatches: Optional[int] = None,
                 clock=time.monotonic):
        self.session = session
        self.k = k if k is not None else _env_float(ANOMALY_K_ENV, 3.0)
        self.cooldown_s = cooldown_s if cooldown_s is not None \
            else _env_float(COOLDOWN_ENV, 600.0)
        self.capture_dispatches = capture_dispatches \
            if capture_dispatches is not None else _env_int(WINDOW_ENV, 2)
        self.min_samples = max(2, int(min_samples))
        self._times: deque = deque(maxlen=max(self.min_samples, int(window)))
        self._clock = clock
        self._cooldown_until = float("-inf")
        self._capturing_left = 0
        self.fired = 0  #: anomaly captures started (tests / debugging)

    def _session(self) -> TraceSession:
        return self.session if self.session is not None \
            else global_trace_session()

    def observe(self, seconds: float) -> None:
        try:
            self._observe(float(seconds))
        except Exception:  # lint: swallowed-exception-ok (trigger failure must never propagate into the fit loop; the log line is the record)
            log.exception("StepAnomalyWatcher: observe failed")

    def _observe(self, seconds: float) -> None:
        if self._capturing_left > 0:
            # dispatches running under the trace: count down, then close the
            # window; traced steps never feed the baseline (trace overhead)
            self._capturing_left -= 1
            if self._capturing_left == 0:
                self._session().stop()
            return
        if len(self._times) >= self.min_samples:
            p50 = statistics.median(self._times)
            if p50 > 0 and seconds > self.k * p50 \
                    and self._clock() >= self._cooldown_until:
                self._cooldown_until = self._clock() + self.cooldown_s
                logdir = self._session().start("anomaly")
                if logdir is not None:
                    self.fired += 1
                    self._capturing_left = max(1, self.capture_dispatches)
                    log.warning(
                        "StepAnomalyWatcher: dispatch %.3fs > %.1fx p50 "
                        "%.3fs; capturing %d dispatches into %s",
                        seconds, self.k, p50, self._capturing_left, logdir)
                    rec = self._session()._rec()
                    if rec is not None:
                        # bundle-link: when a flight-recorder dump dir is
                        # armed the anomaly also writes a bundle whose ring
                        # holds the slow step + the profile_start event
                        rec.dump(reason="profile-anomaly",
                                 extra={"logdir": logdir,
                                        "dispatch_s": seconds,
                                        "p50_s": p50, "k": self.k})
                return  # the anomalous sample never enters the baseline
        self._times.append(seconds)


# The fit-loop hook resolves its watcher lazily from the environment exactly
# once, so the disabled case (no DL4J_PROFILE_TRIGGER) costs two global
# reads per dispatch — inside the telemetry overhead budget.
_WATCHER: Optional[StepAnomalyWatcher] = None
_WATCHER_RESOLVED = False
_WATCHER_LOCK = threading.Lock()


def install_anomaly_watcher(watcher: StepAnomalyWatcher) -> None:
    """Explicitly install a watcher (tests; overrides env resolution)."""
    global _WATCHER, _WATCHER_RESOLVED
    with _WATCHER_LOCK:
        _WATCHER = watcher
        _WATCHER_RESOLVED = True


def uninstall_anomaly_watcher() -> None:
    """Remove the watcher and re-arm env resolution for the next dispatch."""
    global _WATCHER, _WATCHER_RESOLVED
    with _WATCHER_LOCK:
        _WATCHER = None
        _WATCHER_RESOLVED = False


def _resolve_watcher() -> Optional[StepAnomalyWatcher]:
    global _WATCHER, _WATCHER_RESOLVED
    with _WATCHER_LOCK:
        if not _WATCHER_RESOLVED:
            if os.environ.get(TRIGGER_ENV, "").strip() == "anomaly":
                _WATCHER = StepAnomalyWatcher()
            _WATCHER_RESOLVED = True
        return _WATCHER


def note_dispatch(seconds: float) -> None:
    """Fit-loop hook: feed one dispatch wall time to the anomaly trigger
    (no-op unless ``DL4J_PROFILE_TRIGGER=anomaly`` or a watcher was
    installed). Never raises."""
    w = _WATCHER
    if w is None:
        if _WATCHER_RESOLVED:
            return
        w = _resolve_watcher()
        if w is None:
            return
    w.observe(seconds)


# ------------------------------------------------------- first-healthy trigger
def first_healthy_due(base_dir: Optional[str] = None,
                      cooldown_s: Optional[float] = None) -> bool:
    """True when ``DL4J_PROFILE_TRIGGER=first-healthy`` and no capture has
    been marked within the cool-down. The marker file lives under the
    profile base dir so the state is shared across bench child processes —
    the FIRST healthy window captures, the rest of the grid doesn't."""
    if os.environ.get(TRIGGER_ENV, "").strip() != "first-healthy":
        return False
    base = base_dir or os.environ.get(DIR_ENV) or DEFAULT_BASE_DIR
    cd = cooldown_s if cooldown_s is not None \
        else _env_float(COOLDOWN_ENV, 600.0)
    try:
        age = time.time() - os.path.getmtime(
            os.path.join(base, FIRST_HEALTHY_MARKER))
    except OSError:
        return True
    return age > cd


def mark_first_healthy(base_dir: Optional[str] = None) -> None:
    """Record that a first-healthy capture just happened (touches the
    cross-process marker)."""
    base = base_dir or os.environ.get(DIR_ENV) or DEFAULT_BASE_DIR
    try:
        os.makedirs(base, exist_ok=True)
        with open(os.path.join(base, FIRST_HEALTHY_MARKER), "w") as f:
            f.write(f"{time.time()}\n")
    except OSError as e:
        log.warning("could not write first-healthy marker under %s: %r",
                    base, e)
