"""Fleet observability federation: one metrics/trace/flight view per fleet.

Every elastic ps_worker subprocess, serving replica, and broker consumer
holds its own process-local MetricsRegistry, TraceStore, and flight-recorder
ring; before this plane the coordinator could see none of them. This module
is the cross-process half of the observability stack, in the spirit of
Monarch-style regional aggregation over Dapper-style propagated context:

* **Metrics federation** — workers push periodic, cumulative
  ``MetricsRegistry.snapshot()`` frames over the PS transport seam
  (length-prefixed JSON frames, no pickle). The coordinator-side
  :class:`FederatedRegistry` keeps the latest cumulative snapshot per
  ``(member, epoch)`` and merges on read: counters summed, histograms merged
  bucket-wise, gauges last-write. Keying by ``(member, epoch)`` is what
  makes the algebra safe under churn: a restarted worker registers a new
  epoch, so its fresh-from-zero counters start a NEW series instead of
  double-counting into the old one, and the dead epoch's final cumulative
  values stay in the totals forever (fleet counters are monotonic). A
  fenced zombie's frames are rejected wholesale — its series stop updating
  — and a dead member's *gauges* drop out of the export while its counters
  remain.
* **Shipping cumulative snapshots, not deltas**, makes the wire loss- and
  replay-tolerant: a dropped frame only delays the view, a duplicated or
  reordered frame is discarded by the per-member ``seq`` guard, and the
  final flush at worker exit makes the fleet totals EXACT (pinned by
  tests/test_federation.py against a 4-worker elastic run).
* **Trace federation** — workers drain finalized trace records from their
  local TraceStore and ship them on the same frames; the coordinator calls
  :meth:`TraceStore.ingest`, which dedups by span id and re-sorts by wall
  time, so a worker's ``broker.consume``/``ps.push`` fragment stitches into
  the coordinator's copy of the same trace id (the cross-process extension
  of the late-fragment merge).
* **Fleet flight bundles** — :class:`FleetCollector` assembles the
  coordinator's recorder ring, every live member's shipped events, and dead
  workers' last on-disk bundles into one bundle with a single merged
  timeline ordered by wall timestamp (the best causal order available
  without a fleet clock).

The ``fleet-truth`` graftlint rule enforces that this module is the ONLY
place a ``/fleet/*`` surface may read a process-local registry: serving a
process-local ``snapshot()`` as fleet-wide truth is exactly the bug this
plane exists to fix.
"""
from __future__ import annotations

import json
import logging
import os
import socket
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from . import names as _n
from .metrics import global_registry, render_prometheus

log = logging.getLogger(__name__)

#: worker-side publish interval (seconds); small enough that a SIGKILL'd
#: worker loses at most a fraction of a second of fleet-view lag
INTERVAL_ENV = "DL4J_FED_INTERVAL"
DEFAULT_INTERVAL_S = 0.25

#: max flight events shipped per frame / read back from a dead bundle —
#: bounds frame size and fleet-bundle assembly cost
MAX_EVENTS_PER_FRAME = 512

_LabelKey = Tuple[Tuple[str, str], ...]


# ----------------------------------------------------------- merge algebra

def _row_key(row: dict) -> _LabelKey:
    return tuple(sorted((k, str(v)) for k, v in row["labels"].items()))


def _copy_row(row: dict) -> dict:
    out = dict(row)
    out["labels"] = dict(row["labels"])
    if "bucket_counts" in out:
        out["bucket_counts"] = list(out["bucket_counts"])
        out["buckets"] = list(out["buckets"])
    return out


def _merge_hist_row(dst: dict, src: dict) -> None:
    """Bucket-wise histogram merge. Series whose bucket boundaries disagree
    (a version-skewed member) degrade conservatively: the foreign counts
    land in ``+Inf`` only, so cumulative ``le`` series never lie low."""
    dst["sum"] += src["sum"]
    dst["count"] += src["count"]
    if list(dst["buckets"]) == list(src["buckets"]):
        dc, sc = dst["bucket_counts"], src["bucket_counts"]
        for i in range(len(dc)):
            dc[i] += sc[i]
    else:
        dst["bucket_counts"][-1] += sum(src["bucket_counts"])


def merge_snapshots(snapshots: Sequence[dict]) -> dict:
    """Merge ``MetricsRegistry.snapshot()``-shaped dicts into one: counters
    summed, histograms merged bucket-wise, gauges last-write (argument
    order is write order). Associative and order-independent for counters
    and histograms — pinned in tests/test_federation.py. A family whose
    type disagrees with an earlier snapshot's (version skew) is skipped."""
    acc: Dict[str, dict] = {}
    for snap in snapshots:
        for name, fam in snap.items():
            a = acc.get(name)
            if a is None:
                a = acc[name] = {"type": fam["type"],
                                 "help": fam.get("help", ""), "rows": {}}
            elif a["type"] != fam["type"]:
                continue
            for row in fam.get("series", ()):
                key = _row_key(row)
                cur = a["rows"].get(key)
                if cur is None:
                    a["rows"][key] = _copy_row(row)
                elif a["type"] == "counter":
                    cur["value"] += row["value"]
                elif a["type"] == "gauge":
                    cur["value"] = row["value"]
                else:
                    _merge_hist_row(cur, row)
    return {name: {"type": a["type"], "help": a["help"],
                   "series": [a["rows"][k] for k in sorted(a["rows"])]}
            for name, a in sorted(acc.items())}


def tag_snapshot(snapshot: dict, labels: Dict[str, str]) -> dict:
    """Copy of ``snapshot`` with ``labels`` merged into every series — how
    the fleet view attributes each member's series (``worker=...,
    role=...``) before the big merge."""
    out: Dict[str, dict] = {}
    for name, fam in snapshot.items():
        rows = []
        for row in fam.get("series", ()):
            r = _copy_row(row)
            r["labels"].update(labels)
            rows.append(r)
        out[name] = {"type": fam["type"], "help": fam.get("help", ""),
                     "series": rows}
    return out


def strip_gauges(snapshot: dict) -> dict:
    """Drop gauge families — what happens to a dead/fenced member's
    snapshot at export time (its counters remain, frozen)."""
    return {name: fam for name, fam in snapshot.items()
            if fam["type"] != "gauge"}


def _member_label_key(role: str) -> str:
    return {"worker": "worker", "replica": "replica"}.get(role, "member")


# -------------------------------------------------------------- federation

class _Member:
    """One ``(member, epoch)`` generation's latest cumulative state."""

    __slots__ = ("name", "member", "epoch", "role", "seq", "snapshot",
                 "events", "fenced", "final", "last_ts", "frames", "bytes")

    def __init__(self, name: str, member: Optional[int], epoch: int,
                 role: str):
        self.name = name
        self.member = member
        self.epoch = int(epoch)
        self.role = role
        self.seq = 0
        self.snapshot: dict = {}
        self.events: List[dict] = []
        self.fenced = False
        self.final = False
        self.last_ts = 0.0
        self.frames = 0
        self.bytes = 0


class FederatedRegistry:
    """Coordinator-side merge point for member metric/trace/event frames.

    ``validate`` is the PR 13 fencing hook — ``MembershipOracle.validate``
    (side-effect-free, never renews) — so a zombie whose lease lapsed or
    was superseded cannot keep writing into the fleet view, mirroring
    exactly the parameter server's push fencing.
    """

    #: a member generation whose gauges stay exported this long after its
    #: last frame even without a validate hook; past it the series is
    #: presumed dead (a SIGKILL'd worker never sends a final frame)
    STALE_AFTER_S = 30.0

    def __init__(self, *,
                 validate: Optional[Callable[[int, int], bool]] = None,
                 registry=None, trace_store=None, clock=time.time):
        self._lock = threading.Lock()
        self._members: Dict[Tuple[str, int], _Member] = {}
        self.validate = validate
        self._clock = clock
        if registry is None:
            registry = global_registry()
        self._registry = registry
        if trace_store is None:
            # resolve eagerly: constructing the global store is what turns
            # the trace plane ON (trace_span no-ops while it is unbuilt),
            # and the coordinator must be tracing BEFORE its first
            # shard-publish span, not from the first ingested frame
            from .tracing import global_trace_store
            trace_store = global_trace_store()
        self._trace_store = trace_store
        self._c_frames = registry.counter(
            _n.FED_FRAMES_TOTAL, "federation frames ingested (by outcome)")
        self._c_bytes = registry.counter(
            _n.FED_BYTES_TOTAL, "federation frame payload bytes ingested")
        self._c_traces = registry.counter(
            _n.FED_TRACE_RECORDS_TOTAL,
            "trace records stitched from member frames")
        self._g_members = registry.gauge(
            _n.FED_MEMBERS, "member generations known to the federation")

    def _store(self):
        if self._trace_store is not None:
            return self._trace_store
        from .tracing import global_trace_store
        return global_trace_store()

    # ------------------------------------------------------------- ingest
    def ingest(self, *, name: str, epoch: int, seq: int, snapshot: dict,
               member: Optional[int] = None, role: str = "worker",
               events: Sequence[dict] = (), traces: Sequence[dict] = (),
               final: bool = False, nbytes: int = 0) -> dict:
        """Apply one member frame; returns ``{"accepted", "fenced"}``.

        A frame from a fenced ``(member, epoch)`` is rejected wholesale
        (the zombie's series stop updating at their last accepted values);
        a frame whose ``seq`` is not newer than the last accepted one is a
        duplicate/reorder and is discarded.
        """
        fenced = False
        if self.validate is not None and member is not None and not final:
            fenced = not self.validate(member, epoch)
        with self._lock:
            key = (name, int(epoch))
            m = self._members.get(key)
            if fenced:
                if m is not None:
                    m.fenced = True
                outcome = "fenced"
            elif m is not None and seq <= m.seq:
                outcome = "stale"
            else:
                if m is None:
                    m = self._members[key] = _Member(
                        name, member, epoch, role)
                m.seq = int(seq)
                m.snapshot = snapshot
                m.final = m.final or bool(final)
                m.last_ts = self._clock()
                m.frames += 1
                m.bytes += int(nbytes)
                if events:
                    m.events.extend(events)
                    del m.events[:-MAX_EVENTS_PER_FRAME]
                outcome = "accepted"
            n_members = len(self._members)
        self._c_frames.labels(outcome=outcome).inc()
        self._c_bytes.inc(max(0, int(nbytes)))
        self._g_members.set(n_members)
        if outcome == "accepted" and traces:
            self.ingest_traces(traces)
        return {"accepted": outcome == "accepted", "fenced": fenced}

    def ingest_traces(self, records: Sequence[dict]) -> None:
        """Stitch member-shipped trace records into the coordinator's
        TraceStore (span-id-deduped late-fragment merge)."""
        store = self._store()
        n = 0
        for rec in records:
            if isinstance(rec, dict):
                store.ingest(rec)
                n += 1
        if n:
            self._c_traces.inc(n)

    def note_member(self, *, name: str, epoch: int, role: str,
                    member: Optional[int] = None) -> None:
        """Register a member row without a metrics frame — how in-process
        members (serving replicas, which share the coordinator registry)
        appear in the fleet member table."""
        with self._lock:
            key = (name, int(epoch))
            m = self._members.get(key)
            if m is None:
                m = self._members[key] = _Member(name, member, epoch, role)
            m.last_ts = self._clock()
            n = len(self._members)
        self._g_members.set(n)

    def retire_member(self, name: str, epoch: int) -> None:
        """Mark a member generation done (graceful leave / scale-in): its
        gauges drop from the export, its counters stay."""
        with self._lock:
            m = self._members.get((name, int(epoch)))
            if m is not None:
                m.final = True

    # -------------------------------------------------------------- reads
    def _live(self, m: _Member, now: float) -> bool:
        """Should this generation's *gauges* still be exported?"""
        if m.fenced or m.final:
            return False
        if self.validate is not None and m.member is not None:
            return self.validate(m.member, m.epoch)
        return now - m.last_ts <= self.STALE_AFTER_S

    def _member_rows(self) -> List[Tuple[_Member, dict]]:
        """(member, export-filtered snapshot) pairs in last-update order —
        the order gauge last-write resolves in."""
        now = self._clock()
        with self._lock:
            members = sorted(self._members.values(),
                             key=lambda m: (m.last_ts, m.name, m.epoch))
            out = []
            for m in members:
                snap = m.snapshot
                if snap and not self._live(m, now):
                    snap = strip_gauges(snap)
                out.append((m, snap))
            return out

    def totals(self) -> dict:
        """The merged fleet snapshot WITHOUT member labels: counter totals
        across every generation that ever reported (monotonic), gauges from
        live generations only."""
        return merge_snapshots([s for _, s in self._member_rows() if s])

    def fleet_snapshot(self, local: bool = True) -> dict:
        """The labeled fleet view: every member's series tagged
        ``worker``/``replica``/``member`` + ``role``, the coordinator's own
        registry included as ``role="coordinator"`` when ``local``."""
        snaps = []
        if local:
            snaps.append(tag_snapshot(
                self._registry.snapshot(),
                {"member": f"{socket.gethostname()}-{os.getpid()}",
                 "role": "coordinator"}))
        for m, snap in self._member_rows():
            if not snap:
                continue
            snaps.append(tag_snapshot(
                snap, {_member_label_key(m.role): m.name, "role": m.role}))
        return merge_snapshots(snaps)

    def prometheus_text(self) -> str:
        """The ``GET /fleet/metrics`` payload."""
        return render_prometheus(self.fleet_snapshot())

    def member_events(self) -> Dict[str, List[dict]]:
        """Each member generation's shipped flight events (for the fleet
        bundle), keyed ``name@epoch``."""
        with self._lock:
            return {f"{m.name}@{m.epoch}": list(m.events)
                    for m in self._members.values() if m.events}

    def status(self) -> dict:
        now = self._clock()
        with self._lock:
            members = sorted(self._members.values(),
                             key=lambda m: (m.name, m.epoch))
            rows = [{
                "name": m.name, "member": m.member, "epoch": m.epoch,
                "role": m.role, "seq": m.seq, "frames": m.frames,
                "bytes": m.bytes, "fenced": m.fenced, "final": m.final,
                "age_s": round(max(0.0, now - m.last_ts), 3),
                "live": self._live(m, now),
            } for m in members]
        return {"members": rows, "generations": len(rows)}


# ----------------------------------------------------- worker-side publish

def _interval_s() -> float:
    try:
        return float(os.environ.get(INTERVAL_ENV, DEFAULT_INTERVAL_S))
    except (TypeError, ValueError):
        return DEFAULT_INTERVAL_S


class MetricsPublisher:
    """Worker-side federation pump: a daemon thread that ships cumulative
    registry snapshots + new flight events + newly-finalized trace records
    over ``transport.push_metrics`` every ``interval_s``.

    The transport handed in must be the publisher's OWN connection
    (``transport.clone()`` for TCP — the base connection is single-threaded
    by contract). ``stop(final=True)`` joins the thread and then runs one
    last flush from the calling thread, which is what makes fleet counter
    totals exact at worker exit: the final frame carries the complete
    cumulative snapshot, and cumulative-replace semantics make it idempotent.
    """

    def __init__(self, transport, *, name: str, role: str = "worker",
                 interval_s: Optional[float] = None, registry=None,
                 recorder=None, trace_store=None):
        self._transport = transport
        self.name = name
        self.role = role
        self.interval_s = _interval_s() if interval_s is None \
            else float(interval_s)
        self._registry = registry if registry is not None \
            else global_registry()
        self._recorder = recorder
        if trace_store is None:
            # same eager resolution as FederatedRegistry: a process running
            # a publisher is part of the fleet trace plane, so build the
            # global store now — before the worker's first broker.consume
            from .tracing import global_trace_store
            trace_store = global_trace_store()
        self._trace_store = trace_store
        self._seq = 0
        self._ev_ts = 0.0
        self._trace_cursor = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.frames_sent = 0
        self.fenced = False
        self._s_publish = self._registry.histogram(
            _n.FED_PUBLISH_SECONDS,
            "wall seconds per federation publish flush").labels()

    def _recorder_events(self) -> List[dict]:
        rec = self._recorder
        if rec is None:
            from .flight_recorder import global_recorder
            rec = self._recorder = global_recorder()
        evs = [e for e in rec.snapshot() if e.get("ts", 0.0) > self._ev_ts]
        evs = evs[-MAX_EVENTS_PER_FRAME:]
        if evs:
            self._ev_ts = evs[-1].get("ts", self._ev_ts)
        return evs

    def _traces(self) -> List[dict]:
        store = self._trace_store
        if store is None:
            from .tracing import global_trace_store
            store = self._trace_store = global_trace_store()
        cursor, recs = store.drain_finished(self._trace_cursor)
        self._trace_cursor = cursor
        return recs

    def flush(self, final: bool = False) -> bool:
        """One publish frame; returns False when the transport declined
        (older coordinator) or the frame bounced. Cursor state only
        advances on success, so a failed flush retries everything."""
        t0 = time.perf_counter()
        snap = self._registry.snapshot()
        events = self._recorder_events()
        traces = self._traces()
        self._seq += 1
        try:
            res = self._transport.push_metrics(
                snap, seq=self._seq, name=self.name, role=self.role,
                events=events, traces=traces, final=final)
        except Exception as e:
            log.debug("federation publish failed: %r", e)
            res = None
        self._s_publish.observe(time.perf_counter() - t0)
        if not res or not res.get("accepted"):
            self.fenced = bool(res and res.get("fenced"))
            return False
        self.frames_sent += 1
        return True

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            self.flush()

    def start(self) -> "MetricsPublisher":
        self._thread = threading.Thread(
            target=self._run, name="dl4j-fed-publisher", daemon=True)
        self._thread.start()
        return self

    def stop(self, final: bool = True, timeout: float = 5.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout)
            self._thread = None
        if final:
            self.flush(final=True)


# ------------------------------------------------------------ fleet bundle

class FleetCollector:
    """Assembles ONE diagnostic bundle for the whole fleet: the
    coordinator's recorder ring, every member's federation-shipped events,
    and the last on-disk bundle of each dead worker pid found under the
    shared recorder dump dir (which is why elastic ships
    ``DL4J_FLIGHT_RECORDER_DIR`` into child env). The merged timeline is
    ordered by wall timestamp — the only causal order available across
    hosts without a fleet clock — with each line tagged by source."""

    def __init__(self, *, federation: Optional[FederatedRegistry] = None,
                 recorder=None, dir: Optional[str] = None,
                 min_interval_s: float = 5.0, registry=None):
        if recorder is None:
            from .flight_recorder import global_recorder
            recorder = global_recorder()
        self.recorder = recorder
        self.federation = federation
        self.dir = dir
        self.min_interval_s = float(min_interval_s)
        self._lock = threading.Lock()
        self._last_dump = 0.0
        self._seq = 0
        self._c_dumps = (registry or global_registry()).counter(
            _n.FLEET_DUMPS_TOTAL, "fleet flight bundles written (by reason)")

    def _dead_bundle_events(self, base: str) -> List[dict]:
        """Newest bundle per foreign pid, its events tagged by source."""
        newest: Dict[int, dict] = {}
        for m in self.recorder.list_bundles(base):
            pid = m.get("pid")
            if pid is None or pid == os.getpid():
                continue
            if str(os.path.basename(m.get("path", ""))).startswith("fleet-"):
                continue
            if pid not in newest:  # list_bundles is newest-first
                newest[pid] = m
        out: List[dict] = []
        for pid, m in newest.items():
            src = f"bundle:{os.path.basename(m['path'])}"
            try:
                with open(os.path.join(m["path"], "events.jsonl")) as f:
                    lines = f.readlines()[-MAX_EVENTS_PER_FRAME:]
            except OSError:
                continue
            for line in lines:
                try:
                    ev = json.loads(line)
                except ValueError:
                    continue
                ev["source"] = src
                out.append(ev)
        return out

    def dump(self, reason: str = "manual",
             force: bool = False) -> Optional[str]:
        """Write the fleet bundle; returns its path, or None when no dump
        dir is configured or the rate limit holds (trigger sites — shard
        handoff, SLO alert edges — are then free no-ops)."""
        base = self.dir or self.recorder.dump_dir
        if base is None:
            return None
        now = time.time()
        with self._lock:
            if not force and now - self._last_dump < self.min_interval_s:
                return None
            self._last_dump = now
            self._seq += 1
            seq = self._seq
        timeline: List[dict] = []
        for ev in self.recorder.snapshot():
            e = dict(ev)
            e["source"] = "coordinator"
            timeline.append(e)
        member_events = self.federation.member_events() \
            if self.federation is not None else {}
        for src, evs in member_events.items():
            for ev in evs:
                e = dict(ev)
                e["source"] = src
                timeline.append(e)
        timeline.extend(self._dead_bundle_events(base))
        timeline.sort(key=lambda e: (e.get("ts", 0.0),
                                     str(e.get("source", ""))))
        stamp = time.strftime("%Y%m%d-%H%M%S")
        name = f"fleet-{stamp}-p{os.getpid()}-{seq:03d}"
        path = os.path.join(base, name)
        try:
            os.makedirs(path, exist_ok=True)
            with open(os.path.join(path, "merged_timeline.jsonl"), "w") as f:
                for ev in timeline:
                    f.write(json.dumps(ev, default=repr) + "\n")
            files = ["merged_timeline.jsonl"]

            def write_json(fname, obj):
                with open(os.path.join(path, fname), "w") as f:
                    json.dump(obj, f, indent=2, default=repr)
                    f.write("\n")
                files.append(fname)

            if self.federation is not None:
                write_json("metrics.json", self.federation.totals())
                write_json("status.json", self.federation.status())
            write_json("manifest.json", {
                "reason": reason, "ts": now, "pid": os.getpid(),
                "fleet": True, "events": len(timeline),
                "sources": sorted({e["source"] for e in timeline}),
                "files": files + ["manifest.json"],
            })
        except OSError as e:
            log.error("fleet collector could not write bundle %s: %r",
                      path, e)
            return None
        self._c_dumps.labels(reason=reason).inc()
        log.warning("fleet collector: wrote bundle %s (%s)", path, reason)
        return path


# ----------------------------------------------------------------- globals

_FED: Optional[FederatedRegistry] = None
_COLLECTOR: Optional[FleetCollector] = None
_PROVIDERS: Dict[str, Callable[[], Any]] = {}
_GLOBALS_LOCK = threading.Lock()


def global_federation() -> Optional[FederatedRegistry]:
    return _FED


def set_global_federation(fed: Optional[FederatedRegistry]) -> None:
    global _FED
    _FED = fed


def global_fleet_collector() -> Optional[FleetCollector]:
    return _COLLECTOR


def set_global_fleet_collector(col: Optional[FleetCollector]) -> None:
    global _COLLECTOR
    _COLLECTOR = col


def register_status_provider(name: str,
                             fn: Optional[Callable[[], Any]]) -> None:
    """Attach a named block to ``/fleet/status`` (elastic stats, the
    serving fleet, the autoscaler). ``None`` unregisters."""
    with _GLOBALS_LOCK:
        if fn is None:
            _PROVIDERS.pop(name, None)
        else:
            _PROVIDERS[name] = fn


def fleet_status() -> dict:
    """The ``GET /fleet/status`` payload: the federation member table plus
    every registered subsystem block."""
    fed = _FED
    out: Dict[str, Any] = {
        "federation": fed.status() if fed is not None else None}
    with _GLOBALS_LOCK:
        providers = dict(_PROVIDERS)
    for name, fn in sorted(providers.items()):
        try:
            out[name] = fn()
        except Exception as e:  # one sick subsystem must not 500 the page
            out[name] = {"error": repr(e)}
    return out


def fleet_metrics_text() -> str:
    """The ``GET /fleet/metrics`` payload. With no federation running this
    degrades to an HONEST single-member fleet — the local registry labeled
    as this one process — never an unlabeled local snapshot masquerading
    as fleet truth."""
    fed = _FED
    if fed is not None:
        return fed.prometheus_text()
    snap = tag_snapshot(
        global_registry().snapshot(),
        {"member": f"{socket.gethostname()}-{os.getpid()}", "role": "local"})
    return render_prometheus(snap)


def trigger_fleet_dump(reason: str, force: bool = False) -> Optional[str]:
    """Fire the global fleet collector if one is installed — the hook the
    SLO alert edge, the elastic shard-handoff path, and the explicit
    ``/fleet/dump`` API all call."""
    col = _COLLECTOR
    if col is None:
        return None
    return col.dump(reason=reason, force=force)
