"""End-to-end request tracing: W3C trace context + tail-sampled store.

The aggregate planes (PR 2 metrics, PR 5 flight recorder, PR 7 XPlane
attribution) answer "how is the fleet doing"; this plane answers "where did
THIS request's 480ms go" — admission wait vs batcher queue vs bucket-growth
stall vs paged-KV park vs device dispatch. It is Dapper-shaped and
deliberately tiny:

- **Ids** are W3C ``traceparent``-compatible: ``00-<32 hex trace>-<16 hex
  span>-<2 hex flags>``. :func:`parse_traceparent` accepts an incoming
  header (so an upstream gateway's ids propagate through us) and
  :meth:`Span.traceparent` re-serializes for the response echo / onward hop.
- **Propagation** is a contextvar: ``trace_span(name)`` parents under the
  ambient span on the same thread. Cross-thread hops (the MicroBatcher's
  dispatcher, the DecodeEngine's pump) carry an explicit :class:`SpanRef`
  on the request/session object instead — contextvars do not follow work
  across threads, and the dispatch side links or parents from the ref.
- **Fan-in** uses span links: one batch dispatch span *links* the N parent
  request traces rather than picking one parent (OTel batch-consumer
  semantics), so ``/serve/traces/<id>`` can walk from any member request to
  the shared dispatch.
- **Sampling is tail-based**: every span of a live trace is buffered; the
  keep/drop decision happens when the trace completes, so error traces,
  429'd admissions and p99-exceeding requests are ALWAYS kept even at a
  head probability of 0. Ordinary traces are kept with probability
  ``DL4J_TRACE_SAMPLE`` (default 1.0 — the ring bounds memory, not the
  sampler).
- **Zero-alloc when off**: ``trace_span()``/``start_span()`` return one
  process-wide no-op singleton when tracing is disabled — no generator, no
  Span object, no dict — so the serve hot path pays one attribute load.

Persistence mirrors the PR 7 profile index: kept traces append to
``traces.jsonl`` and index into ``trace_index.db`` (a FileStatsStorage
sqlite file) beside ``profile_index.db`` when a base dir is configured
(``DL4J_TRACE_DIR``); the in-memory ring serves ``GET /serve/traces``
either way.
"""
from __future__ import annotations

import collections
import contextvars
import json
import os
import random
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from . import names as _n

ENABLE_ENV = "DL4J_TRACE"
SAMPLE_ENV = "DL4J_TRACE_SAMPLE"
DIR_ENV = "DL4J_TRACE_DIR"
CAPACITY_ENV = "DL4J_TRACE_CAPACITY"
TRACEPARENT_HEADER = "traceparent"
INDEX_DB = "trace_index.db"
TRACES_JSONL = "traces.jsonl"
#: completed traces retained in memory (ring; oldest evicted)
DEFAULT_CAPACITY = 512
#: sqlite index session/type ids (FileStatsStorage vocabulary)
_INDEX_SESSION = "traces"
_INDEX_TYPE = "TraceRecord"

_rand = random.Random()


# --------------------------------------------------------------------- ids

def _new_trace_id() -> str:
    return f"{_rand.getrandbits(128):032x}"


def _new_span_id() -> str:
    return f"{_rand.getrandbits(64):016x}"


def parse_traceparent(header: Optional[str]) -> Optional["SpanRef"]:
    """``00-<32hex>-<16hex>-<2hex>`` -> SpanRef, else None (malformed
    headers mint a fresh trace rather than erroring the request)."""
    if not header:
        return None
    parts = header.strip().split("-")
    if len(parts) != 4:
        return None
    ver, trace_id, span_id, _flags = parts
    if len(ver) != 2 or len(trace_id) != 32 or len(span_id) != 16:
        return None
    try:
        int(ver, 16), int(trace_id, 16), int(span_id, 16)
    except ValueError:
        return None
    if ver == "ff" or trace_id == "0" * 32 or span_id == "0" * 16:
        return None
    return SpanRef(trace_id, span_id)


def format_traceparent(trace_id: str, span_id: str) -> str:
    return f"00-{trace_id}-{span_id}-01"


class SpanRef:
    """A (trace_id, span_id) pair that travels across threads/objects where
    the contextvar cannot — on ``_Request`` slots, ``DecodeSession``s, and
    as batch span links."""

    __slots__ = ("trace_id", "span_id")

    def __init__(self, trace_id: str, span_id: str):
        self.trace_id = trace_id
        self.span_id = span_id

    def traceparent(self) -> str:
        return format_traceparent(self.trace_id, self.span_id)

    def __repr__(self):  # pragma: no cover - debugging aid
        return f"SpanRef({self.traceparent()})"


# ------------------------------------------------------------------- spans

_current: contextvars.ContextVar[Optional["Span"]] = \
    contextvars.ContextVar("dl4j_trace_span", default=None)


class Span:
    """One timed operation. Context-manager entry makes it the ambient
    parent for the thread; manual ``start_span``/``finish()`` use skips the
    contextvar entirely (cross-thread spans owned by request/session
    objects)."""

    __slots__ = ("trace_id", "span_id", "parent_id", "name", "ts", "mono",
                 "status", "attrs", "links", "_store", "_t0", "_token",
                 "_finished")

    def __init__(self, store: "TraceStore", name: str,
                 parent: Optional[object], links: Tuple[SpanRef, ...],
                 attrs: Optional[Dict[str, Any]]):
        if parent is None:
            parent = _current.get()
        if parent is not None:
            self.trace_id = parent.trace_id
            self.parent_id = parent.span_id
        else:
            self.trace_id = _new_trace_id()
            self.parent_id = None
        self.span_id = _new_span_id()
        self.name = name
        self.ts = time.time()
        self.mono = time.perf_counter()
        self._t0 = self.mono
        self.status = "ok"
        self.attrs = attrs or {}
        self.links = tuple(links)
        self._store = store
        self._token = None
        self._finished = False
        store._open(self)

    # -- mutation -------------------------------------------------------
    def set_attr(self, **kv) -> "Span":
        self.attrs.update(kv)
        return self

    def add_link(self, ref: Optional[SpanRef]) -> "Span":
        if ref is not None:
            self.links = self.links + (ref,)
        return self

    def set_status(self, status: str) -> "Span":
        self.status = status
        return self

    # -- identity -------------------------------------------------------
    def ref(self) -> SpanRef:
        return SpanRef(self.trace_id, self.span_id)

    def traceparent(self) -> str:
        return format_traceparent(self.trace_id, self.span_id)

    # -- lifecycle ------------------------------------------------------
    def finish(self) -> None:
        if self._finished:
            return
        self._finished = True
        dur = time.perf_counter() - self._t0
        self._store._close(self, dur)

    def __enter__(self) -> "Span":
        self._token = _current.set(self)
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        if self._token is not None:
            _current.reset(self._token)
            self._token = None
        if exc_type is not None and self.status == "ok":
            self.status = "error"
            self.attrs.setdefault("error", repr(exc))
        self.finish()
        return False


class _NoopSpan:
    """The disabled path: one shared instance, every method a no-op, usable
    both as a context manager and via manual finish()."""

    __slots__ = ()
    trace_id = ""
    span_id = ""
    parent_id = None
    name = ""
    status = "ok"
    links = ()

    def set_attr(self, **kv):
        return self

    def add_link(self, ref):
        return self

    def set_status(self, status):
        return self

    def ref(self):
        return None

    def traceparent(self) -> str:
        return ""

    def finish(self) -> None:
        pass

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        return False


NOOP_SPAN = _NoopSpan()


def trace_span(name: str, *, parent: Optional[object] = None,
               links: Tuple[SpanRef, ...] = (), **attrs):
    """Start a span for ``with`` use: child of ``parent`` (a Span or
    SpanRef), else of the thread's ambient span, else a new trace root.
    Returns the no-op singleton when tracing is off."""
    st = _STORE
    if st is None or not st.enabled:
        return NOOP_SPAN
    return Span(st, name, parent, links, attrs)


def start_span(name: str, *, parent: Optional[object] = None,
               links: Tuple[SpanRef, ...] = (), **attrs):
    """Start a span WITHOUT entering it (the cross-thread form: the caller
    owns it on an object attribute and calls ``finish()`` later; the
    ambient contextvar is untouched). The graftlint ``orphan-span`` rule
    polices locals created this way."""
    st = _STORE
    if st is None or not st.enabled:
        return NOOP_SPAN
    return Span(st, name, parent, links, attrs)


def current_span() -> Optional[Span]:
    """The thread's ambient span (None outside any ``with trace_span``)."""
    return _current.get()


# ------------------------------------------------------------------- store

def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except (TypeError, ValueError):
        return default


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except (TypeError, ValueError):
        return default


class _TraceRecord:
    """Duck-typed Persistable for the FileStatsStorage sqlite index — the
    same vocabulary ProfileRecord uses for profile_index.db."""

    def __init__(self, entry: dict):
        self.entry = entry

    def get_session_id(self) -> str:
        return _INDEX_SESSION

    def get_type_id(self) -> str:
        return _INDEX_TYPE

    def get_worker_id(self) -> str:
        return self.entry.get("trace_id", "?")

    def get_timestamp(self) -> int:
        return int(self.entry.get("ts", 0.0) * 1000)

    def encode(self) -> bytes:
        return json.dumps(self.entry).encode("utf-8")

    @classmethod
    def decode(cls, data: bytes) -> "_TraceRecord":
        return cls(json.loads(data.decode("utf-8")))


class TraceStore:
    """Bounded store of completed span trees with tail-based sampling.

    Live traces accumulate finished spans in ``_live``; when a trace's last
    open span closes the tree is finalized and the keep/drop decision runs:
    error/rejected status and roots slower than the rolling p99 ALWAYS
    keep, everything else keeps with probability ``sample``. Kept traces
    enter the in-memory ring (and the JSONL + sqlite index when
    ``base_dir`` is set); dropped traces count into
    ``dl4j_trace_traces_dropped_total`` and vanish.
    """

    def __init__(self, *, capacity: Optional[int] = None,
                 sample: Optional[float] = None,
                 base_dir: Optional[str] = None,
                 enabled: Optional[bool] = None,
                 registry=None):
        if enabled is None:
            enabled = os.environ.get(ENABLE_ENV, "1").lower() \
                not in ("0", "false", "off")
        self.enabled = bool(enabled)
        self.capacity = capacity if capacity is not None \
            else _env_int(CAPACITY_ENV, DEFAULT_CAPACITY)
        self.sample = sample if sample is not None \
            else _env_float(SAMPLE_ENV, 1.0)
        self.base_dir = base_dir if base_dir is not None \
            else os.environ.get(DIR_ENV) or None
        self._lock = threading.Lock()
        #: trace_id -> {"open": int, "spans": [span dict, ...]}
        self._live: Dict[str, dict] = {}
        #: trace_id -> finalized record (insertion-ordered ring)
        self._ring: "collections.OrderedDict[str, dict]" = \
            collections.OrderedDict()
        self._durs: collections.deque = collections.deque(maxlen=512)  #: guarded-by: _lock
        #: cached rolling p99 — re-sorting 512 floats on EVERY finalize is
        #: the single biggest cost on the serve hot path, and a tail
        #: threshold that lags by <32 traces samples identically in
        #: practice
        self._p99_cache: Optional[float] = None  #: guarded-by: _lock
        self._p99_stale = 0  #: guarded-by: _lock
        #: metric name -> [(value, trace_id, ts), ...] worst-first, <=8
        self._exemplars: Dict[str, List[Tuple[float, str, float]]] = {}
        if registry is None:
            from .metrics import global_registry
            registry = global_registry()
        self._c_spans = registry.counter(
            _n.TRACE_SPANS_TOTAL, "trace spans finished")
        self._c_kept = registry.counter(
            _n.TRACE_TRACES_KEPT_TOTAL,
            "completed traces kept by the tail sampler (by reason)")
        self._c_dropped = registry.counter(
            _n.TRACE_TRACES_DROPPED_TOTAL,
            "completed traces dropped by the tail sampler")
        self._g_live = registry.gauge(
            _n.TRACE_LIVE_TRACES, "traces with open spans right now")
        #: pre-resolved label series — labels() re-keys the labelset dict
        #: on every call, which adds up at one spans-counter inc per span
        self._s_spans: Dict[str, object] = {}
        self._s_kept = {r: self._c_kept.labels(reason=r)
                        for r in ("error", "p99", "sampled")}
        self._s_dropped = {r: self._c_dropped.labels(reason=r)
                           for r in ("sampled_out", "live_overflow")}
        self._sg_live = self._g_live.labels()
        self._index_failed = False
        #: monotonic finalize counter; every record entering the ring gets
        #: the next value so drain_finished() can ship "new since seq N"
        #: to the fleet federation without re-sending the whole ring
        self._seq = 0  #: guarded-by: _lock

    # -- span bookkeeping ----------------------------------------------
    def _open(self, span: Span) -> None:
        with self._lock:
            t = self._live.get(span.trace_id)
            if t is None:
                # leak guard: a span started but never finished (a crashed
                # session) must not pin memory forever
                if len(self._live) >= 4 * self.capacity:
                    self._live.pop(next(iter(self._live)))
                    self._s_dropped["live_overflow"].inc()
                t = self._live[span.trace_id] = {"open": 0, "spans": []}
                self._sg_live.set(len(self._live))
            t["open"] += 1

    def _close(self, span: Span, dur_s: float) -> None:
        series = self._s_spans.get(span.name)
        if series is None:
            # span names are a small code-defined set; cap the handle
            # cache anyway so a buggy dynamic name can't grow it
            series = self._c_spans.labels(name=span.name)
            if len(self._s_spans) < 64:
                self._s_spans[span.name] = series
        series.inc()
        done = None
        with self._lock:
            t = self._live.get(span.trace_id)
            if t is None:  # evicted by the leak guard mid-flight
                return
            t["spans"].append({
                "trace_id": span.trace_id, "span_id": span.span_id,
                "parent_id": span.parent_id, "name": span.name,
                "ts": span.ts, "mono": span.mono,
                "dur_s": round(dur_s, 9), "status": span.status,
                "attrs": span.attrs,
                "links": [r.traceparent() for r in span.links],
            })
            t["open"] -= 1
            if t["open"] <= 0:
                done = self._live.pop(span.trace_id)
                self._sg_live.set(len(self._live))
        if done is not None:
            self._finalize(span.trace_id, done["spans"])

    # -- tail sampling --------------------------------------------------
    #: requires-lock: _lock
    def _p99(self) -> Optional[float]:
        if len(self._durs) < 20:
            return None
        if self._p99_cache is None or self._p99_stale >= 32:
            s = sorted(self._durs)
            self._p99_cache = s[min(len(s) - 1, int(len(s) * 0.99))]
            self._p99_stale = 0
        return self._p99_cache

    def _finalize(self, trace_id: str, spans: List[dict]) -> None:
        spans.sort(key=lambda s: s["mono"])
        root = next((s for s in spans if s["parent_id"] is None), spans[0])
        dur = root["dur_s"]
        bad = any(s["status"] != "ok" for s in spans)
        # tail-sampler state is shared by every finishing request thread:
        # unlocked, two finalizes race the p99 cache refresh, and
        # sorted(_durs) can see the deque mutate mid-iteration
        with self._lock:
            p99 = self._p99()
            self._durs.append(dur)
            self._p99_stale += 1
        if bad:
            reason = "error"
        elif p99 is not None and dur > p99:
            reason = "p99"
        elif self.sample >= 1.0 or _rand.random() < self.sample:
            reason = "sampled"
        else:
            self._s_dropped["sampled_out"].inc()
            return
        record = {"trace_id": trace_id, "root": root["name"],
                  "status": ("error" if bad else "ok"),
                  "ts": root["ts"], "dur_s": dur,
                  "n_spans": len(spans), "keep_reason": reason,
                  "spans": spans}
        with self._lock:
            prior = self._ring.pop(trace_id, None)
            if prior is not None:  # late fragment: merge, keep root info
                merged = prior["spans"] + spans
                merged.sort(key=lambda s: s["mono"])
                prior["spans"] = merged
                prior["n_spans"] = len(merged)
                record = prior
            self._seq += 1
            record["seq"] = self._seq
            self._ring[trace_id] = record
            while len(self._ring) > self.capacity:
                self._ring.popitem(last=False)
        self._s_kept[reason].inc()
        if self.base_dir:
            self._persist(record)

    # -- persistence (beside the PR 7 profile index) --------------------
    def _persist(self, record: dict) -> None:
        try:
            os.makedirs(self.base_dir, exist_ok=True)
            with open(os.path.join(self.base_dir, TRACES_JSONL), "a",
                      encoding="utf-8") as f:
                f.write(json.dumps(record) + "\n")
            entry = {k: record[k] for k in
                     ("trace_id", "root", "status", "ts", "dur_s",
                      "n_spans", "keep_reason")}
            from ..ui.storage import FileStatsStorage
            st = FileStatsStorage(os.path.join(self.base_dir, INDEX_DB))
            try:
                st.put_update(_TraceRecord(entry))
            finally:
                st.close()
        except Exception:
            # persistence must never fail a request; note once
            self._index_failed = True

    def index_entries(self) -> List[dict]:
        """Decoded rows of trace_index.db, newest first (empty when no
        base_dir or nothing kept yet)."""
        if not self.base_dir:
            return []
        path = os.path.join(self.base_dir, INDEX_DB)
        if not os.path.exists(path):
            return []
        from ..ui.storage import FileStatsStorage
        st = FileStatsStorage(path)
        out = []
        try:
            for wid in st.list_worker_ids_for_session(_INDEX_SESSION):
                for blob in st.get_all_updates_after(
                        _INDEX_SESSION, _INDEX_TYPE, wid, -1):
                    try:
                        out.append(_TraceRecord.decode(blob).entry)
                    except (ValueError, UnicodeDecodeError):
                        continue
        finally:
            st.close()
        out.sort(key=lambda e: e.get("ts", 0.0), reverse=True)
        return out

    # -- reads ----------------------------------------------------------
    def get(self, trace_id: str) -> Optional[dict]:
        with self._lock:
            rec = self._ring.get(trace_id)
            return dict(rec) if rec is not None else None

    def list(self, n: int = 50) -> List[dict]:
        """Newest-first summaries (no span bodies) for /serve/traces."""
        with self._lock:
            recs = list(self._ring.values())[-n:]
        return [{k: r[k] for k in ("trace_id", "root", "status", "ts",
                                   "dur_s", "n_spans", "keep_reason")}
                for r in reversed(recs)]

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    # -- federation (cross-process stitch) ------------------------------
    def drain_finished(self, after_seq: int = 0) -> Tuple[int, List[dict]]:
        """Records finalized since ``after_seq`` (the federation export
        cursor), plus the new cursor. A late-fragment merge re-stamps its
        record with a fresh seq, so a trace that grew after its first ship
        ships again — the receiving :meth:`ingest` dedups by span id."""
        with self._lock:
            recs = [dict(r) for r in self._ring.values()
                    if r.get("seq", 0) > after_seq]
            return self._seq, recs

    def ingest(self, record: dict) -> None:
        """Merge a finalized trace record from ANOTHER process into this
        store — the cross-process half of the late-fragment merge. A
        worker's ``broker.consume``/``ps.push`` fragment lands on the
        coordinator's copy of the same trace id: spans are deduped by span
        id and re-sorted by wall ``ts`` (mono clocks do not compare across
        processes), and the summary row (root, status, dur) is recomputed
        over the union so ``/serve/traces`` shows one stitched tree."""
        spans = list(record.get("spans") or ())
        trace_id = record.get("trace_id")
        if not trace_id or not spans:
            return
        with self._lock:
            prior = self._ring.pop(trace_id, None)
            if prior is not None:
                seen = {s.get("span_id") for s in prior["spans"]}
                spans = [s for s in spans if s.get("span_id") not in seen]
                merged = prior["spans"] + spans
                rec = dict(prior)
            else:
                merged = spans
                rec = {"trace_id": trace_id,
                       "keep_reason": record.get("keep_reason", "ingested")}
            merged.sort(key=lambda s: s.get("ts", 0.0))
            root = next((s for s in merged if s.get("parent_id") is None),
                        merged[0])
            rec["spans"] = merged
            rec["n_spans"] = len(merged)
            rec["root"] = root.get("name", "?")
            rec["ts"] = root.get("ts", 0.0)
            rec["dur_s"] = root.get("dur_s", 0.0)
            rec["status"] = "error" if any(
                s.get("status") != "ok" for s in merged) else "ok"
            self._seq += 1
            rec["seq"] = self._seq
            self._ring[trace_id] = rec
            while len(self._ring) > self.capacity:
                self._ring.popitem(last=False)

    # -- exemplars ------------------------------------------------------
    def put_exemplar(self, metric: str, value: float,
                     trace_id: str) -> None:
        """Attach a trace id to a histogram observation so a burning SLO
        over that histogram can name an offending trace. Keeps the <=8
        worst observations of the last 10 minutes per metric."""
        if not trace_id:
            return
        now = time.time()
        with self._lock:
            ex = self._exemplars.setdefault(metric, [])
            ex.append((value, trace_id, now))
            ex[:] = sorted((e for e in ex if now - e[2] < 600.0),
                           reverse=True)[:8]

    def exemplar(self, metric: str,
                 window_s: Optional[float] = None) -> Optional[dict]:
        now = time.time()
        with self._lock:
            for value, trace_id, ts in self._exemplars.get(metric, ()):
                if window_s is None or now - ts <= window_s:
                    return {"trace_id": trace_id, "value": value, "ts": ts}
        return None


# ----------------------------------------------------------------- globals

_STORE: Optional[TraceStore] = None
_STORE_LOCK = threading.Lock()


def global_trace_store() -> TraceStore:
    global _STORE
    if _STORE is None:
        with _STORE_LOCK:
            if _STORE is None:
                _STORE = TraceStore()
    return _STORE


def set_global_trace_store(store: Optional[TraceStore]) -> None:
    """Swap the process store (tests install a fresh one per case)."""
    global _STORE
    _STORE = store


def configure(*, enabled: Optional[bool] = None,
              sample: Optional[float] = None,
              base_dir: Optional[str] = None,
              capacity: Optional[int] = None) -> TraceStore:
    """CLI/bench knob: adjust the global store in place (creating it if
    needed) and return it."""
    st = global_trace_store()
    if enabled is not None:
        st.enabled = bool(enabled)
    if sample is not None:
        st.sample = float(sample)
    if base_dir is not None:
        st.base_dir = base_dir or None
    if capacity is not None:
        st.capacity = int(capacity)
    return st
