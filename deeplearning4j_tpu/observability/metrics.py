"""Process-global metrics registry: counters, gauges, fixed-bucket histograms.

The reference's observability pipeline (BaseStatsListener + SBE wire format +
training UI) is event-per-iteration push; what it cannot express is always-on
aggregate state in the Prometheus/xprof mold — monotonic counters a scraper
can rate(), HBM gauges, latency histograms. This registry is that layer: the
instrumentation spine the compile tracker, step-time attribution, span API,
``/metrics`` route, and ``--telemetry-out`` snapshots all write through.

Design constraints (why this is not just a dict of floats):

* **Hot-path cost.** Instrument points sit inside the fit loops between jitted
  dispatches, budgeted at <=2% of a LeNet step (pinned in
  tests/test_bench_contract.py). Every ``inc``/``observe``/``set`` is one lock
  acquire plus float arithmetic; label resolution (the dict work) happens once
  at ``labels()`` time, so call sites hold a pre-resolved series handle.
* **Lock-safe.** Listeners, the UI server thread, and async prefetch threads
  all touch the registry; one registry-wide ``threading.Lock`` guards series
  creation and every mutation (uncontended CPython lock ops are ~100ns —
  far inside the budget — and keep snapshot/exposition trivially consistent).
* **Kill switch.** ``set_enabled(False)`` turns every mutation into a no-op
  for overhead A/Bs; exposition still works on whatever was recorded.
"""
from __future__ import annotations

import json
import logging
import math
import os
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

from . import names as _names

log = logging.getLogger(__name__)

#: default histogram buckets (seconds): 100us .. ~100s, log-ish spacing —
#: covers everything from a listener callback to a cold XLA compile
DEFAULT_BUCKETS = (1e-4, 5e-4, 1e-3, 5e-3, 1e-2, 5e-2, 0.1, 0.5, 1.0, 5.0,
                   10.0, 60.0, 120.0)

_VALID_TYPES = ("counter", "gauge", "histogram")

#: max distinct labelsets one family will register; past it, labels() hands
#: back a detached overflow series (mutations work, exposition skips it) so
#: an unbounded label — a trace id, a session id — can never OOM the registry
LABELSET_CAP_ENV = "DL4J_METRICS_MAX_LABELSETS"
DEFAULT_MAX_LABELSETS = 256


def _labelset_cap() -> int:
    try:
        return int(os.environ.get(LABELSET_CAP_ENV, DEFAULT_MAX_LABELSETS))
    except (TypeError, ValueError):
        return DEFAULT_MAX_LABELSETS


class _Series:
    """One (metric, labelset) time series. Mutations take the registry lock."""

    __slots__ = ("family", "labels", "value", "bucket_counts", "sum", "count")

    def __init__(self, family: "_Family", labels: Tuple[Tuple[str, str], ...]):
        self.family = family
        self.labels = labels
        self.value = 0.0                      # counter / gauge
        if family.type == "histogram":
            self.bucket_counts = [0] * (len(family.buckets) + 1)  # +inf last
            self.sum = 0.0
            self.count = 0

    # -- mutation (call-site API; handles are cached by callers) ------------
    def inc(self, amount: float = 1.0) -> None:
        reg = self.family.registry
        if not reg._enabled:
            return
        if amount < 0:
            raise ValueError("counters only go up; use a gauge")
        with reg._lock:
            self.value += amount

    def set(self, value: float) -> None:
        reg = self.family.registry
        if not reg._enabled:
            return
        with reg._lock:
            self.value = float(value)

    def observe(self, value: float) -> None:
        reg = self.family.registry
        if not reg._enabled:
            return
        fam = self.family
        with reg._lock:
            self.sum += value
            self.count += 1
            i = 0
            n = len(fam.buckets)
            while i < n and value > fam.buckets[i]:
                i += 1
            self.bucket_counts[i] += 1

    def time(self):
        """``with series.time():`` — observe the block's wall seconds."""
        return _Timer(self)


class _Timer:
    __slots__ = ("series", "_t0")

    def __init__(self, series: _Series):
        self.series = series

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.series.observe(time.perf_counter() - self._t0)
        return False


class _Family:
    """A named metric with a help string; holds one series per labelset."""

    def __init__(self, registry: "MetricsRegistry", name: str, help: str,
                 type: str, buckets: Sequence[float] = DEFAULT_BUCKETS):
        self.registry = registry
        self.name = name
        self.help = help
        self.type = type
        self.buckets = tuple(buckets) if type == "histogram" else ()
        self._series: Dict[Tuple[Tuple[str, str], ...], _Series] = {}
        self._overflow: Optional[_Series] = None

    def labels(self, **labels: str) -> _Series:
        """Resolve (and memoize) the series for this labelset. Do this ONCE
        per call site, not per step — the returned handle is the hot path.

        Cardinality guard: once a family holds ``DL4J_METRICS_MAX_LABELSETS``
        distinct labelsets (default 256), unseen labelsets resolve to one
        shared detached series — writable but never exported — and each such
        call counts into ``dl4j_metrics_dropped_labelsets_total``."""
        key = tuple(sorted((k, str(v)) for k, v in labels.items()))
        reg = self.registry
        dropped = False
        with reg._lock:
            s = self._series.get(key)
            if s is None:
                if (len(self._series) >= reg._max_labelsets
                        and self.name !=
                        _names.METRICS_DROPPED_LABELSETS_TOTAL):
                    if self._overflow is None:
                        self._overflow = _Series(
                            self, (("overflow", "true"),))
                    s = self._overflow
                    dropped = True
                else:
                    s = self._series[key] = _Series(self, key)
        if dropped:
            reg._note_dropped_labelset(self.name)
        return s

    # label-less convenience: family acts as its own default series
    def inc(self, amount: float = 1.0) -> None:
        self.labels().inc(amount)

    def set(self, value: float) -> None:
        self.labels().set(value)

    def observe(self, value: float) -> None:
        self.labels().observe(value)

    def time(self):
        return self.labels().time()


class MetricsRegistry:
    """Prometheus-style registry: get-or-create families, text exposition,
    JSONL snapshots. One process-global instance (``global_registry()``)
    backs the framework instrumentation; tests construct private ones."""

    def __init__(self):
        self._lock = threading.Lock()
        self._families: Dict[str, _Family] = {}
        self._enabled = True
        self._max_labelsets = _labelset_cap()
        self._warned_families: Dict[str, float] = {}  #: guarded-by: _lock

    def _note_dropped_labelset(self, family: str) -> None:
        """Called (outside the lock) when a family refused a new labelset:
        count it, and warn at most once a minute per family."""
        self.counter(
            _names.METRICS_DROPPED_LABELSETS_TOTAL,
            "labels() calls refused a new series by the cardinality cap"
        ).labels(family=family).inc()
        now = time.time()
        # check-then-set on the rate-limit map must be atomic: two request
        # threads hitting the cap together both read a stale `last` and
        # both warn. The counter above already released self._lock, so
        # taking it here cannot deadlock.
        with self._lock:
            last = self._warned_families.get(family)
            warn = last is None or now - last >= 60.0
            if warn:
                self._warned_families[family] = now
        if warn:
            log.warning(
                "metric family %s hit the labelset cap (%d); further "
                "labelsets collapse into an unexported overflow series "
                "(raise %s to widen)", family, self._max_labelsets,
                LABELSET_CAP_ENV)

    # ------------------------------------------------------------- creation
    def _family(self, name: str, help: str, type: str,
                buckets: Sequence[float] = DEFAULT_BUCKETS) -> _Family:
        if type not in _VALID_TYPES:
            raise ValueError(f"unknown metric type {type!r}")
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                fam = self._families[name] = _Family(self, name, help, type,
                                                     buckets)
            elif fam.type != type:
                raise ValueError(
                    f"metric {name!r} already registered as {fam.type}, "
                    f"not {type}")
            return fam

    def counter(self, name: str, help: str = "") -> _Family:
        return self._family(name, help, "counter")

    def gauge(self, name: str, help: str = "") -> _Family:
        return self._family(name, help, "gauge")

    def histogram(self, name: str, help: str = "",
                  buckets: Sequence[float] = DEFAULT_BUCKETS) -> _Family:
        return self._family(name, help, "histogram", buckets)

    # -------------------------------------------------------------- control
    def set_enabled(self, flag: bool) -> None:
        """Kill switch: False turns every inc/set/observe into a no-op
        (the overhead-A/B lever; exposition of recorded data still works)."""
        self._enabled = bool(flag)

    @property
    def enabled(self) -> bool:
        return self._enabled

    def clear(self) -> None:
        """Drop all recorded series (keeps family definitions). Test hook."""
        with self._lock:
            for fam in self._families.values():
                fam._series.clear()
                fam._overflow = None

    # ----------------------------------------------------------- exposition
    @staticmethod
    def _fmt_labels(labels: Tuple[Tuple[str, str], ...],
                    extra: Optional[Tuple[Tuple[str, str], ...]] = None) -> str:
        pairs = list(labels) + list(extra or ())
        if not pairs:
            return ""
        def esc(v: str) -> str:
            return v.replace("\\", "\\\\").replace('"', '\\"').replace(
                "\n", "\\n")
        return "{" + ",".join(f'{k}="{esc(v)}"' for k, v in pairs) + "}"

    @staticmethod
    def _fmt_value(v: float) -> str:
        if math.isinf(v):
            return "+Inf" if v > 0 else "-Inf"
        return repr(float(v))

    def prometheus_text(self) -> str:
        """Prometheus text exposition format (the ``/metrics`` payload):
        ``# HELP`` / ``# TYPE`` headers, histogram ``_bucket``/``_sum``/
        ``_count`` expansion with cumulative ``le`` labels.

        Rendering goes through :func:`render_prometheus` over ``snapshot()``
        — the same path the fleet federation uses to render merged remote
        snapshots — so local and federated exposition can never drift."""
        return render_prometheus(self.snapshot())

    def snapshot(self) -> dict:
        """JSON-ready dump of every series (the ``/train/telemetry/data``
        payload and the ``--telemetry-out`` record body)."""
        out: Dict[str, dict] = {}
        with self._lock:
            for name, fam in sorted(self._families.items()):
                series = []
                for key in sorted(fam._series):
                    s = fam._series[key]
                    row: dict = {"labels": dict(key)}
                    if fam.type == "histogram":
                        row.update(sum=s.sum, count=s.count,
                                   buckets=list(fam.buckets),
                                   bucket_counts=list(s.bucket_counts))
                    else:
                        row["value"] = s.value
                    series.append(row)
                if series:
                    out[name] = {"type": fam.type, "help": fam.help,
                                 "series": series}
        return out

    def write_jsonl(self, path: str, **meta) -> None:
        """Append ONE JSON line (`{"ts": ..., "metrics": {...}, **meta}`) to
        ``path`` — the snapshot export format bench.py/cli.py dump beside
        their headline JSON. Appending (not truncating) keeps one file per
        run valid across retries."""
        rec = {"ts": time.time(), **meta, "metrics": self.snapshot()}
        with open(path, "a") as f:
            f.write(json.dumps(rec) + "\n")


def render_prometheus(snapshot: dict,
                      extra_labels: Optional[Dict[str, str]] = None) -> str:
    """Render a ``MetricsRegistry.snapshot()``-shaped dict as Prometheus
    text exposition. ``extra_labels`` (e.g. ``{"worker": ..., "role": ...}``)
    are appended to every series — how the federation tags each member's
    series in the fleet view. Works on any snapshot dict, local or one that
    crossed the wire as JSON."""
    extra = tuple(sorted((k, str(v)) for k, v in (extra_labels or {}).items()))
    fmt_labels = MetricsRegistry._fmt_labels
    fmt_value = MetricsRegistry._fmt_value
    lines: List[str] = []
    for name in sorted(snapshot):
        fam = snapshot[name]
        series = fam.get("series") or []
        if not series:
            continue
        if fam.get("help"):
            lines.append(f"# HELP {name} {fam['help']}")
        lines.append(f"# TYPE {name} {fam['type']}")
        for row in sorted(series,
                          key=lambda r: sorted(r["labels"].items())):
            key = tuple(sorted(
                (k, str(v)) for k, v in row["labels"].items())) + extra
            if fam["type"] == "histogram":
                cum = 0
                counts = row["bucket_counts"]
                for i, le in enumerate(row["buckets"]):
                    cum += counts[i]
                    lbl = fmt_labels(key, (("le", f"{le:g}"),))
                    lines.append(f"{name}_bucket{lbl} {cum}")
                cum += counts[-1]
                lbl = fmt_labels(key, (("le", "+Inf"),))
                lines.append(f"{name}_bucket{lbl} {cum}")
                lbl = fmt_labels(key)
                lines.append(f"{name}_sum{lbl} {fmt_value(row['sum'])}")
                lines.append(f"{name}_count{lbl} {row['count']}")
            else:
                lbl = fmt_labels(key)
                lines.append(f"{name}{lbl} {fmt_value(row['value'])}")
    return "\n".join(lines) + ("\n" if lines else "")


_GLOBAL = MetricsRegistry()


def global_registry() -> MetricsRegistry:
    """THE process-global registry every framework instrument writes to."""
    return _GLOBAL


def tree_nbytes(tree) -> int:
    """Total bytes of the array leaves of a pytree — works on concrete
    arrays AND tracers (both carry shape/dtype), so collective-traffic
    accounting can size a transfer at trace time or dispatch time."""
    import numpy as _np
    try:
        import jax
        leaves = jax.tree_util.tree_leaves(tree)
    except Exception:  # pragma: no cover - no jax (pure-host tooling)
        leaves = tree if isinstance(tree, (list, tuple)) else [tree]
    total = 0
    for leaf in leaves:
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is None or dtype is None:
            continue
        total += int(_np.prod(shape, dtype=_np.int64)) * \
            _np.dtype(dtype).itemsize
    return total
