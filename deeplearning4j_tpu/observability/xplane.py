"""Stdlib-only XPlane (``.xplane.pb``) parser -> per-op attribution summary.

``jax.profiler.start_trace`` writes its artifact as an XSpace protobuf
(``plugins/profile/<ts>/<host>.xplane.pb``), but the jax build on this image
ships no reader for it (``jax.profiler.ProfileData`` does not exist in
0.4.37) and TensorBoard is not installed. The trace is useless to the
framework unless we can read it ourselves — so this module walks the
protobuf wire format directly: varints and length-delimited submessages,
nothing else, no generated bindings, no third-party deps.

Only the fields attribution needs are decoded (verified against traces from
this jax build; the numbers are the upstream tsl/profiler field ids):

    XSpace      { repeated XPlane planes = 1; }
    XPlane      { string name = 2; repeated XLine lines = 3;
                  map<int64, XEventMetadata> event_metadata = 4; }
    XEventMetadata { int64 id = 1; string name = 2; }
    XLine       { string name = 2; repeated XEvent events = 4;
                  string display_name = 11; }
    XEvent      { int64 metadata_id = 1; int64 duration_ps = 3; }

Unknown fields are skipped (forward-compatible); *structural* damage — a
truncated varint, a length running past the buffer — raises
:class:`XPlaneParseError`, which :func:`summarize` converts into an
``{"error": ...}`` record so a half-written trace can never crash a fit
loop or a bench row.

The bucketing rules are lifted from scripts/profile_flagship.py (which now
delegates here): classify by the defining HLO opcode, never by substring
search over the whole HLO string — operand text routinely contains
``transpose``/``reshape``, which round 4's parser misread as ~38%
"datamovement" on every model.
"""
from __future__ import annotations

import glob
import os
import re
import struct
from typing import Dict, Iterator, List, Optional, Tuple


class XPlaneParseError(ValueError):
    """Structurally invalid protobuf wire data (truncated / malformed)."""


# protobuf wire types
_VARINT, _I64, _LEN, _I32 = 0, 1, 2, 5


def _read_varint(buf: bytes, i: int) -> Tuple[int, int]:
    result = shift = 0
    n = len(buf)
    while True:
        if i >= n:
            raise XPlaneParseError("truncated varint")
        b = buf[i]
        result |= (b & 0x7F) << shift
        i += 1
        if not b & 0x80:
            return result, i
        shift += 7
        if shift > 63:
            raise XPlaneParseError("varint longer than 64 bits")


def _walk(buf: bytes) -> Iterator[Tuple[int, int, object]]:
    """Yield ``(field_number, wire_type, value)`` over one message's bytes.

    Values are ints for varints, raw bytes for everything else; nested
    messages are the caller's job (feed the bytes back through _walk).
    """
    i, n = 0, len(buf)
    while i < n:
        tag, i = _read_varint(buf, i)
        field, wt = tag >> 3, tag & 7
        if field == 0:
            raise XPlaneParseError("field number 0")
        if wt == _VARINT:
            v, i = _read_varint(buf, i)
        elif wt == _I64:
            if i + 8 > n:
                raise XPlaneParseError("truncated 64-bit field")
            v, i = buf[i:i + 8], i + 8
        elif wt == _LEN:
            ln, i = _read_varint(buf, i)
            if i + ln > n:
                raise XPlaneParseError(
                    "length-delimited field overruns buffer")
            v, i = buf[i:i + ln], i + ln
        elif wt == _I32:
            if i + 4 > n:
                raise XPlaneParseError("truncated 32-bit field")
            v, i = buf[i:i + 4], i + 4
        else:
            raise XPlaneParseError(f"unsupported wire type {wt}")
        yield field, wt, v


def _utf8(v: object) -> str:
    return v.decode("utf-8", "replace") if isinstance(v, bytes) else str(v)


def _parse_event_metadata(buf: bytes) -> Tuple[Optional[int], str]:
    """One event_metadata map entry: key=1 (id), value=2 (XEventMetadata)."""
    eid: Optional[int] = None
    name = ""
    for field, wt, v in _walk(buf):
        if field == 1 and wt == _VARINT:
            eid = v
        elif field == 2 and wt == _LEN:
            for f2, w2, v2 in _walk(v):
                if f2 == 1 and w2 == _VARINT:
                    eid = v2  # XEventMetadata.id is authoritative
                elif f2 == 2 and w2 == _LEN:
                    name = _utf8(v2)
    return eid, name


def _parse_event(buf: bytes) -> Tuple[int, int]:
    """(metadata_id, duration_ps) of one XEvent."""
    mid = dur_ps = 0
    for field, wt, v in _walk(buf):
        if field == 1 and wt == _VARINT:
            mid = v
        elif field == 3 and wt == _VARINT:
            dur_ps = v
    return mid, dur_ps


def _parse_line(buf: bytes, names: Dict[int, str]) -> dict:
    name = display = ""
    events: List[Tuple[str, int]] = []
    for field, wt, v in _walk(buf):
        if field == 2 and wt == _LEN:
            name = _utf8(v)
        elif field == 11 and wt == _LEN:
            display = _utf8(v)
        elif field == 4 and wt == _LEN:
            mid, dur_ps = _parse_event(v)
            events.append((names.get(mid, f"<metadata {mid}>"), dur_ps))
    return {"name": name, "display_name": display, "events": events}


def _parse_plane(buf: bytes) -> dict:
    name = ""
    line_bufs: List[bytes] = []
    event_names: Dict[int, str] = {}
    for field, wt, v in _walk(buf):
        if field == 2 and wt == _LEN:
            name = _utf8(v)
        elif field == 3 and wt == _LEN:
            line_bufs.append(v)  # defer: event_metadata may come after lines
        elif field == 4 and wt == _LEN:
            eid, enm = _parse_event_metadata(v)
            if eid is not None:
                event_names[eid] = enm
    return {"name": name,
            "lines": [_parse_line(b, event_names) for b in line_bufs]}


def parse_planes(data: bytes) -> List[dict]:
    """Decode an XSpace buffer into plane dicts (name, lines->events).

    Raises :class:`XPlaneParseError` on structural damage; use
    :func:`summarize` for the never-raises entry point.
    """
    return [_parse_plane(v) for field, wt, v in _walk(data)
            if field == 1 and wt == _LEN]


# --------------------------------------------------------------- attribution
def opcode(nm: str) -> str:
    """The defining HLO opcode of ``%name = type opcode(args)``. Bucketing
    must use THIS, not substring search over the whole HLO string (see the
    module docstring for the round-4 misattribution that rule fixed)."""
    m = re.search(r"=\s*(?:\([^=]*?\)\s*|\S+\s+)?([a-z][a-z0-9\-_.]*)\(", nm)
    return m.group(1) if m else nm.split(".")[0].lstrip("%")


def bucket(nm: str) -> str:
    """Category of one op event: matmul / conv / collective / datamovement /
    reduce-vs-compute fusion, else the opcode itself (long tail)."""
    op = opcode(nm)
    # fusions: classify by the name prefix XLA gives them (it encodes the
    # fused ops: transpose_..., convert_reduce_..., maximum_add_...)
    label = nm.lstrip("%").split(" ")[0].split(".")[0].lower()
    if "conv" in op or label.startswith("convolution"):
        return "conv"
    if op in ("dot", "custom-call") or "matmul" in label:
        return "matmul/custom"
    if any(t in op for t in ("all-reduce", "all-gather", "collective",
                             "reduce-scatter", "permute")):
        return "collective"
    if op in ("copy", "transpose", "reshape", "bitcast",
              "dynamic-slice", "dynamic-update-slice") \
            or label.startswith(("copy", "transpose", "bitcast")):
        return "datamovement"
    if op == "fusion":
        # TPU traces do not expose fusion bodies; the big kOutput fusions
        # CONTAIN the convolutions/matmuls plus their elementwise epilogues,
        # so this bucket is "compute", not "elementwise overhead"
        if label.startswith(("convert_reduce", "multiply_reduce", "reduce")):
            return "fusion:reduce"
        return "fusion:compute"
    return op


#: control-flow wrappers (the K-step scan loop) span their whole body and
#: would double-count every inner op
_CONTROL_FLOW = ("while", "conditional", "call")

#: the profiler's own bookkeeping shows up as giant host events (e.g.
#: ``$profiler.py:91 start_trace`` spans the whole capture) — pure noise
_BOOKKEEPING = ("start_trace", "stop_trace")

_PJIT_RE = re.compile(r"PjitFunction\((.*)\)")


def _is_host_python_line(line: dict) -> bool:
    nm = (line.get("display_name") or line.get("name") or "").strip().lower()
    return nm == "python"


def find_trace(logdir: str) -> Optional[str]:
    """Newest ``*.xplane.pb`` under a trace directory (or the file itself)."""
    if os.path.isfile(logdir):
        return logdir
    paths = sorted(glob.glob(os.path.join(logdir, "**", "*.xplane.pb"),
                             recursive=True))
    return paths[-1] if paths else None


def summarize(logdir: str, top: int = 25) -> dict:
    """Attribution summary of the newest trace under ``logdir`` — top
    self-time ops, category split (sums to ~100%% of counted time), per-fn
    share from the host pjit spans. Never raises: every failure mode comes
    back as ``{"error": ...}`` so callers inside fit loops / bench rows can
    attach the record verbatim.
    """
    try:
        path = find_trace(logdir)
        if path is None:
            return {"error": f"no xplane.pb under {logdir}"}
        with open(path, "rb") as f:
            data = f.read()
        planes = parse_planes(data)
    except (OSError, XPlaneParseError) as e:
        return {"error": f"unreadable xplane trace: {e!r}", "trace": logdir}

    out: dict = {"trace": path, "planes": [p["name"] for p in planes]}
    # device planes only ("/device:TPU:0" etc.); fall back to host planes so
    # the pipeline still summarizes something on CPU-only runs
    device = [p for p in planes
              if any(t in p["name"].lower() for t in ("tpu", "gpu", "device"))]
    summarized = device or planes
    out["summarized_planes"] = [p["name"] for p in summarized]

    op_time: Dict[str, int] = {}
    cat_time: Dict[str, int] = {}
    total_ps = 0
    for plane in summarized:
        lines = plane["lines"]
        # device planes carry container lines ("XLA Modules", "Steps",
        # "Framework Name Scope") spanning the same wall time as the per-op
        # line — summing every line double-counts. Keep exactly the XLA
        # per-op line when present.
        op_lines = [l for l in lines
                    if (l["name"] or "").strip().lower() in ("xla ops", "ops")]
        for line in (op_lines or lines):
            host_line = _is_host_python_line(line)
            for nm, dur_ps in line["events"]:
                if any(b in nm for b in _BOOKKEEPING) or nm.startswith("$"):
                    continue
                if not host_line and opcode(nm) in _CONTROL_FLOW:
                    continue
                cat = "host" if host_line else bucket(nm)
                op_time[nm] = op_time.get(nm, 0) + dur_ps
                cat_time[cat] = cat_time.get(cat, 0) + dur_ps
                total_ps += dur_ps

    out["total_device_ns"] = total_ps // 1000
    ranked = sorted(op_time.items(), key=lambda kv: -kv[1])[:top]
    out["top_ops"] = [
        {"op": k, "ns": v // 1000,
         "pct": round(100.0 * v / total_ps, 2) if total_ps else 0.0}
        for k, v in ranked]

    ranked_cats = sorted(cat_time.items(), key=lambda kv: -kv[1])
    head, tail = ranked_cats[:11], ranked_cats[11:]
    if tail:  # roll the long tail up so the split still sums to ~100%
        head.append((f"other({len(tail)} buckets)", sum(v for _, v in tail)))
    out["categories_pct"] = {
        k: round(100.0 * v / total_ps, 2) if total_ps else 0.0
        for k, v in head}

    # per-fn share: the host "python" line's PjitFunction(...) spans say
    # which jitted program owned the window, whichever planes held the ops
    fn_time: Dict[str, int] = {}
    for plane in planes:
        for line in plane["lines"]:
            if not _is_host_python_line(line):
                continue
            for nm, dur_ps in line["events"]:
                m = _PJIT_RE.search(nm)
                if m:
                    fn_time[m.group(1)] = fn_time.get(m.group(1), 0) + dur_ps
    fn_total = sum(fn_time.values())
    if fn_total:
        out["fn_pct"] = {
            k: round(100.0 * v / fn_total, 2)
            for k, v in sorted(fn_time.items(), key=lambda kv: -kv[1])[:top]}
    return out


# ------------------------------------------------------------------ encoding
# Minimal writers, used by tests/golden/make_xplane_golden.py to build the
# committed fixture with the same field layout the parser reads. Living here
# keeps encoder and parser in one reviewable file.
def encode_varint(v: int) -> bytes:
    out = bytearray()
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def encode_field(field: int, wt: int, payload) -> bytes:
    tag = encode_varint((field << 3) | wt)
    if wt == _VARINT:
        return tag + encode_varint(payload)
    if wt == _LEN:
        return tag + encode_varint(len(payload)) + payload
    if wt == _I64:
        return tag + struct.pack("<q", payload)
    if wt == _I32:
        return tag + struct.pack("<i", payload)
    raise ValueError(f"wire type {wt}")


def encode_message(*fields: bytes) -> bytes:
    return b"".join(fields)
