"""Flight recorder: a bounded in-memory event log with crash-time egress.

The metrics registry answers "what is the current value of X"; it cannot
answer "what was the training loop doing in the seconds before it died".
Round 5's relay outage made the gap concrete: the TPU link dropped mid-run
and the only record was an out-of-band watcher script's log — the framework
itself had nothing to say. The flight recorder is that memory: every fit
path appends cheap, structured step events (step index, dispatch wall time,
batch size, K-group size) to a process-global ring buffer, the compile
tracker appends compile events, and the health monitor / watchdog append
alarms. When something goes wrong — an exception escapes a fit loop, a
health alarm fires, the watchdog detects a stall, or an operator sends
SIGUSR1 — ``dump()`` writes a self-contained diagnostic bundle.

Design constraints:

* **Hot-path cost.** ``record()`` is one dict build + a locked deque append
  — no registry traffic, no device syncs, no I/O. Call sites record once
  per *dispatch* (per K-step group), not per iteration. Events must carry
  host values only (ints/floats/strings); recording a device array would
  make ``dump()`` block on the device, which is exactly what a hang dump
  must never do.
* **Dump never touches the device.** The bundle is assembled entirely from
  host state: the ring buffer, the registry snapshot, cached compile/cost
  data, and ``sys._current_frames()``. Device info is included only when
  the JAX backend was already initialized by the process — ``dump()`` never
  initializes (or waits on) a backend, so it is safe to call from a signal
  handler while the device is wedged.
* **Kill switch.** ``set_enabled(False)`` turns ``record()`` into a no-op,
  mirroring the registry's switch; ``dump()`` still works on whatever was
  recorded.
"""
from __future__ import annotations

import functools
import json
import logging
import os
import re
import signal
import sys
import threading
import time
import traceback
from collections import deque
from typing import Any, Dict, List, Optional

from .metrics import global_registry
from .names import FLIGHT_DUMPS_TOTAL

log = logging.getLogger(__name__)

#: default ring capacity — at one event per K-step dispatch this is hours of
#: training history for a few hundred KB of host memory
DEFAULT_CAPACITY = 4096

#: environment variable configuring the default dump directory (the same
#: knob --flight-recorder-dir sets on bench.py / cli.py)
DUMP_DIR_ENV = "DL4J_FLIGHT_RECORDER_DIR"

#: environment variables worth snapshotting into the bundle (prefix match)
_ENV_PREFIXES = ("JAX_", "XLA_", "DL4J_", "PALLAS_", "BENCH_", "TPU_",
                 "LIBTPU_")


def _slug(text: str, max_len: int = 48) -> str:
    return re.sub(r"[^A-Za-z0-9_.-]+", "-", str(text)).strip("-")[:max_len] \
        or "dump"


def thread_stacks() -> str:
    """Per-thread Python stack dump from ``sys._current_frames()`` — the
    'where is everyone stuck' section of the bundle, also logged verbatim by
    the watchdog when a stall fires."""
    names = {t.ident: t.name for t in threading.enumerate()}
    lines: List[str] = []
    for ident, frame in sorted(sys._current_frames().items()):
        lines.append(f"--- thread {names.get(ident, '<unknown>')} "
                     f"(ident {ident}) ---")
        lines.extend(s.rstrip("\n") for s in traceback.format_stack(frame))
        lines.append("")
    return "\n".join(lines) + "\n"


def _backend_initialized() -> bool:
    """True only if a JAX backend ALREADY exists in this process. Never
    initializes one — a dump from a process whose device link is dead (the
    bench parent after an outage) must not block dialing the backend."""
    mods = sys.modules
    if "jax" not in mods:
        return False
    try:
        xb = mods.get("jax._src.xla_bridge")
        backends = getattr(xb, "_backends", None)
        return bool(backends)
    except Exception:  # pragma: no cover - private API moved  # lint: swallowed-exception-ok (environment capture degrades to host-only info)
        return False


def collect_environment() -> dict:
    """Host + (when safely available) device environment for the bundle."""
    info: Dict[str, Any] = {
        "time": time.time(),
        "pid": os.getpid(),
        "argv": list(sys.argv),
        "cwd": os.getcwd(),
        "python": sys.version,
        "env": {k: v for k, v in sorted(os.environ.items())
                if k.startswith(_ENV_PREFIXES)},
    }
    try:
        import platform

        info["platform"] = platform.platform()
    except Exception:  # pragma: no cover  # lint: swallowed-exception-ok (platform string is best-effort decoration)
        pass
    if "jax" in sys.modules:
        try:
            import jax

            info["jax_version"] = jax.__version__
        except Exception:  # pragma: no cover  # lint: swallowed-exception-ok (version capture is best-effort)
            pass
    if _backend_initialized():
        try:
            import jax

            devs = jax.devices()
            info["backend"] = devs[0].platform if devs else None
            info["device_count"] = len(devs)
            info["local_device_count"] = jax.local_device_count()
            info["devices"] = [
                {"id": d.id, "platform": d.platform,
                 "process_index": d.process_index,
                 "kind": getattr(d, "device_kind", "")} for d in devs]
        except Exception as e:  # backend present but unhealthy — say so
            info["devices_error"] = repr(e)
        try:
            from deeplearning4j_tpu import common

            info["dtype_policy"] = repr(common.policy_key())
        except Exception:  # pragma: no cover  # lint: swallowed-exception-ok (policy key is best-effort decoration)
            pass
    return info


def _jsonable(obj):
    try:
        json.dumps(obj)
        return obj
    except (TypeError, ValueError):
        return repr(obj)


class FlightRecorder:
    """Process-global, thread-safe ring buffer of structured events with a
    ``dump()`` that writes a self-contained diagnostic bundle.

    One global instance (``global_recorder()``) is shared by the fit loops,
    compile tracker, health monitor, and watchdog; tests construct private
    ones with small capacities.
    """

    def __init__(self, capacity: int = DEFAULT_CAPACITY,
                 dump_dir: Optional[str] = None, registry=None):
        self._lock = threading.Lock()
        self._events: deque = deque(maxlen=max(1, int(capacity)))
        self._enabled = True
        self._dropped = 0
        self._dump_seq = 0
        self._registry = registry
        self.dump_dir = dump_dir if dump_dir is not None \
            else os.environ.get(DUMP_DIR_ENV) or None

    # -------------------------------------------------------------- control
    @property
    def capacity(self) -> int:
        return self._events.maxlen

    def set_enabled(self, flag: bool) -> None:
        """Kill switch: False turns every ``record()`` into a no-op
        (mirrors MetricsRegistry.set_enabled; dump still works)."""
        self._enabled = bool(flag)

    @property
    def enabled(self) -> bool:
        return self._enabled

    def set_dump_dir(self, path: Optional[str]) -> None:
        """Configure where unhandled-exception / alarm / signal dumps land.
        None disables automatic dumps (explicit ``dump(dir=...)`` still
        works)."""
        self.dump_dir = path

    def clear(self) -> None:
        with self._lock:
            self._events.clear()
            self._dropped = 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._events)

    # ------------------------------------------------------------ recording
    def record(self, kind: str, **fields) -> None:
        """Append one structured event. Host values only (ints, floats,
        strings) — never device arrays; see the module docstring."""
        if not self._enabled:
            return
        event = {"kind": kind, "ts": time.time(), **fields}
        with self._lock:
            if len(self._events) == self._events.maxlen:
                self._dropped += 1
            self._events.append(event)

    def snapshot(self) -> List[dict]:
        with self._lock:
            return list(self._events)

    @property
    def dropped(self) -> int:
        """Events evicted by the ring bound since the last clear()."""
        return self._dropped

    # ---------------------------------------------------------------- dump
    def _registry_or_global(self):
        return self._registry if self._registry is not None \
            else global_registry()

    def dump(self, dir: Optional[str] = None, reason: str = "manual",
             extra: Optional[dict] = None) -> Optional[str]:
        """Write a diagnostic bundle; returns its path, or None when no
        directory is configured (automatic dump sites are then free no-ops).

        Bundle contents (every section is always written, so consumers can
        rely on the file set): ``manifest.json``, ``events.jsonl``,
        ``metrics.json``, ``environment.json``, ``threads.txt``,
        ``cost_analysis.json``, and ``extra.json`` when ``extra`` is given.
        """
        base = dir or self.dump_dir
        if base is None:
            return None
        with self._lock:
            self._dump_seq += 1
            seq = self._dump_seq
            events = list(self._events)
            dropped = self._dropped
        stamp = time.strftime("%Y%m%d-%H%M%S")
        name = f"flight-{stamp}-p{os.getpid()}-{seq:03d}-{_slug(reason)}"
        path = os.path.join(base, name)
        try:
            os.makedirs(path, exist_ok=True)
            files = []

            def write_json(fname, obj):
                with open(os.path.join(path, fname), "w") as f:
                    json.dump(obj, f, indent=2, default=repr)
                    f.write("\n")
                files.append(fname)

            with open(os.path.join(path, "events.jsonl"), "w") as f:
                for ev in events:
                    f.write(json.dumps(
                        {k: _jsonable(v) for k, v in ev.items()}) + "\n")
            files.append("events.jsonl")
            write_json("metrics.json", self._registry_or_global().snapshot())
            write_json("environment.json", collect_environment())
            with open(os.path.join(path, "threads.txt"), "w") as f:
                f.write(thread_stacks())
            files.append("threads.txt")
            write_json("cost_analysis.json", self._cost_analysis_section())
            if extra is not None:
                write_json("extra.json",
                           {k: _jsonable(v) for k, v in extra.items()})
            write_json("manifest.json", {
                "reason": reason, "ts": time.time(), "pid": os.getpid(),
                "events": len(events), "events_dropped": dropped,
                "capacity": self.capacity, "files": files + ["manifest.json"],
            })
        except OSError as e:
            log.error("flight recorder could not write bundle %s: %r",
                      path, e)
            return None
        self._registry_or_global().counter(
            FLIGHT_DUMPS_TOTAL,
            "flight-recorder diagnostic bundles written").labels(
                reason=_slug(reason)).inc()
        log.warning("flight recorder: wrote diagnostic bundle %s (%s)",
                    path, reason)
        return path

    @staticmethod
    def _cost_analysis_section() -> dict:
        """Cached compile/cost data only — computing a fresh cost analysis
        would compile, and a dump taken during a hang must not."""
        try:
            from .compile_tracker import global_tracker

            t = global_tracker()
            return {"step": t.step,
                    "compile_events": t.snapshot_events(),
                    "cost_analyses": t.snapshot_cost_analyses()}
        except Exception as e:  # tracker import/shape drift must not kill a crash dump
            return {"error": repr(e)}

    def list_bundles(self, dir: Optional[str] = None) -> List[dict]:
        """Manifests of the bundles under the dump directory, newest first
        (the UI server's ``/train/health/bundles`` payload)."""
        base = dir or self.dump_dir
        out: List[dict] = []
        if not base or not os.path.isdir(base):
            return out
        for entry in sorted(os.listdir(base), reverse=True):
            manifest = os.path.join(base, entry, "manifest.json")
            if not os.path.isfile(manifest):
                continue
            try:
                with open(manifest) as f:
                    m = json.load(f)
            except (OSError, ValueError):
                m = {"error": "unreadable manifest"}
            m["path"] = os.path.join(base, entry)
            out.append(m)
        return out


_GLOBAL = FlightRecorder()


def global_recorder() -> FlightRecorder:
    """THE process-global recorder the fit loops and alarm paths write to."""
    return _GLOBAL


# ------------------------------------------------------- exception egress
def dump_on_unhandled(site: str):
    """Decorator for the fit entry points: an exception escaping the wrapped
    call records an event and (when a dump dir is configured) writes one
    bundle, then re-raises. Nested decorated frames (fit -> fit_iterator)
    dump once — the exception object is marked after the first bundle."""

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            try:
                return fn(*args, **kwargs)
            except Exception as e:
                _note_unhandled(site, e)
                raise
        return wrapper

    return deco


def _note_unhandled(site: str, e: BaseException) -> None:
    rec = global_recorder()
    rec.record("exception", site=site, error=repr(e)[:500])
    if getattr(e, "_dl4j_recorder_dumped", False):
        return
    try:
        if rec.dump(reason=f"exception-{site}") is not None:
            e._dl4j_recorder_dumped = True
    except Exception:
        # the dump must never mask the training error being propagated
        log.exception("flight recorder dump failed while handling an "
                      "exception from %s", site)


# --------------------------------------------------------- signal egress
def install_signal_handlers(recorder: Optional[FlightRecorder] = None,
                            signals: Optional[tuple] = None) -> dict:
    """Opt-in SIGTERM/SIGUSR1 dump hooks (main thread only — CPython signal
    rule). SIGUSR1 is a live diagnostic poke: dump and keep running. SIGTERM
    dumps, then chains to the previous handler (or re-raises the default
    termination) so orchestrator kills still terminate the process. Returns
    the {signum: previous_handler} map for ``uninstall_signal_handlers``."""
    # explicit None check: an EMPTY recorder is falsy (__len__ == 0)
    rec = recorder if recorder is not None else global_recorder()
    sigs = signals or (signal.SIGTERM, signal.SIGUSR1)
    previous: dict = {}

    def handler(signum, frame):
        try:
            sig_name = signal.Signals(signum).name
        except ValueError:
            sig_name = str(signum)
        rec.record("signal", signum=signum, name=sig_name)
        try:
            rec.dump(reason=f"signal-{sig_name}")
        except Exception:
            log.exception("flight recorder dump failed in %s handler",
                          sig_name)
        prev = previous.get(signum)
        if callable(prev):
            prev(signum, frame)
        elif prev == signal.SIG_DFL and signum != signal.SIGUSR1:
            # restore the default disposition and re-deliver so SIGTERM
            # still terminates after the dump
            signal.signal(signum, signal.SIG_DFL)
            os.kill(os.getpid(), signum)

    for s in sigs:
        previous[s] = signal.signal(s, handler)
    return previous


def uninstall_signal_handlers(previous: dict) -> None:
    for signum, prev in previous.items():
        signal.signal(signum, prev)
