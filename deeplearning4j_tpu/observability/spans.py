"""Span API: one ``with span("epoch/3/fwd")`` feeds BOTH trace viewers.

``ProfilerListener`` already captures XPlane windows, but user-defined
phases only show up there if the code annotates them — and ad-hoc
``jax.profiler.TraceAnnotation`` calls leave no persistent record once the
trace window closes. A span does triple duty: the annotation makes the
phase visible in xprof/perfetto timelines, the registry histogram keeps an
always-on latency distribution a ``/metrics`` scraper can watch between
(or without) profiler windows, and enter/exit events go into the flight
recorder's ring so a crash bundle carries the recent span timeline (which
phase the run died inside, not just that it died).

Span names are hierarchical-by-convention (``"epoch/3/stage"``); the
registry series is labeled with the name verbatim, so high-cardinality
names (per-step indices) belong in the annotation half only — pass
``metric_name`` to collapse them for the histogram.

Since the request-tracing plane landed (observability/tracing.py), a
``span()`` additionally opens a REAL trace span under the thread's ambient
trace context: training phases called inside a traced request show up in
its ``/serve/traces/<id>`` tree, and outside any trace the span costs one
no-op context manager. No call site outside observability/ changed.
"""
from __future__ import annotations

import contextlib
import time
from typing import Optional

from .flight_recorder import global_recorder
from .metrics import global_registry
from .names import SPAN_SECONDS
from .tracing import NOOP_SPAN, current_span, trace_span


@contextlib.contextmanager
def span(name: str, metric_name: Optional[str] = None, registry=None,
         recorder=None):
    """Annotate a phase in XPlane traces AND record its wall time in the
    registry histogram ``dl4j_span_seconds{name=...}`` AND leave
    ``span_enter``/``span_exit`` events in the flight-recorder ring AND
    open a trace span under the ambient trace context (tracing.py).

    ``metric_name`` overrides the histogram label (use it to collapse
    per-index names like ``epoch/3`` into a bounded series like ``epoch``).
    """
    reg = registry if registry is not None else global_registry()
    # explicit None check: an EMPTY recorder is falsy (__len__ == 0)
    rec = recorder if recorder is not None else global_recorder()
    hist = reg.histogram(SPAN_SECONDS,
                         "wall seconds of user/framework span() phases")
    series = hist.labels(name=metric_name or name)
    try:
        import jax.profiler as _prof
        ann = _prof.TraceAnnotation(name)
    except Exception:  # pragma: no cover - profiler API absent
        ann = contextlib.nullcontext()
    rec.record("span_enter", name=name)
    # a real trace span only under an ambient trace — a bare training
    # phase must not mint root traces into the ring
    tspan = trace_span(metric_name or name) if current_span() is not None \
        else NOOP_SPAN
    t0 = time.perf_counter()
    with ann, tspan:
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            series.observe(dt)
            rec.record("span_exit", name=name, dur_s=dt)
