"""Full-batch optimizers: LBFGS, nonlinear CG, line gradient descent.

Reference: optimize/Solver.java:41 (facade, algo switch :55), solvers/
BaseOptimizer.java:173 (line-search loop), BackTrackLineSearch.java:159,
solvers/{LBFGS,ConjugateGradient,LineGradientDescent,StochasticGradientDescent}.java.

TPU-native redesign: each optimizer is ONE jit-compiled ``lax.while_loop`` /
``lax.scan`` over the flattened parameter vector — no per-iteration host round
trips. The SGD fast path stays in the networks' fused train step
(make_train_step); this module covers the full-batch algorithms.
"""
from __future__ import annotations

import functools
from typing import Callable, NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from deeplearning4j_tpu.utils.pytree import flatten_params, unflatten_params

Array = jax.Array


# --------------------------------------------------------------------- line search
def _backtrack(f: Callable[[Array], Array], x: Array, fx: Array, g: Array,
               d: Array, step0: Array, c1: float = 1e-4, rho: float = 0.5,
               max_steps: int = 20):
    """Armijo backtracking line search (reference BackTrackLineSearch.java:159).

    Returns (step, new_x, new_f). Falls back to step=0 (no move) if no
    sufficient-decrease step is found within max_steps halvings.
    """
    gd = jnp.vdot(g, d)

    def cond(carry):
        step, i, ok = carry[0], carry[3], carry[4]
        return jnp.logical_and(i < max_steps, jnp.logical_not(ok))

    def body(carry):
        step, bx, bf, i, ok = carry
        nx = x + step * d
        nf = f(nx)
        good = nf <= fx + c1 * step * gd
        return (jnp.where(good, step, step * rho),
                jnp.where(good, nx, bx),
                jnp.where(good, nf, bf),
                i + 1,
                good)

    step, nx, nf, _, ok = lax.while_loop(
        cond, body, (step0, x, fx, jnp.int32(0), jnp.bool_(False)))
    return jnp.where(ok, step, 0.0), jnp.where(ok, nx, x), jnp.where(ok, nf, fx)


class MinimizeResult(NamedTuple):
    x: Array
    loss: Array
    iterations: Array


# --------------------------------------------------------------------------- LBFGS
def minimize_lbfgs(f: Callable[[Array], Array], x0: Array, max_iters: int = 100,
                   history: int = 10, tol: float = 1e-6) -> MinimizeResult:
    """Limited-memory BFGS with fixed-size (jit-static) history ring buffers
    (reference solvers/LBFGS.java — reimagined as a single traced while_loop)."""
    n = x0.shape[0]
    vg = jax.value_and_grad(f)

    def two_loop(g, S, Y, rho, k):
        # standard two-loop recursion over min(k, m) stored pairs
        m = history

        def bwd(i, carry):
            q, alpha = carry
            idx = (k - 1 - i) % m
            valid = i < jnp.minimum(k, m)
            a = jnp.where(valid, rho[idx] * jnp.vdot(S[idx], q), 0.0)
            q = q - jnp.where(valid, a, 0.0) * Y[idx]
            return q, alpha.at[idx].set(a)

        q, alpha = lax.fori_loop(0, m, bwd, (g, jnp.zeros(m, g.dtype)))
        # initial Hessian scaling gamma = s·y / y·y of most recent pair
        last = (k - 1) % m
        ys = jnp.vdot(S[last], Y[last])
        yy = jnp.vdot(Y[last], Y[last])
        gamma = jnp.where(k > 0, ys / jnp.maximum(yy, 1e-20), 1.0)
        r = gamma * q

        def fwd(i, r):
            idx = (k - jnp.minimum(k, m) + i) % m
            valid = i < jnp.minimum(k, m)
            beta = jnp.where(valid, rho[idx] * jnp.vdot(Y[idx], r), 0.0)
            return r + jnp.where(valid, alpha[idx] - beta, 0.0) * S[idx]

        return lax.fori_loop(0, m, fwd, r)

    def cond(st):
        x, fx, g, S, Y, rho, k, done = st
        return jnp.logical_and(k < max_iters, jnp.logical_not(done))

    def body(st):
        x, fx, g, S, Y, rho, k, _ = st
        d = -two_loop(g, S, Y, rho, k)
        # fall back to steepest descent if d is not a descent direction
        descent = jnp.vdot(g, d) < 0
        d = jnp.where(descent, d, -g)
        step0 = jnp.where(k == 0, 1.0 / jnp.maximum(jnp.linalg.norm(g), 1.0), 1.0)
        step, nx, nf = _backtrack(f, x, fx, g, d, step0)
        _, ng = vg(nx)
        s = nx - x
        y = ng - g
        sy = jnp.vdot(s, y)
        slot = k % history
        good_pair = sy > 1e-10
        S = jnp.where(good_pair, S.at[slot].set(s), S)
        Y = jnp.where(good_pair, Y.at[slot].set(y), Y)
        rho = jnp.where(good_pair, rho.at[slot].set(1.0 / jnp.maximum(sy, 1e-20)), rho)
        done = jnp.logical_or(jnp.linalg.norm(ng) < tol, step == 0.0)
        return nx, nf, ng, S, Y, rho, k + 1, done

    f0, g0 = vg(x0)
    S = jnp.zeros((history, n), x0.dtype)
    Y = jnp.zeros((history, n), x0.dtype)
    rho = jnp.zeros((history,), x0.dtype)
    x, fx, g, _, _, _, k, _ = lax.while_loop(
        cond, body, (x0, f0, g0, S, Y, rho, jnp.int32(0), jnp.bool_(False)))
    return MinimizeResult(x, fx, k)


# ------------------------------------------------------------------------------ CG
def minimize_cg(f: Callable[[Array], Array], x0: Array, max_iters: int = 100,
                tol: float = 1e-6) -> MinimizeResult:
    """Polak-Ribière(+) nonlinear conjugate gradient with Armijo line search
    (reference solvers/ConjugateGradient.java)."""
    vg = jax.value_and_grad(f)

    def cond(st):
        x, fx, g, d, k, done = st
        return jnp.logical_and(k < max_iters, jnp.logical_not(done))

    def body(st):
        x, fx, g, d, k, _ = st
        step, nx, nf = _backtrack(f, x, fx, g, d, jnp.asarray(1.0, x.dtype))
        _, ng = vg(nx)
        beta = jnp.maximum(jnp.vdot(ng, ng - g)
                           / jnp.maximum(jnp.vdot(g, g), 1e-20), 0.0)
        nd = -ng + beta * d
        # restart with steepest descent when nd is not a descent direction
        nd = jnp.where(jnp.vdot(ng, nd) < 0, nd, -ng)
        done = jnp.logical_or(jnp.linalg.norm(ng) < tol, step == 0.0)
        return nx, nf, ng, nd, k + 1, done

    f0, g0 = vg(x0)
    x, fx, g, d, k, _ = lax.while_loop(
        cond, body, (x0, f0, g0, -g0, jnp.int32(0), jnp.bool_(False)))
    return MinimizeResult(x, fx, k)


# -------------------------------------------------------------------- line GD
def minimize_line_gd(f: Callable[[Array], Array], x0: Array, max_iters: int = 100,
                     tol: float = 1e-6) -> MinimizeResult:
    """Steepest descent with line search (reference solvers/LineGradientDescent.java)."""
    vg = jax.value_and_grad(f)

    def cond(st):
        x, fx, g, k, done = st
        return jnp.logical_and(k < max_iters, jnp.logical_not(done))

    def body(st):
        x, fx, g, k, _ = st
        step, nx, nf = _backtrack(f, x, fx, g, -g, jnp.asarray(1.0, x.dtype))
        _, ng = vg(nx)
        done = jnp.logical_or(jnp.linalg.norm(ng) < tol, step == 0.0)
        return nx, nf, ng, k + 1, done

    f0, g0 = vg(x0)
    x, fx, g, k, _ = lax.while_loop(
        cond, body, (x0, f0, g0, jnp.int32(0), jnp.bool_(False)))
    return MinimizeResult(x, fx, k)


_ALGOS = {
    "lbfgs": minimize_lbfgs,
    "conjugate_gradient": minimize_cg,
    "line_gradient_descent": minimize_line_gd,
}


class Solver:
    """Facade dispatching on ``optimization_algo`` (reference Solver.java:48-66).

    For the full-batch algorithms the model's loss on the given batch is exposed
    as a function of the flat parameter vector and minimized in one jitted call;
    the result is written back into the model's param pytree.
    """

    def __init__(self, model, max_iters: int = None):
        self.model = model
        g = model.conf.global_conf
        self.algo = g.optimization_algo
        self.max_iters = max_iters if max_iters is not None else max(1, g.iterations)
        self._jit_runs: dict = {}

    def optimize(self, x, y) -> float:
        from deeplearning4j_tpu.nn.graph_network import ComputationGraph, graph_loss
        from deeplearning4j_tpu.nn.multilayer import loss_fn

        net = self.model
        if self.algo == "stochastic_gradient_descent":
            net.fit(x, y)
            return net.score_value
        if self.algo not in _ALGOS:
            raise ValueError(f"Unknown optimization_algo: {self.algo}")

        template = net.params_list
        if isinstance(net, ComputationGraph):
            xs = [jnp.asarray(a) for a in (x if isinstance(x, list) else [x])]
            ys = [jnp.asarray(a) for a in (y if isinstance(y, list) else [y])]
        else:
            xa, ya = jnp.asarray(x), jnp.asarray(y)

        # cache the compiled minimizer per batch shape — the batch is a traced
        # argument, so repeated optimize() calls reuse the compiled loop
        shapes = tuple((tuple(a.shape), str(a.dtype)) for a in
                       jax.tree_util.tree_leaves((x, y)))
        run = self._jit_runs.get(shapes)
        if run is None:
            minimize = functools.partial(_ALGOS[self.algo], max_iters=self.max_iters)
            if isinstance(net, ComputationGraph):
                def run_impl(x0, xs, ys):
                    def fl(flat):
                        p = unflatten_params(template, flat)
                        loss, _ = graph_loss(net.conf, p, net.state_list, xs, ys, None)
                        return loss
                    return minimize(fl, x0)
            else:
                def run_impl(x0, xa, ya):
                    def fl(flat):
                        p = unflatten_params(template, flat)
                        loss, _ = loss_fn(net.conf, p, net.state_list, xa, ya, None)
                        return loss
                    return minimize(fl, x0)
            run = self._jit_runs[shapes] = jax.jit(run_impl)  # lint: adhoc-jit-ok (line-search inner loop over closure-captured f64 objective; no conf/policy identity for the seams to key on)
        if isinstance(net, ComputationGraph):
            result = run(flatten_params(template, jnp.float32), xs, ys)
        else:
            result = run(flatten_params(template, jnp.float32), xa, ya)
        net.params_list = unflatten_params(template, result.x)
        net.score_value = float(result.loss)
        net.iteration += int(result.iterations)
        for listener in net.listeners:
            listener.iteration_done(net, net.iteration)
        return net.score_value
