"""Training listeners.

Reference: optimize/api/IterationListener.java + TrainingListener.java and
optimize/listeners/*.java (ScoreIterationListener, PerformanceListener,
CollectScoresIterationListener). Listeners run on host between jitted steps — exactly
the reference's seam (StochasticGradientDescent.java:64 iterationDone), so the
training-UI / stats pipeline attaches here identically.
"""
from __future__ import annotations

import logging
import time
from typing import Optional

log = logging.getLogger(__name__)


class IterationListener:
    """Base listener (reference optimize/api/IterationListener.java)."""

    def iteration_done(self, model, iteration: int) -> None:
        pass

    def on_epoch_start(self, model) -> None:
        pass

    def on_epoch_end(self, model) -> None:
        pass


TrainingListener = IterationListener  # epoch hooks included above


class ScoreIterationListener(IterationListener):
    """Log score every N iterations (reference ScoreIterationListener.java).

    Emits through the logger ONCE per report (the old behaviour double-
    reported via log.info AND print). ``echo=True`` additionally mirrors to
    stdout for bare scripts with no logging configured.
    """

    def __init__(self, print_iterations: int = 10, echo: bool = False):
        self.print_iterations = max(1, print_iterations)
        self.echo = echo

    def iteration_done(self, model, iteration: int) -> None:
        if iteration % self.print_iterations == 0:
            log.info("Score at iteration %d is %s", iteration,
                     model.score_value)
            if self.echo:
                # lint: bare-print-ok (echo=True is an explicit user opt-in to console output)
                print(f"Score at iteration {iteration} is "
                      f"{model.score_value}")


class PerformanceListener(IterationListener):
    """Throughput reporting: samples/sec + batches/sec (reference
    PerformanceListener.java). Used by bench.py for the headline metric."""

    def __init__(self, frequency: int = 1, report: bool = True,
                 batch_size: int = 0):
        self.frequency = max(1, frequency)
        self.report = report
        self.last_time: Optional[float] = None
        self.last_iter = 0
        self.samples_per_sec = 0.0
        self.batches_per_sec = 0.0
        # 0 = infer per report from the model's last fitted batch (every fit
        # path sets model.last_batch_size); a nonzero value pins it. The old
        # behaviour — a 0 default that nothing populated — made
        # samples_per_sec always 0.0 unless the caller poked the attribute.
        self.batch_size = batch_size

    def iteration_done(self, model, iteration: int) -> None:
        now = time.perf_counter()
        if self.last_time is not None and iteration % self.frequency == 0:
            dt = now - self.last_time
            iters = iteration - self.last_iter
            if dt > 0 and iters > 0:
                bs = self.batch_size or getattr(model, "last_batch_size", 0)
                self.batches_per_sec = iters / dt
                self.samples_per_sec = self.batches_per_sec * bs
                if self.report:
                    log.info("iteration %d: %.1f batches/sec, "
                             "%.1f samples/sec", iteration,
                             self.batches_per_sec, self.samples_per_sec)
        if iteration % self.frequency == 0:
            self.last_time = now
            self.last_iter = iteration


class CollectScoresIterationListener(IterationListener):
    """Collect (iteration, score) pairs (reference CollectScoresIterationListener.java)."""

    def __init__(self, frequency: int = 1):
        self.frequency = max(1, frequency)
        self.scores: list[tuple[int, float]] = []

    def iteration_done(self, model, iteration: int) -> None:
        if iteration % self.frequency == 0:
            self.scores.append((iteration, model.score_value))


class TimeIterationListener(IterationListener):
    """Estimate remaining training time (reference TimeIterationListener.java)."""

    def __init__(self, total_iterations: int, frequency: int = 50):
        self.total_iterations = total_iterations
        # report cadence in iterations (was hardcoded at 50 — useless for
        # workloads shorter than 50 iterations)
        self.frequency = max(1, frequency)
        self.start = time.perf_counter()

    def iteration_done(self, model, iteration: int) -> None:
        elapsed = time.perf_counter() - self.start
        if iteration > 0 and iteration % self.frequency == 0:
            remaining = elapsed / iteration * (self.total_iterations - iteration)
            log.info("iteration %d/%d, ETA %.0fs", iteration,
                     self.total_iterations, remaining)


class ParamAndGradientIterationListener(IterationListener):
    """Per-iteration parameter/update statistics (reference
    ParamAndGradientIterationListener.java): mean magnitudes of parameters and
    of the last applied update per named variable, optionally written to file."""

    def __init__(self, iterations: int = 1, output_file: Optional[str] = None,
                 print_mean_magnitudes: bool = True):
        self.iterations = max(1, iterations)
        self.output_file = output_file
        self.print_mean_magnitudes = print_mean_magnitudes
        self._last: Optional[dict] = None
        self.rows: list = []

    @staticmethod
    def _flatten(params, prefix=""):
        import numpy as np
        out = {}
        items = (params.items() if isinstance(params, dict)
                 else enumerate(params))
        for k, v in items:
            name = f"{prefix}{k}"
            if isinstance(v, (dict, list, tuple)):
                out.update(ParamAndGradientIterationListener._flatten(
                    v, name + "_"))
            elif v is not None and hasattr(v, "shape"):
                out[name] = np.asarray(v)
        return out

    def iteration_done(self, model, iteration: int) -> None:
        import numpy as np
        flat = self._flatten(getattr(model, "params_list", {}) or {})
        log_now = iteration % self.iterations == 0
        if log_now:
            row = {"iteration": iteration, "score": float(model.score_value)}
            for name, arr in flat.items():
                row[f"param_{name}"] = float(np.mean(np.abs(arr)))
                if self._last is not None and name in self._last \
                        and self._last[name].shape == arr.shape:
                    row[f"update_{name}"] = float(np.mean(np.abs(
                        arr - self._last[name])))
        # refresh every call so update_ deltas always span exactly one step
        self._last = {k: v.copy() for k, v in flat.items()}
        if not log_now:
            return
        self.rows.append(row)
        if self.print_mean_magnitudes:
            log.info("iter %d param/update mean magnitudes: %s",
                     iteration, {k: round(v, 6) for k, v in row.items()
                                 if k.startswith(("param_", "update_"))})
        if self.output_file:
            import json
            with open(self.output_file, "a") as f:
                f.write(json.dumps(row) + "\n")


class ProfilerListener(IterationListener):
    """Capture an XLA/XPlane profiler trace over a window of iterations
    (SURVEY.md §5 tracing: the TPU-native analog of the reference's
    SparkTrainingStats timeline + PerformanceListener is a jax.profiler
    trace — kernel-level timing viewable in TensorBoard/Perfetto/xprof).

    Starts tracing when ``start_iteration`` completes and stops
    ``num_iterations`` later, writing to ``log_dir``. One-shot by default;
    set ``repeat_every`` to re-arm periodically (each window goes to a
    fresh subdirectory).

    Capture goes through the process-global
    :class:`~deeplearning4j_tpu.observability.profiler.TraceSession` — the
    profiler is a process singleton, and a listener window overlapping a
    bench/script/anomaly capture must log-and-skip, never raise from inside
    the fit loop. Completed windows land in ``self.windows`` with their
    attribution summaries in ``self.summaries``."""

    def __init__(self, log_dir: str, start_iteration: int = 10,
                 num_iterations: int = 5,
                 repeat_every: Optional[int] = None):
        self.log_dir = log_dir
        self.start_iteration = start_iteration
        self.num_iterations = max(1, num_iterations)
        self.repeat_every = repeat_every
        self.windows: list = []  # directories of completed traces
        self.summaries: list = []  # attribution dicts, parallel to windows
        self._active_since: Optional[int] = None

    @staticmethod
    def _session():
        from deeplearning4j_tpu.observability.profiler import \
            global_trace_session
        return global_trace_session()

    def _start(self, iteration: int) -> None:
        import os

        sub = (os.path.join(self.log_dir, f"iter_{iteration}")
               if self.repeat_every else self.log_dir)
        # None = the session is owned by another capture (or the profiler
        # refused): skip this window and retry on a later iteration — the
        # session already logged the collision
        if self._session().start("listener", logdir=sub) is None:
            return
        self._active_since = iteration
        self._dir = sub

    def _stop(self) -> None:
        self.summaries.append(self._session().stop())
        self.windows.append(self._dir)
        self._active_since = None
        if self.repeat_every:
            self.start_iteration += self.repeat_every

    def iteration_done(self, model, iteration: int) -> None:
        if self._active_since is None:
            if iteration >= self.start_iteration and \
                    (not self.windows or self.repeat_every):
                self._start(iteration)
        elif iteration - self._active_since >= self.num_iterations:
            # read the score first so the traced window includes the real
            # device work (lazy score would otherwise sync outside the trace)
            _ = model.score_value
            self._stop()

    def on_epoch_end(self, model) -> None:
        if self._active_since is not None:
            self._stop()


class CheckpointListener(IterationListener):
    """Periodic checkpointing for deterministic restart (SURVEY.md §5:
    reference ModelSerializer zips include updater state so training resumes
    bit-identically; early-stopping savers persist best/latest per epoch).
    Writes model zips every N iterations and/or every epoch end, keeping the
    last ``keep_last`` files plus `latest.zip`."""

    def __init__(self, directory: str, every_n_iterations: Optional[int] = None,
                 every_n_epochs: Optional[int] = 1, keep_last: int = 3,
                 sharded: bool = False):
        import glob
        import os
        self.directory = directory
        os.makedirs(directory, exist_ok=True)
        self.every_n_iterations = every_n_iterations
        self.every_n_epochs = every_n_epochs
        self.keep_last = keep_last
        #: sharded=True writes orbax sharded checkpoint DIRECTORIES
        #: (utils/sharded_checkpoint) instead of zip files — no host gather
        #: for mesh-distributed params; LATEST is a pointer file
        self.sharded = sharded
        # rotation must honor keep_last across restarts: seed from disk
        pattern = ("checkpoint_*" if sharded else "checkpoint_*.zip")
        self._written: list = sorted(
            (p for p in glob.glob(os.path.join(directory, pattern))
             if sharded == os.path.isdir(p)),
            key=os.path.getmtime)

    def _save_sharded(self, model, tag: str) -> str:
        import os
        import shutil
        from deeplearning4j_tpu.utils.sharded_checkpoint import save_sharded
        path = os.path.join(self.directory, f"checkpoint_{tag}")
        if os.path.isdir(path):  # re-saved tag: orbax requires a fresh dir
            shutil.rmtree(path)
        save_sharded(path, model)
        tmp = os.path.join(self.directory, "LATEST.tmp")
        with open(tmp, "w") as f:
            f.write(os.path.basename(path))
        os.replace(tmp, os.path.join(self.directory, "LATEST"))
        if path in self._written:
            self._written.remove(path)
        self._written.append(path)
        while len(self._written) > self.keep_last:
            shutil.rmtree(self._written.pop(0), ignore_errors=True)
        return path

    def _save(self, model, tag: str) -> str:
        import os
        import shutil
        if self.sharded:
            return self._save_sharded(model, tag)
        from deeplearning4j_tpu.utils.model_serializer import write_model
        path = os.path.join(self.directory, f"checkpoint_{tag}.zip")
        tmp = path + ".tmp"
        # atomic: a crash mid-write must never leave a truncated zip behind
        write_model(model, tmp)
        os.replace(tmp, path)
        latest_tmp = os.path.join(self.directory, "latest.zip.tmp")
        shutil.copyfile(path, latest_tmp)  # file copy, not a 2nd serialize
        os.replace(latest_tmp, os.path.join(self.directory, "latest.zip"))
        if path in self._written:  # re-saved tag (e.g. resume after rollback)
            self._written.remove(path)
        self._written.append(path)
        while len(self._written) > self.keep_last:
            old = self._written.pop(0)
            try:
                os.remove(old)
            except OSError:
                log.debug("could not remove rotated checkpoint %s", old,
                          exc_info=True)
        return path

    def iteration_done(self, model, iteration: int) -> None:
        if self.every_n_iterations and iteration % self.every_n_iterations == 0:
            self._save(model, f"iter_{iteration}")

    def on_epoch_end(self, model) -> None:
        epoch = getattr(model, "epoch", 0)
        if self.every_n_epochs and epoch % self.every_n_epochs == 0:
            self._save(model, f"epoch_{epoch}")

    @staticmethod
    def last_checkpoint(directory: str) -> Optional[str]:
        import os
        p = os.path.join(directory, "latest.zip")
        if os.path.exists(p):
            return p
        ptr = os.path.join(directory, "LATEST")  # sharded-mode pointer file
        if os.path.exists(ptr):
            with open(ptr) as f:
                cand = os.path.join(directory, f.read().strip())
            if os.path.isdir(cand):
                return cand
        return None


class NanScoreWatcher(IterationListener):
    """Failure detection: raise (or callback) the moment the score goes
    NaN/Inf instead of training on garbage (SURVEY.md §5 — the reference's
    only divergence guard is InvalidScoreIterationTerminationCondition in
    early stopping; this makes it available to any fit loop)."""

    def __init__(self, on_invalid=None):
        self.on_invalid = on_invalid
        self.triggered = False

    def iteration_done(self, model, iteration: int) -> None:
        import math
        s = float(model.score_value)
        if math.isnan(s) or math.isinf(s):
            self.triggered = True
            if self.on_invalid is not None:
                self.on_invalid(model, iteration, s)
            else:
                raise FloatingPointError(
                    f"invalid score {s} at iteration {iteration}")
