from deeplearning4j_tpu.optimize.listeners import (
    IterationListener, ScoreIterationListener, PerformanceListener,
    CollectScoresIterationListener, TimeIterationListener,
)
