#!/usr/bin/env bash
# Run the test suite on a virtual 8-device CPU mesh (reference runtests.sh analog).
#
# PALLAS_AXON_POOL_IPS is cleared so the axon TPU-relay sitecustomize doesn't dial
# the tunnel for CPU-only test runs (it can hang interpreter startup); tests never
# need the real chip. bench.py, by contrast, runs under the default env to use it.
#
#   ./runtests.sh [pytest args]   # the suite
#   ./runtests.sh lint [args]     # graftlint over the package (see docs/GUIDE.md)
#   ./runtests.sh health [args]   # failure-diagnostics suite: flight recorder,
#                                 # health monitor, watchdog, overhead budget
#   ./runtests.sh rnn [args]      # recurrent engine: fused/pallas-vs-scan
#                                 # equivalence, dispatch gate, layer tests
#   ./runtests.sh profile [args]  # trace-attribution engine: XPlane parser
#                                 # golden tests, TraceSession lock, triggers,
#                                 # e2e CPU capture + bench attribution row
#   ./runtests.sh serve [args]    # serving engine: non-donated predict,
#                                 # bucketed micro-batching semantics, 429
#                                 # backpressure, hot swap, streaming, HTTP
#                                 # front-end, bench serve-axis contract
#   ./runtests.sh ps [args]       # async parameter-server engine: staleness
#                                 # math, bf16 wire codec, transport parity,
#                                 # 2-process TCP loss parity, loopback
#                                 # broker reconnect, bench ps-axis contract
#   ./runtests.sh decode [args]   # continuous-batching decode engine:
#                                 # continuous-vs-static bitwise equality,
#                                 # mid-decode admission/eviction, int8
#                                 # drift bounds, compile-per-bucket, the
#                                 # streaming churn regression, /v1/generate
#   ./runtests.sh paged [args]    # paged KV memory plane + speculative
#                                 # decoding: paged-vs-dense bitwise at
#                                 # every bucket, CoW forks, refcount
#                                 # churn, spec-vs-greedy bitwise, pool
#                                 # 429s, the 2x-sessions ratio, bench
#                                 # decode-kv-axis contract
#   ./runtests.sh serve-shard [args]  # sharded multi-replica serving:
#                                 # dp_tp bitwise-vs-single-device, rolling
#                                 # hot swap zero-loss, least-queue router,
#                                 # multi-input graphs, per-replica metrics,
#                                 # bench replica-axis contract
#   ./runtests.sh elastic [args]  # elastic preemption-tolerant training:
#                                 # membership lease math, zombie epoch
#                                 # fencing, half-open-socket retry bounds,
#                                 # broker shard handoff, the slow chaos
#                                 # SIGKILL+respawn loss-parity run, bench
#                                 # elastic-axis contract
#   ./runtests.sh dataplane [args]  # zero-copy host data plane: wire codec
#                                 # fuzz, shm seqlock rings, SIGKILL orphan
#                                 # reaper, shm/tcp transport + fit parity,
#                                 # native ingest decode parity, bench
#                                 # dataplane-axis contract
#   ./runtests.sh compile [args]  # warm-start compile plane: cache-hit
#                                 # bitwise identity (train/predict/decode),
#                                 # corruption quarantine, cross-process
#                                 # reuse, warmup-before-swap ordering,
#                                 # kill switch, bench compile-cache-axis
#                                 # contract
#   ./runtests.sh autoscale [args]  # SLO-driven autoscaling fleet:
#                                 # add/remove replica atomicity, scale-in
#                                 # drain zero-loss, zombie lease fencing,
#                                 # hysteresis (≤1 event per cooldown),
#                                 # priority shedding order, warm scale-up
#                                 # no-fresh-compile pin, bench axis contract
#   ./runtests.sh lock [args]     # concurrency plane: the four lock rules
#                                 # over the package + their fixture suite,
#                                 # then the threaded serve/autoscale/replica
#                                 # suites under the runtime lock-order
#                                 # witness (DL4J_LOCK_WITNESS=1) asserting
#                                 # the executed acquisition graph acyclic
#   ./runtests.sh trace [args]    # request tracing + SLO engine: traceparent
#                                 # propagation through HTTP/batcher/decode/
#                                 # replica, tail sampling (429 always kept),
#                                 # burn-rate math + alert actions, cardinality
#                                 # guard, orphan-span lint rule, the <=2%
#                                 # tracing overhead budget, bench axis contract
#   ./runtests.sh fleet [args]    # fleet observability federation: merge
#                                 # algebra exactness, zombie-gauge fencing,
#                                 # restart-epoch monotonicity, cross-process
#                                 # trace stitching, /fleet/* routes, fleet
#                                 # bundle timeline, fleet-truth lint rule,
#                                 # the <=2% federation overhead budget
set -e
cd "$(dirname "$0")"

if [ "${1-}" = "lint" ]; then
  shift
  PALLAS_AXON_POOL_IPS= \
  JAX_PLATFORMS=cpu \
  exec python -m deeplearning4j_tpu.lint "$@"
fi

if [ "${1-}" = "rnn" ]; then
  shift
  PALLAS_AXON_POOL_IPS= \
  JAX_PLATFORMS=cpu \
  XLA_FLAGS="--xla_force_host_platform_device_count=8" \
  exec python -m pytest tests/test_lstm_fast.py tests/test_layers.py -q "$@"
fi

if [ "${1-}" = "profile" ]; then
  shift
  # includes the slow end-to-end bench --xplane-attribution subprocess row
  PALLAS_AXON_POOL_IPS= \
  JAX_PLATFORMS=cpu \
  XLA_FLAGS="--xla_force_host_platform_device_count=8" \
  exec python -m pytest tests/test_profiler.py \
    tests/test_bench_contract.py::test_xplane_attribution_contract -q "$@"
fi

if [ "${1-}" = "serve" ]; then
  shift
  PALLAS_AXON_POOL_IPS= \
  JAX_PLATFORMS=cpu \
  XLA_FLAGS="--xla_force_host_platform_device_count=8" \
  exec python -m pytest tests/test_serving.py tests/test_serving_http.py \
    tests/test_bench_contract.py::test_config_key_serve_axes \
    tests/test_bench_contract.py::test_grid_row_serve -q "$@"
fi

if [ "${1-}" = "ps" ]; then
  shift
  # includes the slow 2-process TCP loss-parity run
  PALLAS_AXON_POOL_IPS= \
  JAX_PLATFORMS=cpu \
  XLA_FLAGS="--xla_force_host_platform_device_count=8" \
  exec python -m pytest tests/test_param_server.py \
    tests/test_streaming_broker.py \
    tests/test_bench_contract.py::test_config_key_ps_axes \
    tests/test_bench_contract.py::test_grid_row_ps_async -q "$@"
fi

if [ "${1-}" = "decode" ]; then
  shift
  PALLAS_AXON_POOL_IPS= \
  JAX_PLATFORMS=cpu \
  XLA_FLAGS="--xla_force_host_platform_device_count=8" \
  exec python -m pytest tests/test_decode.py \
    tests/test_bench_contract.py::test_config_key_serve_decode_axes -q "$@"
fi

if [ "${1-}" = "paged" ]; then
  shift
  PALLAS_AXON_POOL_IPS= \
  JAX_PLATFORMS=cpu \
  XLA_FLAGS="--xla_force_host_platform_device_count=8" \
  exec python -m pytest tests/test_paged_decode.py \
    tests/test_decode.py \
    tests/test_bench_contract.py::test_config_key_decode_kv_axes -q "$@"
fi

if [ "${1-}" = "serve-shard" ]; then
  shift
  PALLAS_AXON_POOL_IPS= \
  JAX_PLATFORMS=cpu \
  XLA_FLAGS="--xla_force_host_platform_device_count=8" \
  exec python -m pytest tests/test_serving_replica.py \
    tests/test_bench_contract.py::test_config_key_serve_replica_axes -q "$@"
fi

if [ "${1-}" = "elastic" ]; then
  shift
  # includes the slow chaos SIGKILL+respawn loss-parity run
  PALLAS_AXON_POOL_IPS= \
  JAX_PLATFORMS=cpu \
  XLA_FLAGS="--xla_force_host_platform_device_count=8" \
  exec python -m pytest tests/test_elastic.py \
    tests/test_bench_contract.py::test_config_key_elastic_axes \
    tests/test_bench_contract.py::test_grid_row_elastic -q "$@"
fi

if [ "${1-}" = "dataplane" ]; then
  shift
  # includes the slow shm/tcp fit-parity run and the SIGKILL orphan-reaper
  # chaos test; test_param_server/test_streaming_broker ride along because
  # the shm transport and the native broker decode share their surfaces
  PALLAS_AXON_POOL_IPS= \
  JAX_PLATFORMS=cpu \
  XLA_FLAGS="--xla_force_host_platform_device_count=8" \
  exec python -m pytest tests/test_dataplane.py \
    tests/test_param_server.py \
    tests/test_streaming_broker.py \
    tests/test_bench_contract.py::test_config_key_dataplane_axes \
    tests/test_bench_contract.py::test_grid_row_ingest -q "$@"
fi

if [ "${1-}" = "compile" ]; then
  shift
  PALLAS_AXON_POOL_IPS= \
  JAX_PLATFORMS=cpu \
  XLA_FLAGS="--xla_force_host_platform_device_count=8" \
  exec python -m pytest tests/test_compile_cache.py \
    tests/test_bench_contract.py::test_config_key_compile_cache_axes -q "$@"
fi

if [ "${1-}" = "autoscale" ]; then
  shift
  PALLAS_AXON_POOL_IPS= \
  JAX_PLATFORMS=cpu \
  XLA_FLAGS="--xla_force_host_platform_device_count=8" \
  exec python -m pytest tests/test_autoscale.py \
    tests/test_bench_contract.py::test_config_key_serve_autoscale_axis -q "$@"
fi

if [ "${1-}" = "trace" ]; then
  shift
  PALLAS_AXON_POOL_IPS= \
  JAX_PLATFORMS=cpu \
  XLA_FLAGS="--xla_force_host_platform_device_count=8" \
  exec python -m pytest tests/test_tracing.py \
    tests/test_bench_contract.py::test_config_key_serve_tracing_axis -q "$@"
fi

if [ "${1-}" = "lock" ]; then
  shift
  # phase 1: static — the four concurrency rules over the real tree must
  # be clean, and their fixture/witness unit suite must pass
  PALLAS_AXON_POOL_IPS= \
  JAX_PLATFORMS=cpu \
  python -m deeplearning4j_tpu.lint deeplearning4j_tpu \
    --rules lockguard,lock-order,blocking-under-lock,thread-lifecycle
  PALLAS_AXON_POOL_IPS= \
  JAX_PLATFORMS=cpu \
  XLA_FLAGS="--xla_force_host_platform_device_count=8" \
  python -m pytest tests/test_lint_concurrency.py -q "$@"
  # phase 2: dynamic — the threaded suites under the witness; the
  # session-teardown fixture in conftest.py asserts the lock graph the
  # run actually executed is acyclic
  PALLAS_AXON_POOL_IPS= \
  JAX_PLATFORMS=cpu \
  XLA_FLAGS="--xla_force_host_platform_device_count=8" \
  DL4J_LOCK_WITNESS=1 \
  exec python -m pytest tests/test_serving.py tests/test_serving_http.py \
    tests/test_serving_replica.py tests/test_autoscale.py -q "$@"
fi

if [ "${1-}" = "health" ]; then
  shift
  PALLAS_AXON_POOL_IPS= \
  JAX_PLATFORMS=cpu \
  XLA_FLAGS="--xla_force_host_platform_device_count=8" \
  exec python -m pytest tests/test_flight_recorder.py \
    tests/test_bench_contract.py::test_telemetry_overhead_budget -q "$@"
fi

if [ "${1-}" = "fleet" ]; then
  shift
  PALLAS_AXON_POOL_IPS= \
  JAX_PLATFORMS=cpu \
  XLA_FLAGS="--xla_force_host_platform_device_count=8" \
  exec python -m pytest tests/test_federation.py \
    tests/test_bench_contract.py::test_federation_overhead_budget -q "$@"
fi

PALLAS_AXON_POOL_IPS= \
JAX_PLATFORMS=cpu \
XLA_FLAGS="--xla_force_host_platform_device_count=8" \
python -m pytest tests/ -q "$@"
